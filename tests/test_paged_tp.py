"""Tensor-parallel paged serving (ISSUE 12): the paged decode hot path on
a mesh — sharded arena, shard_mapped paged attention, lifted eligibility
gate.

Coverage:
- gate text (tier-1, in-process, no mesh): the eligibility error no
  longer blames the mesh — a TP engine pages; what's left is the
  windowed interleave, explicit ring pins, adapters and speculation;
- compile stability (tier-1, clean subprocess): the shard_mapped paged
  step compiles ONCE across decode steps with varying live-slot counts,
  page-table contents and lengths, and the store's pow2 gather/write
  bucketing holds under the mesh (PR 8's contract must survive
  shard_map);
- the layout x path matrix's mesh dimension (slow, clean subprocess per
  scenario): plain and int8-KV engines on a tp=2 CPU mesh decode
  token-identically to the CONTIGUOUS mesh loop (greedy + seeded
  sampling), adopt handed-off pages as prefix hits (wire and device
  paths), and leak zero pages on both arenas; the replicate-arena
  escape hatch gets the same identity + leak checks.

ISOLATION NOTE (PR 6 device-subset-mesh precedent): every jax scenario
runs in a fresh subprocess (`python tests/test_paged_tp.py <scenario>`).
Executables compiled for meshes over device subsets trigger heap
corruption in this image's XLA:CPU when they share a process with the
suite's accumulated compiler state; standalone they pass 100% of runs.
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys

import pytest

SEED = 20260804
_REPO = pathlib.Path(__file__).resolve().parent.parent


def _ctx(msg: str) -> str:
    return f"{msg} (seed={SEED})"


def _run_scenario(name: str, marker: str, timeout: int = 540):
    """One scenario in a clean interpreter (see the ISOLATION NOTE)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = str(_REPO) + os.pathsep + env.get("PYTHONPATH", "")
    # the persistent compile cache composes badly with device-subset
    # meshes (the PR 6 pinned repro) — keep the child in-memory only
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run([sys.executable, os.path.abspath(__file__), name],
                          env=env, capture_output=True, text=True,
                          timeout=timeout, cwd=str(_REPO))
    assert proc.returncode == 0, _ctx(
        f"tp scenario {name} failed (rc={proc.returncode}):\n"
        f"stdout tail: {proc.stdout[-1500:]}\n"
        f"stderr tail: {proc.stderr[-1500:]}")
    assert marker in proc.stdout, _ctx(
        f"{marker} missing:\n{proc.stdout[-1500:]}")


def test_gate_error_does_not_blame_the_mesh():
    """ISSUE 12 gate-text regression: mesh engines page now, so the
    paged_decode=True error must name only the TRUE exclusions —
    windowed interleave, ring_cache=True pins, structural constraints —
    and never 'no mesh' / single-host. Since ISSUE 14 speculation and
    adapters ride the paged loop too, so the error must not name them
    either (and a speculative config no longer raises at all — trigger
    the gate via prefix_cache_enabled=False instead)."""
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)
    cfg = tiny_llama(vocab_size=64, embed_dim=32, n_layers=1, n_heads=2,
                     n_kv_heads=2, mlp_dim=64, max_seq_len=128,
                     dtype=jnp.float32, param_dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError) as ei:
        ServingEngine(cfg, params, ServingConfig(
            slots=2, cache_len=128, kv_page_tokens=8,
            paged_decode=True, prefix_cache_enabled=False))
    msg = str(ei.value)
    assert "interleave" in msg and "ring_cache=True" in msg
    assert "adapters" not in msg and "speculation" not in msg
    assert "no mesh" not in msg and "Single host" not in msg \
        and "single host" not in msg


def test_bad_kv_arena_sharding_is_a_loud_error():
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)
    cfg = tiny_llama(vocab_size=64, embed_dim=32, n_layers=1, n_heads=2,
                     n_kv_heads=2, mlp_dim=64, max_seq_len=128,
                     dtype=jnp.float32, param_dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="kv_arena_sharding"):
        ServingEngine(cfg, params, ServingConfig(
            slots=1, cache_len=128, kv_page_tokens=8,
            kv_arena_sharding="sideways"))


def test_shard_mapped_paged_step_compiles_once_in_clean_process():
    """Tier-1 compile-stability pin: the tp=2 paged step stays at ONE
    jit cache entry across steps whose live-slot mix, page-table
    contents and lengths all vary, and the mesh store's pow2
    gather/write bucketing compiles O(log) variants (the PR 8 contract
    survives shard_map)."""
    _run_scenario("compile", "COMPILE_ONCE_OK")


@pytest.mark.slow
def test_tp2_plain_matrix_in_clean_process():
    """Mesh row of the layout x path matrix, plain K/V: token identity
    vs the contiguous mesh loop, wire + device adoption hits, zero
    leaks, sharded-arena evidence."""
    _run_scenario("plain", "PLAIN_TP2_OK", timeout=720)


@pytest.mark.slow
def test_tp2_int8_kv_matrix_in_clean_process():
    """Mesh row, int8-KV: dequant-in-kernel paged decode under shard_map
    (scales shard alongside), adoption hit, zero leaks."""
    _run_scenario("int8", "INT8_TP2_OK", timeout=720)


@pytest.mark.slow
def test_tp2_replicate_arena_in_clean_process():
    """kv_arena_sharding="replicate": the escape hatch keeps paged
    decode token-identical with a fully replicated arena (and still
    compiles once — replicated specs, no per-step arena reshard)."""
    _run_scenario("replicate", "REPLICATE_TP2_OK", timeout=720)


# --------------------------------------------------------------------------
# jax scenarios — executed by the subprocess tests above
# --------------------------------------------------------------------------

def _tiny_cfg():
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.models import tiny_llama
    return tiny_llama(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, mlp_dim=128, max_seq_len=256,
                      dtype=jnp.float32, param_dtype=jnp.float32)


def _mesh2():
    import jax
    from k8s_runpod_kubelet_tpu.parallel import MeshConfig, make_mesh
    return make_mesh(MeshConfig(data=1, tensor=2), jax.devices()[:2])


_SC = dict(slots=2, max_prefill_len=8, cache_len=64, max_new_tokens=12,
           kv_page_tokens=4)


def _scenario_compile():
    """Varying live slots / page tables / lengths -> ONE paged-step
    executable; store gather/write stay pow2-bucketed on the mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from k8s_runpod_kubelet_tpu.models import init_params
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    cfg, mesh = _tiny_cfg(), _mesh2()
    params = init_params(cfg, jax.random.PRNGKey(0), mesh)
    e = ServingEngine(cfg, params, ServingConfig(**_SC), mesh=mesh).start()
    try:
        assert e._paged_loop and e.mesh is not None, _ctx(
            "paged loop must be ON for a tp=2 engine")
        assert e._paged_tp == 2, _ctx(f"paged_tp={e._paged_tp}")
        rng = np.random.default_rng(SEED)
        # live-slot counts vary naturally: 1 then 2 concurrent, lengths
        # and table contents differ per request
        e.submit([5, 9, 2], max_new_tokens=6).result(timeout=300)
        futs = [e.submit([int(rng.integers(1, 120)) for _ in range(n)],
                         max_new_tokens=6) for n in (3, 9)]
        for f in futs:
            f.result(timeout=300)
        assert e._paged_step._cache_size() == 1, _ctx(
            f"paged step compiled {e._paged_step._cache_size()} times — "
            "the shard_mapped step must compile ONCE across varying "
            "live-slot counts and page tables")
        # pow2 bucketing on the mesh store: distinct run lengths share
        # log-many write/gather executables, never one per length
        st = e._kv_store
        assert st._write._cache_size() <= 4, _ctx(
            f"write jit compiled {st._write._cache_size()} variants")
        assert st._gather._cache_size() <= 4, _ctx(
            f"gather jit compiled {st._gather._cache_size()} variants")
        e.drain()
        s = e.prefix_cache_stats()
        assert s["pages_free"] + s["nodes"] == s["pages_total"], _ctx(str(s))
    finally:
        e.stop()
    print("COMPILE_ONCE_OK", flush=True)


def _matrix(extra: dict, marker: str, check_device_path: bool):
    """Shared body for the mesh matrix scenarios: identity vs the
    contiguous mesh loop, adoption hit, zero leaks."""
    import jax

    from k8s_runpod_kubelet_tpu.models import init_params
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    cfg, mesh = _tiny_cfg(), _mesh2()
    params = init_params(cfg, jax.random.PRNGKey(0), mesh)
    paged = ServingEngine(cfg, params, ServingConfig(**_SC, **extra),
                          mesh=mesh).start()
    contig = ServingEngine(cfg, params,
                           ServingConfig(**_SC, **extra, paged_decode=False),
                           mesh=mesh).start()
    engines = [paged]
    try:
        assert paged._paged_loop and not contig._paged_loop, _ctx(marker)
        if extra.get("kv_arena_sharding") == "replicate":
            assert paged._kv_store.arena["k"].sharding.is_fully_replicated, \
                _ctx(str(paged._kv_store.arena["k"].sharding))
        else:
            # the arena genuinely spans the mesh (kv-heads sharded)
            some = next(iter(paged._kv_store.arena.values()))
            assert len(some.sharding.device_set) == 2, _ctx(
                str(some.sharding))
        prompts = [[5, 9, 2], [7, 3, 1, 4, 1, 5, 9, 2, 6], [11, 13]]
        for i, p in enumerate(prompts):
            kw = dict(max_new_tokens=12)
            if i % 3 == 2:  # seeded sampling rides the same identity bar
                kw.update(temperature=0.8, seed=1000 + i)
            a = paged.submit(p, **kw).result(timeout=300)
            b = contig.submit(p, **kw).result(timeout=300)
            assert a["tokens"] == b["tokens"], _ctx(
                f"{marker} prompt {i}: paged != contiguous mesh loop")
        assert paged._paged_step._cache_size() == 1, _ctx(
            f"{marker}: paged step compiled "
            f"{paged._paged_step._cache_size()} times")

        # adoption-hit: a second mesh engine adopts this engine's pages
        # (wire codec), then serves the prompt as a prefix hit,
        # token-identical to the contiguous loop
        shared = [((i * 31) % 120) + 1 for i in range(16)]
        paged.submit(shared + [1], max_new_tokens=2).result(timeout=300)
        dec = ServingEngine(cfg, params, ServingConfig(**_SC, **extra),
                            mesh=mesh).start()
        engines.append(dec)
        out = paged.export_handoff(shared)
        res = dec.adopt_handoff(out["blob"])
        assert res["pages"] == len(shared) // _SC["kv_page_tokens"], _ctx(
            str(res))
        a = dec.submit(shared + [9, 9], max_new_tokens=6).result(timeout=300)
        b = contig.submit(shared + [9, 9], max_new_tokens=6).result(
            timeout=300)
        assert a["tokens"] == b["tokens"], _ctx(f"{marker}: adopted KV "
                                                "decoded differently")
        assert dec.metrics.get_counter(
            "tpu_serving_prefix_cache_hits") >= 1, _ctx(
            f"{marker}: adoption never hit")

        if check_device_path:
            # device-path adoption between two mesh engines: the export
            # comes back host-replicated, adoption re-shards on insert
            expd = paged.export_handoff_device(shared)
            assert all(a_.sharding.is_fully_replicated
                       for a_ in expd["sections"].values()), _ctx(
                "device export sections must be host-replicated")
            dec2 = ServingEngine(cfg, params, ServingConfig(**_SC, **extra),
                                 mesh=mesh).start()
            engines.append(dec2)
            dec2.adopt_handoff_device(expd["tokens"], expd["sections"],
                                      model=cfg.name)
            a = dec2.submit(shared + [7], max_new_tokens=6).result(
                timeout=300)
            b = contig.submit(shared + [7], max_new_tokens=6).result(
                timeout=300)
            assert a["tokens"] == b["tokens"], _ctx(
                f"{marker}: device-adopted KV decoded differently")

        for e in engines:
            e.drain()
            assert e.drained, _ctx(marker)
            s = e.prefix_cache_stats()
            assert s["pages_free"] + s["nodes"] == s["pages_total"], _ctx(
                f"{marker}: leaked pages ({s})")
    finally:
        for e in engines + [contig]:
            e.stop()
    print(marker, flush=True)


def _scenario_plain():
    _matrix({}, "PLAIN_TP2_OK", check_device_path=True)


def _scenario_int8():
    _matrix({"quantize_kv_int8": True}, "INT8_TP2_OK",
            check_device_path=False)


def _scenario_replicate():
    _matrix({"kv_arena_sharding": "replicate"}, "REPLICATE_TP2_OK",
            check_device_path=False)


def _main(argv: list) -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    scenarios = {"compile": _scenario_compile, "plain": _scenario_plain,
                 "int8": _scenario_int8, "replicate": _scenario_replicate}
    scenarios[argv[0]]()
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))
