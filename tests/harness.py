"""Shared test harness: a fully-wired hermetic kubelet.

FakeKubeClient + FakeTpuServer + TpuClient + InMemoryWorkerTransport + a
controllable clock — the hermetic full-loop setup the reference never had
(SURVEY.md §4 lesson).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from k8s_runpod_kubelet_tpu.cloud import HttpTransport, TpuClient
from k8s_runpod_kubelet_tpu.cloud.fake_server import FakeTpuServer
from k8s_runpod_kubelet_tpu.config import Config
from k8s_runpod_kubelet_tpu.gang import GangExecutor, InMemoryWorkerTransport
from k8s_runpod_kubelet_tpu.kube import FakeKubeClient
from k8s_runpod_kubelet_tpu.provider import Provider


class FakeClock:
    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float):
        self.t += dt


@dataclasses.dataclass
class Harness:
    server: FakeTpuServer
    kube: FakeKubeClient
    tpu: TpuClient
    provider: Provider
    clock: FakeClock
    transport: InMemoryWorkerTransport
    cfg: Config
    metrics: object = None   # chaos harness: the shared Metrics registry
    breaker: object = None   # chaos harness: the transport's CircuitBreaker

    def close(self):
        self.server.stop()

    @property
    def fake(self):
        return self.server.service


def make_harness(provision_delay_s: float = 0.0,
                 workload_auto_finish_s: Optional[float] = None,
                 cfg: Optional[Config] = None) -> Harness:
    server = FakeTpuServer(provision_delay_s=provision_delay_s,
                           workload_auto_finish_s=workload_auto_finish_s).start()
    kube = FakeKubeClient()
    tpu = TpuClient(HttpTransport(server.base_url, token="t", sleep=lambda s: None),
                    project="test-proj", zone="us-central2-b")
    clock = FakeClock()
    cfg = cfg or Config(node_name="virtual-tpu", zone="us-central2-b")
    transport = InMemoryWorkerTransport()
    provider = Provider(cfg, kube, tpu, gang_executor=GangExecutor(transport),
                        clock=clock)
    return Harness(server=server, kube=kube, tpu=tpu, provider=provider,
                   clock=clock, transport=transport, cfg=cfg)


def make_chaos_harness(seed: int = 0, provision_delay_s: float = 20.0,
                       cfg: Optional[Config] = None,
                       breaker_threshold: int = 5,
                       breaker_reset_s: float = 60.0) -> Harness:
    """Chaos-soak harness (ISSUE 3): ONE FakeClock shared by the provider,
    the HTTP transport (whose retry sleeps ADVANCE it — simulated time pays
    for backoff, wall time doesn't), the circuit breaker, and the fake
    server's slice state machine. Zero real sleeps; attach a FaultPlan via
    ``h.fake.fault_plan``."""
    import random as _random

    from k8s_runpod_kubelet_tpu.cloud import CircuitBreaker
    from k8s_runpod_kubelet_tpu.metrics import Metrics

    clock = FakeClock()
    server = FakeTpuServer(provision_delay_s=provision_delay_s,
                           clock=clock).start()
    kube = FakeKubeClient()
    metrics = Metrics()
    breaker = CircuitBreaker(failure_threshold=breaker_threshold,
                             reset_timeout_s=breaker_reset_s,
                             clock=clock, metrics=metrics)
    http = HttpTransport(server.base_url, token="t", sleep=clock.advance,
                         clock=clock, rng=_random.Random(seed),
                         breaker=breaker, metrics=metrics)
    tpu = TpuClient(http, project="test-proj", zone="us-central2-b")
    cfg = cfg or Config(node_name="virtual-tpu", zone="us-central2-b",
                        # a chaos plan may preempt the same pod many times
                        # and black the API out for minutes; the soak proves
                        # CONVERGENCE, not the give-up ladders
                        preemption_requeue_limit=100,
                        max_pending_s=7200.0,
                        breaker_failure_threshold=breaker_threshold,
                        breaker_reset_s=breaker_reset_s)
    transport = InMemoryWorkerTransport()
    provider = Provider(cfg, kube, tpu, gang_executor=GangExecutor(transport),
                        metrics=metrics, clock=clock)
    return Harness(server=server, kube=kube, tpu=tpu, provider=provider,
                   clock=clock, transport=transport, cfg=cfg,
                   metrics=metrics, breaker=breaker)


def make_ssh_harness(provision_delay_s: float = 0.0,
                     cfg: Optional[Config] = None) -> Harness:
    """Real-cloud-path harness: the fake server exposes ONLY the plain Cloud
    TPU v2 surface (:detailed/:workload 404), and workload launch/status flow
    through the SSH workload backend onto a docker-lite FakeWorkerHost."""
    from k8s_runpod_kubelet_tpu.cloud import SshWorkloadBackend
    from k8s_runpod_kubelet_tpu.gang import FakeWorkerHost

    server = FakeTpuServer(provision_delay_s=provision_delay_s).start()
    server.service.extensions_enabled = False
    kube = FakeKubeClient()
    clock = FakeClock()
    cfg = cfg or Config(node_name="virtual-tpu", zone="us-central2-b")
    transport = FakeWorkerHost()
    gang = GangExecutor(transport)
    tpu = TpuClient(HttpTransport(server.base_url, token="t", sleep=lambda s: None),
                    project="test-proj", zone="us-central2-b",
                    workload_backend=SshWorkloadBackend(gang))
    provider = Provider(cfg, kube, tpu, gang_executor=gang, clock=clock)
    return Harness(server=server, kube=kube, tpu=tpu, provider=provider,
                   clock=clock, transport=transport, cfg=cfg)


def make_pod(name="train", ns="default", node="virtual-tpu", chips=16,
             annotations: Optional[dict] = None, ports: Optional[list] = None,
             containers: Optional[list] = None, uid: Optional[str] = None):
    if containers is None:
        c = {"name": "main", "image": "gcr.io/proj/maxtext:latest"}
        if chips:
            c["resources"] = {"limits": {"google.com/tpu": str(chips)}}
        if ports:
            c["ports"] = [{"containerPort": p, "protocol": "TCP"} for p in ports]
        containers = [c]
    meta = {"name": name, "namespace": ns}
    if uid:
        meta["uid"] = uid
    if annotations:
        meta["annotations"] = dict(annotations)
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {"nodeName": node, "containers": containers}}
