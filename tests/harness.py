"""Shared test harness: a fully-wired hermetic kubelet.

FakeKubeClient + FakeTpuServer + TpuClient + InMemoryWorkerTransport + a
controllable clock — the hermetic full-loop setup the reference never had
(SURVEY.md §4 lesson).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from k8s_runpod_kubelet_tpu.cloud import HttpTransport, TpuClient
from k8s_runpod_kubelet_tpu.cloud.fake_server import FakeTpuServer
from k8s_runpod_kubelet_tpu.config import Config
from k8s_runpod_kubelet_tpu.gang import GangExecutor, InMemoryWorkerTransport
from k8s_runpod_kubelet_tpu.kube import FakeKubeClient
from k8s_runpod_kubelet_tpu.provider import Provider


class FakeClock:
    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float):
        self.t += dt


@dataclasses.dataclass
class Harness:
    server: FakeTpuServer
    kube: FakeKubeClient
    tpu: TpuClient
    provider: Provider
    clock: FakeClock
    transport: InMemoryWorkerTransport
    cfg: Config
    metrics: object = None   # chaos harness: the shared Metrics registry
    breaker: object = None   # chaos harness: the transport's CircuitBreaker

    def close(self):
        self.server.stop()

    @property
    def fake(self):
        return self.server.service


def make_harness(provision_delay_s: float = 0.0,
                 workload_auto_finish_s: Optional[float] = None,
                 cfg: Optional[Config] = None) -> Harness:
    server = FakeTpuServer(provision_delay_s=provision_delay_s,
                           workload_auto_finish_s=workload_auto_finish_s).start()
    kube = FakeKubeClient()
    tpu = TpuClient(HttpTransport(server.base_url, token="t", sleep=lambda s: None),
                    project="test-proj", zone="us-central2-b")
    clock = FakeClock()
    cfg = cfg or Config(node_name="virtual-tpu", zone="us-central2-b")
    transport = InMemoryWorkerTransport()
    provider = Provider(cfg, kube, tpu, gang_executor=GangExecutor(transport),
                        clock=clock)
    return Harness(server=server, kube=kube, tpu=tpu, provider=provider,
                   clock=clock, transport=transport, cfg=cfg)


def make_chaos_harness(seed: int = 0, provision_delay_s: float = 20.0,
                       cfg: Optional[Config] = None,
                       breaker_threshold: int = 5,
                       breaker_reset_s: float = 60.0) -> Harness:
    """Chaos-soak harness (ISSUE 3): ONE FakeClock shared by the provider,
    the HTTP transport (whose retry sleeps ADVANCE it — simulated time pays
    for backoff, wall time doesn't), the circuit breaker, and the fake
    server's slice state machine. Zero real sleeps; attach a FaultPlan via
    ``h.fake.fault_plan``."""
    import random as _random

    from k8s_runpod_kubelet_tpu.cloud import CircuitBreaker
    from k8s_runpod_kubelet_tpu.metrics import Metrics

    clock = FakeClock()
    server = FakeTpuServer(provision_delay_s=provision_delay_s,
                           clock=clock).start()
    kube = FakeKubeClient()
    metrics = Metrics()
    breaker = CircuitBreaker(failure_threshold=breaker_threshold,
                             reset_timeout_s=breaker_reset_s,
                             clock=clock, metrics=metrics)
    http = HttpTransport(server.base_url, token="t", sleep=clock.advance,
                         clock=clock, rng=_random.Random(seed),
                         breaker=breaker, metrics=metrics)
    tpu = TpuClient(http, project="test-proj", zone="us-central2-b")
    cfg = cfg or Config(node_name="virtual-tpu", zone="us-central2-b",
                        # a chaos plan may preempt the same pod many times
                        # and black the API out for minutes; the soak proves
                        # CONVERGENCE, not the give-up ladders
                        preemption_requeue_limit=100,
                        max_pending_s=7200.0,
                        breaker_failure_threshold=breaker_threshold,
                        breaker_reset_s=breaker_reset_s)
    transport = InMemoryWorkerTransport()
    provider = Provider(cfg, kube, tpu, gang_executor=GangExecutor(transport),
                        metrics=metrics, clock=clock)
    return Harness(server=server, kube=kube, tpu=tpu, provider=provider,
                   clock=clock, transport=transport, cfg=cfg,
                   metrics=metrics, breaker=breaker)


def make_ssh_harness(provision_delay_s: float = 0.0,
                     cfg: Optional[Config] = None) -> Harness:
    """Real-cloud-path harness: the fake server exposes ONLY the plain Cloud
    TPU v2 surface (:detailed/:workload 404), and workload launch/status flow
    through the SSH workload backend onto a docker-lite FakeWorkerHost."""
    from k8s_runpod_kubelet_tpu.cloud import SshWorkloadBackend
    from k8s_runpod_kubelet_tpu.gang import FakeWorkerHost

    server = FakeTpuServer(provision_delay_s=provision_delay_s).start()
    server.service.extensions_enabled = False
    kube = FakeKubeClient()
    clock = FakeClock()
    cfg = cfg or Config(node_name="virtual-tpu", zone="us-central2-b")
    transport = FakeWorkerHost()
    gang = GangExecutor(transport)
    tpu = TpuClient(HttpTransport(server.base_url, token="t", sleep=lambda s: None),
                    project="test-proj", zone="us-central2-b",
                    workload_backend=SshWorkloadBackend(gang))
    provider = Provider(cfg, kube, tpu, gang_executor=gang, clock=clock)
    return Harness(server=server, kube=kube, tpu=tpu, provider=provider,
                   clock=clock, transport=transport, cfg=cfg)


class FakeReplica:
    """In-process fake serving replica: the serve_main surface the fleet
    router touches (/generate, /v1/*, /drain, /readyz, /healthz, /prefix),
    with scriptable stats, fault switches, and a kill() that drops the
    listener so new connections are refused — no jax, fast tier.

    Streams: ``stream_chunks`` bytes are sent one chunked frame at a time;
    ``stream_gates[i]`` (threading.Event) blocks chunk i+1 until the test
    sets it (proves the router relays without buffering); ``die_after``
    aborts the socket after N chunks WITHOUT the chunked terminator (a
    replica dying mid-stream). A shared ``tracer`` records a
    serving.request span per generate call, parented on the inbound
    traceparent — the router->engine trace-join evidence."""

    def __init__(self, replica_id: str, tracer=None):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        self.replica_id = replica_id
        self.tracer = tracer
        self.lock = threading.Lock()
        self.requests: list = []       # (path, body) of every POST served
        self.generated = 0
        self.draining = False
        self.fail_next = 0             # next N generation POSTs answer 500
        self.reject_429 = False        # generation POSTs answer 429
        self.reject_400 = False        # generation POSTs answer 400
        self.stream_chunks = [b'{"token": 1}\n', b'{"token": 2}\n',
                              b'{"tokens": [1, 2], "rid": "fake"}\n']
        self.stream_gates: list = []   # Event before chunk i+1 (i = index)
        self.die_after = None          # abort socket after this many chunks
        self.stats = {"free_slots": 4, "active_slots": 0, "max_slots": 4,
                      "queue_depth": 0, "max_queue_depth": 0,
                      "kv_cache_tokens": 0, "ttft_p95_s": 0.0,
                      "draining": False}
        rep = self

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, status, payload, headers=None):
                import json as _j
                body = _j.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    return self._json(200, {"ok": True})
                if self.path == "/readyz":
                    return self._json(503 if rep.draining else 200,
                                      {"draining": rep.draining})
                if self.path == "/v1/models":
                    return self._json(200, {"object": "list", "data": [
                        {"id": "fake-model", "object": "model",
                         "owned_by": rep.replica_id}]})
                return self._json(404, {"error": "no route"})

            def _record_span(self):
                if rep.tracer is None:
                    return
                from k8s_runpod_kubelet_tpu.tracing import parse_traceparent
                inbound = parse_traceparent(self.headers.get("traceparent"))
                now = rep.tracer.clock()
                rep.tracer.record(
                    "serving.request", now, now,
                    trace_id=inbound[0] if inbound else None,
                    parent_id=inbound[1] if inbound else "",
                    attrs={"replica_id": rep.replica_id})

            def do_POST(self):
                import json as _j
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                try:
                    body = _j.loads(raw) if raw else {}
                except _j.JSONDecodeError:
                    body = {}
                with rep.lock:
                    rep.requests.append((self.path, body))
                if self.path == "/drain":
                    rep.draining = True
                    with rep.lock:
                        rep.stats["draining"] = True
                    return self._json(200, {"draining": True})
                if self.path == "/prefix":
                    return self._json(200, {"registered": True})
                # generation routes
                if rep.draining:
                    return self._json(503, {"error": {
                        "message": "engine is draining",
                        "type": "overloaded_error"}},
                        {"Retry-After": "1"})
                if rep.reject_429:
                    return self._json(429, {"error": {
                        "message": "queue at max_queue_depth",
                        "type": "overloaded_error"}},
                        {"Retry-After": "1"})
                if rep.reject_400:
                    return self._json(400, {"error": {
                        "message": "bad prompt",
                        "type": "invalid_request_error"}})
                with rep.lock:
                    if rep.fail_next > 0:
                        rep.fail_next -= 1
                        return self._json(500, {"error": "injected failure"})
                self._record_span()
                if body.get("stream"):
                    return self._stream()
                with rep.lock:
                    rep.generated += 1
                return self._json(200, {"tokens": [1, 2, 3],
                                        "rid": f"{rep.replica_id}-r",
                                        "replica_id": rep.replica_id})

            def _stream(self):
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                with rep.lock:
                    rep.generated += 1
                for i, chunk in enumerate(rep.stream_chunks):
                    if rep.die_after is not None and i >= rep.die_after:
                        # mid-stream death: abort the socket, NO terminator
                        self.close_connection = True
                        self.connection.close()
                        return
                    self.wfile.write(f"{len(chunk):x}\r\n".encode()
                                     + chunk + b"\r\n")
                    self.wfile.flush()
                    if i < len(rep.stream_gates):
                        # released only once the TEST saw chunk i relayed:
                        # a buffering router deadlocks here (and the
                        # client's socket timeout fails the test loudly)
                        rep.stream_gates[i].wait(10.0)
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def set_stats(self, **kw):
        with self.lock:
            self.stats.update(kw)

    def heartbeat_payload(self) -> dict:
        with self.lock:
            return {"replica_id": self.replica_id, "stats": dict(self.stats)}

    def kill(self):
        """Drop the listener: in-flight handlers die with their sockets,
        new connections are refused (the dead-replica failure mode)."""
        self._httpd.shutdown()
        self._httpd.server_close()

    close = kill


def make_pod(name="train", ns="default", node="virtual-tpu", chips=16,
             annotations: Optional[dict] = None, ports: Optional[list] = None,
             containers: Optional[list] = None, uid: Optional[str] = None):
    if containers is None:
        c = {"name": "main", "image": "gcr.io/proj/maxtext:latest"}
        if chips:
            c["resources"] = {"limits": {"google.com/tpu": str(chips)}}
        if ports:
            c["ports"] = [{"containerPort": p, "protocol": "TCP"} for p in ports]
        containers = [c]
    meta = {"name": name, "namespace": ns}
    if uid:
        meta["uid"] = uid
    if annotations:
        meta["annotations"] = dict(annotations)
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {"nodeName": node, "containers": containers}}
