"""Regression tests for the second review round: recovery resilience,
reconcile reentrancy, preemption-requeue naming, DELETING status."""

import threading

import pytest

from k8s_runpod_kubelet_tpu.cloud.types import QueuedResourceState as S
from k8s_runpod_kubelet_tpu.kube import objects as ko
from k8s_runpod_kubelet_tpu.provider import Provider
from k8s_runpod_kubelet_tpu.provider.annotations import Annotations as A

from harness import make_harness, make_pod


@pytest.fixture()
def h():
    h = make_harness()
    yield h
    h.close()


def bind_pod(h, pod):
    created = h.kube.create_pod(pod)
    h.provider.create_pod(created)
    return h.kube.get_pod(ko.namespace(created), ko.name(created))


class TestRecoveryResilience:
    def test_cloud_outage_at_startup_does_not_fail_pods(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        h.provider.update_all_pod_statuses()
        # restart during a cloud blackout
        h.fake.api_down = True
        p2 = Provider(h.cfg, h.kube, h.tpu, clock=h.clock)
        p2.load_running()
        got = h.kube.get_pod("default", "train")
        assert got["status"]["phase"] != "Failed"  # NOT falsely killed
        assert ko.annotations(got)[A.QUEUED_RESOURCE] == qr  # binding intact
        assert p2.instances["default/train"].qr_name == qr  # re-bound blind
        # cloud comes back: reconcile completes the picture
        h.fake.api_down = False
        p2._probe_cloud(force=True)
        p2.update_all_pod_statuses()
        assert h.kube.get_pod("default", "train")["status"]["phase"] == "Running"

    def test_one_bad_pod_does_not_abort_recovery_of_rest(self, h):
        pod_a = bind_pod(h, make_pod(name="a", chips=16))
        pod_b = bind_pod(h, make_pod(name="b", chips=16))
        # pod a's slice will 500 on detailed-status during recovery
        import k8s_runpod_kubelet_tpu.cloud.tpu_client as tc
        p2 = Provider(h.cfg, h.kube, h.tpu, clock=h.clock)
        real_detailed = p2.tpu.get_detailed_status
        qr_a = ko.annotations(pod_a)[A.QUEUED_RESOURCE]

        def flaky(name, zone=None):
            if name == qr_a:
                raise tc.TpuApiError("internal error", status=500)
            return real_detailed(name, zone=zone)

        p2.tpu.get_detailed_status = flaky
        p2.load_running()
        # b fully recovered, a recovered by annotation (not lost)
        assert p2.instances["default/b"].qr_name
        assert p2.instances["default/a"].qr_name == qr_a


class TestReconcileReentrancy:
    def test_concurrent_passes_single_gang_launch(self, h):
        bind_pod(h, make_pod(chips=16))
        barrier = threading.Barrier(2, timeout=5)
        results = []

        def run():
            try:
                barrier.wait()
            except threading.BrokenBarrierError:
                pass
            h.provider.update_all_pod_statuses()
            results.append(1)

        threads = [threading.Thread(target=run) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # exactly one workload launch despite two concurrent passes
        qr = h.provider.instances["default/train"].qr_name
        launches = [p for m, p in h.fake.request_log if p.endswith(":workload")]
        assert len(launches) == 1
        h.provider.update_all_pod_statuses()
        assert h.kube.get_pod("default", "train")["status"]["phase"] == "Running"


class TestPreemptionNaming:
    def test_requeue_uses_fresh_slice_name(self, h):
        h.cfg.preemption_requeue_limit = 1
        pod = bind_pod(h, make_pod(chips=16))
        qr1 = ko.annotations(pod)[A.QUEUED_RESOURCE]
        h.provider.update_all_pod_statuses()
        # make the dying slice LINGER (async delete, like the real API)
        h.fake.preempt(qr1)
        h.fake.stuck(qr1, S.SUSPENDED)
        h.provider.update_all_pod_statuses()  # requeue
        h.provider.process_pending_pods()     # redeploy
        pod = h.kube.get_pod("default", "train")
        qr2 = ko.annotations(pod)[A.QUEUED_RESOURCE]
        assert qr2 != qr1  # never adopts the dying predecessor
        assert qr2.endswith("-r1")
        h.provider.update_all_pod_statuses()
        assert h.kube.get_pod("default", "train")["status"]["phase"] == "Running"


class TestDeletingStatus:
    def test_deleting_never_reports_running_for_pending_pod(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        # slice deleted out-of-band while pod was never launched
        h.fake.stuck(qr, S.DELETING)
        h.provider.update_all_pod_statuses()
        status = h.kube.get_pod("default", "train")["status"]
        assert status["phase"] == "Pending"
        assert status["reason"] == "SliceDeleting"
        # north-star metric did NOT record a bogus sample
        obs = h.provider.metrics.get_observations("tpu_kubelet_schedule_to_ready_seconds")
        assert obs == []
