"""GC ladder + crash-recovery tests (kubelet.go:1188-1796 parity, hermetic)."""

import pytest

from k8s_runpod_kubelet_tpu.cloud.types import QueuedResourceState as S
from k8s_runpod_kubelet_tpu.provider.annotations import Annotations as A
from k8s_runpod_kubelet_tpu.kube import objects as ko

from harness import make_harness, make_pod


@pytest.fixture()
def h():
    h = make_harness()
    yield h
    h.close()


def bind_pod(h, pod):
    created = h.kube.create_pod(pod)
    h.provider.create_pod(created)
    return h.kube.get_pod(ko.namespace(created), ko.name(created))


class TestCleanup:
    def test_tombstone_reterminates_until_gone(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        # make the slice survive the first delete (stuck DELETING)
        h.fake.stuck(qr, S.DELETING)
        h.provider.delete_pod(pod)
        assert qr in h.fake.resources  # still there
        assert "default/train" in h.provider.deleted
        # sweep: re-terminates after 60s
        h.clock.advance(120)
        h.fake.get(qr).provision_delay_s = 0.0  # unstick: next delete works
        h.provider.cleanup_deleted_pods()
        h.provider.cleanup_deleted_pods()  # second pass notices 404, drops tombstone
        assert qr not in h.fake.resources
        assert h.provider.deleted == {}

    def test_stuck_terminating_no_slice_forced(self, h):
        pod = h.kube.create_pod(make_pod(name="zombie", chips=16))
        h.kube.delete_pod("default", "zombie")  # graceful -> deletionTimestamp
        h.provider.cleanup_stuck_terminating_pods()
        assert h.kube.list_pods() == []  # forced immediately (kubelet.go:1253-1271)

    def test_stuck_terminating_reterminate_after_5min(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        h.kube.delete_pod("default", "train")  # deletionTimestamp now (real time)
        deletes_before = h.fake.delete_count
        h.provider.cleanup_stuck_terminating_pods()
        assert h.fake.delete_count == deletes_before  # < 5 min: no action
        # rewrite deletionTimestamp 6 minutes into the past
        import time
        past = ko.now_iso(time.time() - 6 * 60)
        h.kube.store[("pods", "default", "train")]["metadata"]["deletionTimestamp"] = past
        h.clock.t = time.time()  # align fake clock with wall time for this test
        h.provider.cleanup_stuck_terminating_pods()
        assert h.fake.delete_count == deletes_before + 1  # re-terminated (:1332-1347)
        assert h.kube.list_pods() != []  # but pod not yet forced

    def test_stuck_terminating_force_after_15min(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        h.fake.stuck(qr, S.DELETING)
        h.kube.delete_pod("default", "train")
        import time
        past = ko.now_iso(time.time() - 16 * 60)
        h.kube.store[("pods", "default", "train")]["metadata"]["deletionTimestamp"] = past
        h.clock.t = time.time()
        h.provider.cleanup_stuck_terminating_pods()
        assert h.kube.list_pods() == []  # forced regardless (:1350-1366)

    def test_stuck_unreachable_tracked_per_pod_key(self, h):
        """Regression (VERDICT r1 weak #8): unreachable_since used to be
        looked up in self.deleted[key], but entries on this path are keyed
        key+"/released" or absent entirely, so the unreachable clock never
        started. Use an unparseable deletionTimestamp (deleting_for=0) so
        only the real per-key tracking can escalate."""
        bind_pod(h, make_pod(chips=16))
        h.kube.delete_pod("default", "train")
        h.kube.store[("pods", "default", "train")]["metadata"][
            "deletionTimestamp"] = "not-a-timestamp"
        h.fake.api_down = True  # slice status errors 503 (non-404)
        h.provider.cleanup_stuck_terminating_pods()
        assert h.kube.list_pods() != []  # first sighting: start the clock only
        assert "default/train" in h.provider._stuck_unreachable
        h.clock.advance(11 * 60)  # > stuck_unreachable_force_s (10 min)
        h.provider.cleanup_stuck_terminating_pods()
        assert h.kube.list_pods() == []  # escalated via unreachable tracking
        assert "default/train" not in h.provider._stuck_unreachable

    def test_stuck_unreachable_entry_cleared_on_any_force_delete(self, h):
        """Exiting the ladder via the slice-404 branch must clear the
        unreachable timestamp, or a later same-named pod inherits it and is
        force-deleted without its 10-minute grace (r2 review finding)."""
        bind_pod(h, make_pod(chips=16))
        qr = None
        from k8s_runpod_kubelet_tpu.provider.annotations import Annotations
        qr = ko.annotations(h.kube.get_pod("default", "train"))[A.QUEUED_RESOURCE]
        h.kube.delete_pod("default", "train")
        h.kube.store[("pods", "default", "train")]["metadata"][
            "deletionTimestamp"] = "not-a-timestamp"
        h.fake.api_down = True
        h.provider.cleanup_stuck_terminating_pods()  # starts the clock
        assert "default/train" in h.provider._stuck_unreachable
        # slice vanishes; API back up: next sweep force-deletes via 404 branch
        h.fake.api_down = False
        h.fake.vanish(qr)
        h.provider.cleanup_stuck_terminating_pods()
        assert h.kube.list_pods() == []
        assert "default/train" not in h.provider._stuck_unreachable

    def test_orphan_slice_swept_when_pod_gone(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        # pod vanishes from K8s without the provider seeing a delete event;
        # drop provider caches to simulate a restart that lost them
        h.kube.delete_pod("default", "train", grace_period_s=0)
        h.provider.pods.clear()
        h.provider.instances.clear()
        h.provider.cleanup_orphaned_slices()
        assert qr not in h.fake.resources

    def test_orphan_sweep_spares_foreign_slices(self, h):
        from k8s_runpod_kubelet_tpu.cloud.tpu_client import TpuParameters, WorkloadSpec
        h.tpu.create_queued_resource(TpuParameters(
            name="qr-foreign", accelerator_type="v5litepod-4",
            runtime_version="x", zone="us-central2-b",
            workload=WorkloadSpec(image="img"),
            labels={"managed-by": "someone-else"}))
        h.provider.cleanup_orphaned_slices()
        assert "qr-foreign" in h.fake.resources


class TestRecovery:
    def test_rebinds_annotated_pod(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        h.provider.update_all_pod_statuses()  # launch workload
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        # simulate restart: fresh provider, same kube + cloud
        from harness import make_harness as _mh
        from k8s_runpod_kubelet_tpu.provider import Provider
        from k8s_runpod_kubelet_tpu.gang import GangExecutor
        p2 = Provider(h.cfg, h.kube, h.tpu,
                      gang_executor=GangExecutor(h.transport), clock=h.clock)
        p2.load_running()
        info = p2.instances["default/train"]
        assert info.qr_name == qr
        assert info.workload_launched is True  # inferred from live runtime
        p2.update_all_pod_statuses()
        assert h.kube.get_pod("default", "train")["status"]["phase"] == "Running"

    def test_restart_does_not_reemit_recovery_event(self, h):
        """A requeued pod that recovered BEFORE a kubelet restart must not
        announce RecoveredFromPreemption again after it: the restarted
        provider re-enters ready once, and a duplicate event/metric would
        inflate the recovery count on every restart."""
        h.cfg.preemption_requeue_limit = 2
        pod = bind_pod(h, make_pod(chips=16))
        qr1 = ko.annotations(pod)[A.QUEUED_RESOURCE]
        h.provider.update_all_pod_statuses()
        h.fake.preempt(qr1)
        h.provider.update_all_pod_statuses()   # requeue
        h.provider.process_pending_pods()      # redeploy
        h.provider.update_all_pod_statuses()   # relaunch -> ready -> event
        recov = [e for e in h.kube.events
                 if e["reason"] == "RecoveredFromPreemption"]
        assert len(recov) == 1
        # simulate restart: fresh provider over the same kube + cloud
        from k8s_runpod_kubelet_tpu.gang import GangExecutor
        from k8s_runpod_kubelet_tpu.provider import Provider
        p2 = Provider(h.cfg, h.kube, h.tpu,
                      gang_executor=GangExecutor(h.transport), clock=h.clock)
        p2.load_running()
        info = p2.instances["default/train"]
        assert info.preemption_count == 1  # budget survived the restart
        p2.update_all_pod_statuses()       # re-enters ready exactly once
        recov = [e for e in h.kube.events
                 if e["reason"] == "RecoveredFromPreemption"]
        assert len(recov) == 1, [e["message"] for e in recov]
        assert p2.metrics.get_counter("tpu_kubelet_preemption_recoveries") == 0

    def test_restart_between_relaunch_and_ready_still_announces(self, h):
        """The mirror image of the no-duplicate case: if the kubelet dies
        AFTER the post-preemption gang relaunch but BEFORE it ever observed
        Ready (no RecoveredFromPreemption emitted, no tpu.dev/recovered-
        attempt marker), the restarted kubelet must still announce the
        recovery — a running gang alone is not proof it was announced."""
        pod = bind_pod(h, make_pod(chips=16))
        h.provider.update_all_pod_statuses()  # launch; pod Running
        # simulate "this running gang is preemption attempt 1 and nobody
        # announced it": the relaunch annotated the count, then the kubelet
        # died before the ready-observation pass
        h.kube.patch_pod("default", "train", {"metadata": {"annotations": {
            A.PREEMPTION_COUNT: "1"}}})
        from k8s_runpod_kubelet_tpu.gang import GangExecutor
        from k8s_runpod_kubelet_tpu.provider import Provider
        p2 = Provider(h.cfg, h.kube, h.tpu,
                      gang_executor=GangExecutor(h.transport), clock=h.clock)
        p2.load_running()
        assert p2.instances["default/train"].recovery_event_emitted is False
        p2.update_all_pod_statuses()
        recov = [e for e in h.kube.events
                 if e["reason"] == "RecoveredFromPreemption"]
        assert len(recov) == 1
        assert p2.metrics.get_counter("tpu_kubelet_preemption_recoveries") == 1
        # and the durable marker now suppresses a SECOND restart's re-emit
        assert ko.annotations(h.kube.get_pod("default", "train"))[
            A.RECOVERED_ATTEMPT] == "1"

    def test_rebinds_by_pod_uid_label_when_annotation_lost(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        # annotation patch never landed (crash between create and annotate)
        h.kube.patch_pod("default", "train",
                         {"metadata": {"annotations": {A.QUEUED_RESOURCE: None}}})
        from k8s_runpod_kubelet_tpu.provider import Provider
        p2 = Provider(h.cfg, h.kube, h.tpu, clock=h.clock)
        p2.load_running()
        assert p2.instances["default/train"].qr_name == qr

    def test_missing_slice_marks_failed(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        h.fake.vanish(ko.annotations(pod)[A.QUEUED_RESOURCE])
        from k8s_runpod_kubelet_tpu.provider import Provider
        p2 = Provider(h.cfg, h.kube, h.tpu, clock=h.clock)
        p2.load_running()
        got = h.kube.get_pod("default", "train")
        assert got["status"]["phase"] == "Failed"
        assert A.QUEUED_RESOURCE not in ko.annotations(got)

    def test_undeployed_pod_becomes_pending(self, h):
        h.kube.create_pod(make_pod(chips=16))  # bound but provider never saw it
        from k8s_runpod_kubelet_tpu.provider import Provider
        p2 = Provider(h.cfg, h.kube, h.tpu, clock=h.clock)
        p2.load_running()
        assert p2.instances["default/train"].pending_since is not None
        p2.process_pending_pods()  # deploys now
        assert p2.instances["default/train"].qr_name

    def test_orphan_running_slice_adopted_as_virtual_pod(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        h.provider.update_all_pod_statuses()
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        h.kube.delete_pod("default", "train", grace_period_s=0)  # pod gone, slice alive
        from k8s_runpod_kubelet_tpu.provider import Provider
        p2 = Provider(h.cfg, h.kube, h.tpu, clock=h.clock)
        p2.load_running()
        adopted = h.kube.get_pod("default", "train")  # recreated from labels
        assert ko.annotations(adopted)[A.EXTERNAL] == "true"  # kubelet.go:1580
        assert ko.node_name(adopted) == "virtual-tpu"  # fixed node-name bug
        assert p2.instances["default/train"].qr_name == qr

    def test_orphan_terminal_slice_deleted_not_adopted(self, h):
        pod = bind_pod(h, make_pod(chips=16))
        qr = ko.annotations(pod)[A.QUEUED_RESOURCE]
        h.fake.preempt(qr)
        h.kube.delete_pod("default", "train", grace_period_s=0)
        from k8s_runpod_kubelet_tpu.provider import Provider
        p2 = Provider(h.cfg, h.kube, h.tpu, clock=h.clock)
        p2.load_running()
        assert qr not in h.fake.resources
        assert h.kube.list_pods() == []
