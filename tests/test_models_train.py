"""Model + training tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_runpod_kubelet_tpu.models import (LlamaModel, gemma_7b, init_params,
                                           llama3_70b, llama3_8b,
                                           param_logical_axes, tiny_llama)
from k8s_runpod_kubelet_tpu.parallel import MeshConfig, make_mesh, param_shardings
from k8s_runpod_kubelet_tpu.workloads.train import (TrainConfig, Trainer,
                                                    cross_entropy_loss,
                                                    synthetic_batches)

import pytest as _pytest

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = _pytest.mark.slow

CFG = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, mlp_dim=128, max_seq_len=128,
                 dtype=jnp.float32, param_dtype=jnp.float32)


class TestModel:
    def test_forward_shapes(self):
        model = LlamaModel(CFG)
        params = init_params(CFG, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = model.forward(params, tokens)
        assert logits.shape == (2, 16, 128)
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_param_counts_match_known_sizes(self):
        assert llama3_8b().param_count == pytest.approx(8.0e9, rel=0.05)
        assert llama3_70b().param_count == pytest.approx(70.6e9, rel=0.05)
        assert gemma_7b().param_count == pytest.approx(8.5e9, rel=0.1)

    def test_causality(self):
        """Changing a future token must not affect past logits."""
        model = LlamaModel(CFG)
        params = init_params(CFG, jax.random.PRNGKey(0))
        t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        t2 = t1.at[0, 6].set(99)
        l1 = model.forward(params, t1)
        l2 = model.forward(params, t2)
        np.testing.assert_allclose(np.asarray(l1[0, :6]), np.asarray(l2[0, :6]),
                                   rtol=1e-5, atol=1e-5)
        assert not np.allclose(np.asarray(l1[0, 7]), np.asarray(l2[0, 7]))

    def test_decode_matches_forward(self):
        """prefill + decode_step must reproduce the full-sequence forward."""
        model = LlamaModel(CFG)
        params = init_params(CFG, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 128)
        full_logits = model.forward(params, tokens)

        cache = model.init_cache(batch=2, max_len=32)
        last, cache = model.prefill(params, tokens[:, :8], cache)
        np.testing.assert_allclose(np.asarray(last),
                                   np.asarray(full_logits[:, 7]),
                                   rtol=2e-3, atol=2e-3)
        for i in range(8, 12):
            logits, cache = model.decode_step(params, tokens[:, i], cache)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full_logits[:, i]),
                                       rtol=2e-3, atol=2e-3)

    def test_sharded_forward_on_mesh(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        model = LlamaModel(CFG, mesh)
        params = init_params(CFG, jax.random.PRNGKey(0), mesh)
        # params really are sharded
        wq = params["layers"]["wq"]
        assert len(wq.sharding.device_set) == 8
        tokens = jnp.zeros((4, 16), jnp.int32)
        logits = jax.jit(model.forward)(params, tokens)
        assert logits.shape == (4, 16, 128)

    def test_param_logical_axes_tree_matches(self):
        params = init_params(CFG, jax.random.PRNGKey(0))
        axes = param_logical_axes(CFG)
        ps = jax.tree_util.tree_structure(params)
        as_ = jax.tree_util.tree_structure(
            axes, is_leaf=lambda x: isinstance(x, tuple))
        assert ps == as_
        # axes tuples match leaf ranks
        flat_p = jax.tree_util.tree_leaves(params)
        flat_a = jax.tree_util.tree_leaves(
            axes, is_leaf=lambda x: isinstance(x, tuple))
        for p, a in zip(flat_p, flat_a):
            assert p.ndim == len(a), (p.shape, a)


GEMMA_CFG = tiny_llama(name="tiny-gemma", vocab_size=128, embed_dim=64,
                       n_layers=2, n_heads=4, n_kv_heads=4, head_dim=32,
                       mlp_dim=128, max_seq_len=128, rope_theta=10_000.0,
                       tie_embeddings=True, mlp_activation="gelu_tanh",
                       embed_scale=True, norm_zero_centered=True,
                       logit_softcap=30.0, dtype=jnp.float32,
                       param_dtype=jnp.float32)


class TestGemmaFamily:
    """Gemma architectural features: GeGLU, sqrt(E) embedding scale,
    zero-centered RMSNorm, tied head, logit softcap."""

    def test_real_config_is_faithful(self):
        cfg = gemma_7b()
        assert cfg.mlp_activation == "gelu_tanh"
        assert cfg.embed_scale and cfg.norm_zero_centered and cfg.tie_embeddings
        assert cfg.head_dim_ == 256 and cfg.n_kv_heads == 16

    def test_norm_weights_init_zero_centered(self):
        params = init_params(GEMMA_CFG, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(params["final_norm"]), 0.0)
        assert "lm_head" not in params  # tied

    def test_forward_finite_and_softcapped(self):
        model = LlamaModel(GEMMA_CFG)
        params = init_params(GEMMA_CFG, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = model.forward(params, tokens)
        assert bool(jnp.all(jnp.isfinite(logits)))
        assert float(jnp.max(jnp.abs(logits))) <= 30.0

    def test_embed_scale_changes_output(self):
        import dataclasses as dc
        params = init_params(GEMMA_CFG, jax.random.PRNGKey(0))
        tokens = jnp.arange(8, dtype=jnp.int32)[None]
        scaled = LlamaModel(GEMMA_CFG).forward(params, tokens)
        unscaled = LlamaModel(dc.replace(GEMMA_CFG, embed_scale=False)).forward(
            params, tokens)
        assert not np.allclose(np.asarray(scaled), np.asarray(unscaled))

    def test_decode_matches_forward(self):
        """The serving path (prefill/decode) must honor every Gemma feature."""
        model = LlamaModel(GEMMA_CFG)
        params = init_params(GEMMA_CFG, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 128)
        full_logits = model.forward(params, tokens)
        cache = model.init_cache(batch=2, max_len=32)
        last, cache = model.prefill(params, tokens[:, :8], cache)
        np.testing.assert_allclose(np.asarray(last),
                                   np.asarray(full_logits[:, 7]),
                                   rtol=2e-3, atol=2e-3)
        for i in range(8, 12):
            logits, cache = model.decode_step(params, tokens[:, i], cache)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full_logits[:, i]),
                                       rtol=2e-3, atol=2e-3)


GEMMA2_CFG = tiny_llama(name="tiny-gemma2", vocab_size=128, embed_dim=64,
                        n_layers=4, n_heads=4, n_kv_heads=2, head_dim=32,
                        mlp_dim=128, max_seq_len=128, rope_theta=10_000.0,
                        tie_embeddings=True, mlp_activation="gelu_tanh",
                        embed_scale=True, norm_zero_centered=True,
                        logit_softcap=30.0, attn_logit_softcap=50.0,
                        query_pre_attn_scalar=64.0, post_norms=True,
                        sliding_window=8, sliding_window_pattern=2,
                        dtype=jnp.float32, param_dtype=jnp.float32)


class TestGemma2Family:
    """Gemma-2 features on top of Gemma-1: local/global attention interleave,
    attention-score soft cap, query_pre_attn_scalar scaling, sandwich norms."""

    def test_real_config_is_faithful(self):
        from k8s_runpod_kubelet_tpu.models import gemma2_9b
        cfg = gemma2_9b()
        assert cfg.sliding_window == 4096 and cfg.sliding_window_pattern == 2
        assert cfg.attn_logit_softcap == 50.0 and cfg.logit_softcap == 30.0
        assert cfg.post_norms and cfg.tie_embeddings
        assert cfg.query_pre_attn_scalar == 256.0
        assert cfg.n_layers % cfg.sliding_window_pattern == 0

    def test_post_norm_params_exist(self):
        params = init_params(GEMMA2_CFG, jax.random.PRNGKey(0))
        assert params["layers"]["attn_post_norm"].shape == (4, 64)
        assert params["layers"]["mlp_post_norm"].shape == (4, 64)
        # zero-centered init (applied as 1+w)
        np.testing.assert_array_equal(
            np.asarray(params["layers"]["attn_post_norm"]), 0.0)

    def test_local_layers_actually_windowed(self):
        """Perturbing a token beyond every local window but inside the causal
        span must still change the output (global layers see it), while the
        same perturbation with pattern=1 (all-local) must NOT change
        positions more than W past it in a 1-layer model."""
        import dataclasses as dc
        cfg1 = dc.replace(GEMMA2_CFG, n_layers=1, sliding_window_pattern=1,
                          logit_softcap=None)
        model = LlamaModel(cfg1)
        params = init_params(cfg1, jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 128)
        toks2 = toks.at[0, 0].set((toks[0, 0] + 1) % 128)
        a = model.forward(params, toks)
        b = model.forward(params, toks2)
        # position >= W(=8): token 0 is outside the window -> logits equal
        np.testing.assert_allclose(np.asarray(a[0, 12:]),
                                   np.asarray(b[0, 12:]), atol=1e-5)
        assert not np.allclose(np.asarray(a[0, 1:6]), np.asarray(b[0, 1:6]))
        # with the interleave, the global sublayer carries token 0 everywhere
        model2 = LlamaModel(GEMMA2_CFG)
        params2 = init_params(GEMMA2_CFG, jax.random.PRNGKey(0))
        a2 = model2.forward(params2, toks)
        b2 = model2.forward(params2, toks2)
        assert not np.allclose(np.asarray(a2[0, 12:]), np.asarray(b2[0, 12:]))

    def test_decode_matches_forward(self):
        """Prefill + decode must honor windows per sublayer, soft caps, and
        post-norms — parity with the training forward, past the window edge."""
        model = LlamaModel(GEMMA2_CFG)
        params = init_params(GEMMA2_CFG, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 20), 0, 128)
        full_logits = model.forward(params, tokens)
        cache = model.init_cache(batch=2, max_len=32)
        last, cache = model.prefill(params, tokens[:, :8], cache)
        np.testing.assert_allclose(np.asarray(last),
                                   np.asarray(full_logits[:, 7]),
                                   rtol=2e-3, atol=2e-3)
        for i in range(8, 20):  # decode well past the W=8 window boundary
            logits, cache = model.decode_step(params, tokens[:, i], cache)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full_logits[:, i]),
                                       rtol=2e-3, atol=2e-3)

    def test_pattern_must_divide_layers(self):
        import dataclasses as dc
        bad = dc.replace(GEMMA2_CFG, n_layers=3)
        model = LlamaModel(bad)
        params = init_params(bad, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="not divisible"):
            model.forward(params, jnp.zeros((1, 8), jnp.int32))


GEMMA3_CFG = tiny_llama(name="tiny-gemma3", vocab_size=128, embed_dim=64,
                        n_layers=6, n_heads=4, n_kv_heads=2, head_dim=32,
                        mlp_dim=128, max_seq_len=128,
                        rope_theta=100_000.0, rope_local_theta=10_000.0,
                        rope_scaling={"rope_type": "linear", "factor": 2.0},
                        tie_embeddings=True, mlp_activation="gelu_tanh",
                        embed_scale=True, norm_zero_centered=True,
                        query_pre_attn_scalar=32.0, post_norms=True,
                        qk_norm=True, sliding_window=8,
                        sliding_window_pattern=6,
                        dtype=jnp.float32, param_dtype=jnp.float32)


class TestGemma3Family:
    """Gemma-3 on top of Gemma-2: qk-norm, dual RoPE bases (local/global),
    linear rope scaling, 5:1 interleave; soft caps gone."""

    def test_real_config_is_faithful(self):
        from k8s_runpod_kubelet_tpu.models import gemma3_12b
        cfg = gemma3_12b()
        assert cfg.qk_norm and cfg.rope_local_theta == 10_000.0
        assert cfg.sliding_window == 1024 and cfg.sliding_window_pattern == 6
        assert cfg.attn_logit_softcap is None and cfg.logit_softcap is None
        assert cfg.rope_scaling == {"rope_type": "linear", "factor": 8.0}
        assert cfg.n_layers % cfg.sliding_window_pattern == 0

    def test_qk_norm_params_identity_init(self):
        params = init_params(GEMMA3_CFG, jax.random.PRNGKey(0))
        assert params["layers"]["q_norm"].shape == (6, 32)
        # zero-centered: stored 0, applied as (1 + w)
        np.testing.assert_array_equal(np.asarray(params["layers"]["k_norm"]),
                                      0.0)

    def test_qk_norm_changes_output(self):
        import dataclasses as dc
        params = init_params(GEMMA3_CFG, jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 128)
        with_norm = LlamaModel(GEMMA3_CFG).forward(params, toks)
        plain_cfg = dc.replace(GEMMA3_CFG, qk_norm=False)
        plain_params = init_params(plain_cfg, jax.random.PRNGKey(0))
        without = LlamaModel(plain_cfg).forward(plain_params, toks)
        assert not np.allclose(np.asarray(with_norm), np.asarray(without))

    def test_local_and_global_rope_differ(self):
        """Dual bases: zeroing the local theta difference must change
        outputs (the local table is actually used on windowed sublayers)."""
        import dataclasses as dc
        params = init_params(GEMMA3_CFG, jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, 16), 0, 128)
        dual = LlamaModel(GEMMA3_CFG).forward(params, toks)
        single = LlamaModel(dc.replace(GEMMA3_CFG, rope_local_theta=None)
                            ).forward(params, toks)
        assert not np.allclose(np.asarray(dual), np.asarray(single))

    def test_decode_matches_forward(self):
        model = LlamaModel(GEMMA3_CFG)
        params = init_params(GEMMA3_CFG, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 20), 0, 128)
        full_logits = model.forward(params, tokens)
        cache = model.init_cache(batch=2, max_len=32)
        last, cache = model.prefill(params, tokens[:, :8], cache)
        np.testing.assert_allclose(np.asarray(last),
                                   np.asarray(full_logits[:, 7]),
                                   rtol=2e-3, atol=2e-3)
        for i in range(8, 20):
            logits, cache = model.decode_step(params, tokens[:, i], cache)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full_logits[:, i]),
                                       rtol=2e-3, atol=2e-3)


class TestQwenFamily:
    """Qwen2 architectural feature: biased q/k/v projections."""

    QCFG = tiny_llama(name="tiny-qwen", vocab_size=128, embed_dim=64,
                      n_layers=2, n_heads=4, n_kv_heads=2, mlp_dim=128,
                      max_seq_len=128, qkv_bias=True,
                      dtype=jnp.float32, param_dtype=jnp.float32)

    def test_real_config_is_faithful(self):
        from k8s_runpod_kubelet_tpu.models import qwen2_7b
        cfg = qwen2_7b()
        assert cfg.qkv_bias and cfg.n_kv_heads == 4 and cfg.mlp_dim == 18944
        # param count within 2% of the published 7.6B
        assert abs(cfg.param_count - 7.62e9) / 7.62e9 < 0.02

    def test_bias_params_exist_and_init_zero(self):
        params = init_params(self.QCFG, jax.random.PRNGKey(0))
        for name in ("wq_b", "wk_b", "wv_b"):
            np.testing.assert_array_equal(np.asarray(params["layers"][name]), 0.0)
        axes = param_logical_axes(self.QCFG)
        assert axes["layers"]["wq_b"] == ("layer", "heads")

    def test_zero_bias_matches_biasless_model(self):
        import dataclasses as dc
        params = init_params(self.QCFG, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
        with_bias = LlamaModel(self.QCFG).forward(params, tokens)
        plain = {k: v for k, v in params.items()}
        plain["layers"] = {k: v for k, v in params["layers"].items()
                           if not k.endswith("_b")}
        without = LlamaModel(dc.replace(self.QCFG, qkv_bias=False)).forward(
            plain, tokens)
        np.testing.assert_allclose(np.asarray(with_bias), np.asarray(without),
                                   rtol=1e-6, atol=1e-6)

    def test_nonzero_bias_changes_output_and_decode_matches(self):
        params = init_params(self.QCFG, jax.random.PRNGKey(0))
        zeroed = LlamaModel(self.QCFG).forward(
            params, jnp.arange(8, dtype=jnp.int32)[None])
        params["layers"]["wq_b"] = jnp.full_like(params["layers"]["wq_b"], 0.3)
        params["layers"]["wk_b"] = jnp.full_like(params["layers"]["wk_b"], -0.2)
        params["layers"]["wv_b"] = jnp.full_like(params["layers"]["wv_b"], 0.1)
        model = LlamaModel(self.QCFG)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 128)
        full_logits = model.forward(params, tokens)
        assert not np.allclose(np.asarray(full_logits[:1, :8]), np.asarray(zeroed))
        # serving path honors the bias
        cache = model.init_cache(batch=2, max_len=32)
        last, cache = model.prefill(params, tokens[:, :8], cache)
        np.testing.assert_allclose(np.asarray(last),
                                   np.asarray(full_logits[:, 7]),
                                   rtol=2e-3, atol=2e-3)
        for i in range(8, 12):
            logits, cache = model.decode_step(params, tokens[:, i], cache)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full_logits[:, i]),
                                       rtol=2e-3, atol=2e-3)

    def test_trains_on_mesh(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        tc = TrainConfig(batch_size=4, seq_len=16, steps=2, warmup_steps=1)
        out = Trainer(self.QCFG, tc, mesh=mesh).run(steps=2)
        assert np.isfinite(out["final_loss"])


class TestTraining:
    def test_loss_decreases_on_memorization(self):
        tc = TrainConfig(learning_rate=1e-2, warmup_steps=2, batch_size=2,
                         seq_len=32, steps=20, grad_clip=1.0)
        trainer = Trainer(CFG, tc)
        fixed = jax.random.randint(jax.random.PRNGKey(7), (2, 33), 0, 128)
        batches = iter(lambda: fixed, None)  # same batch forever
        first = trainer.run(steps=1, batches=batches)
        out = trainer.run(steps=19, batches=batches)
        assert out["final_loss"] < first["final_loss"] * 0.7
        assert out["tokens_per_s"] > 0

    def test_sharded_training_on_mesh(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, seq=1, tensor=2))
        tc = TrainConfig(batch_size=4, seq_len=32, steps=3)
        trainer = Trainer(CFG, tc, mesh=mesh)
        out = trainer.run(steps=3)
        assert np.isfinite(out["final_loss"])
        # grads flowed through sharded params: params still sharded after update
        assert len(trainer.params["layers"]["wq"].sharding.device_set) == 8

    def test_ring_attention_training_on_seq_axis(self):
        mesh = make_mesh(MeshConfig(data=1, fsdp=2, seq=2, tensor=2))
        tc = TrainConfig(batch_size=2, seq_len=64, steps=2)
        trainer = Trainer(CFG, tc, mesh=mesh)
        out = trainer.run(steps=2)
        assert np.isfinite(out["final_loss"])

    def test_gemma2_interleave_trains_on_seq_axis(self):
        # windowed-interleave + softcap under sequence parallelism: the two
        # r2 "known seams" guards are gone; local sublayers band-mask on the
        # ring, global sublayers ring the full context (VERDICT r2 item 4)
        mesh = make_mesh(MeshConfig(data=1, fsdp=2, seq=2, tensor=2))
        tc = TrainConfig(batch_size=2, seq_len=64, steps=2)
        trainer = Trainer(GEMMA2_CFG, tc, mesh=mesh)
        out = trainer.run(steps=2)
        assert np.isfinite(out["final_loss"])

    def test_gemma2_seq_axis_logits_match_single_device(self):
        # parity, not just "runs": seq-sharded forward == unsharded forward
        ref_model = LlamaModel(GEMMA2_CFG)
        params = init_params(GEMMA2_CFG, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 128)
        ref = ref_model.forward(params, tokens)
        mesh = make_mesh(MeshConfig(data=1, fsdp=2, seq=2, tensor=2))
        sharded_model = LlamaModel(GEMMA2_CFG, mesh)
        sharded_params = init_params(GEMMA2_CFG, jax.random.PRNGKey(0), mesh)
        got = jax.jit(sharded_model.forward)(sharded_params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_checkpoint_resume(self, tmp_path):
        tc = TrainConfig(batch_size=2, seq_len=16, steps=4,
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         checkpoint_every=2)
        t1 = Trainer(CFG, tc)
        t1.run(steps=4)
        t1.save()
        t2 = Trainer(CFG, tc)
        assert t2.restore() is True
        assert t2.step == t1.step
        np.testing.assert_allclose(
            np.asarray(t1.params["final_norm"]),
            np.asarray(t2.params["final_norm"]))

    def test_cross_entropy_sanity(self):
        logits = jnp.zeros((1, 4, 10))
        targets = jnp.zeros((1, 4), jnp.int32)
        assert float(cross_entropy_loss(logits, targets)) == pytest.approx(
            np.log(10), rel=1e-5)


class TestZLoss:
    def test_z_loss_bounds_logit_magnitude(self):
        """Training WITH z-loss keeps mean |log Z| smaller than without,
        while the reported loss stays the plain CE (curves comparable)."""
        tc_kw = dict(batch_size=4, seq_len=32, steps=60, warmup_steps=5,
                     learning_rate=3e-3)
        outs = {}
        for name, coef in (("plain", 0.0), ("zloss", 1e-2)):
            tc = TrainConfig(z_loss_coef=coef, **tc_kw)
            trainer = Trainer(CFG, tc, seed=0)
            batches = synthetic_batches(CFG, tc)
            out = trainer.run(steps=60, batches=batches)
            logits = trainer.model.forward(trainer.params,
                                           next(batches)[:, :-1])
            lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
            outs[name] = (out["final_loss"], float(jnp.mean(jnp.abs(lse))))
        assert outs["zloss"][1] < outs["plain"][1]
        # reported loss is CE only: same order of magnitude either way
        assert abs(outs["zloss"][0] - outs["plain"][0]) < 1.0


class TestGradAccumAndEval:
    def _cfg(self):
        import dataclasses
        import jax.numpy as jnp
        from k8s_runpod_kubelet_tpu.models import tiny_llama
        return dataclasses.replace(
            tiny_llama(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, mlp_dim=96, max_seq_len=64),
            dtype=jnp.float32, param_dtype=jnp.float32, remat=False)

    def test_accumulated_step_matches_full_batch(self):
        """accum=4 over a 8-row batch must produce (numerically close) the
        same update as one full-batch step — same mean gradient."""
        import jax
        import numpy as np
        from k8s_runpod_kubelet_tpu.workloads.train import TrainConfig, Trainer
        cfg = self._cfg()
        batch = jax.random.randint(jax.random.PRNGKey(0), (8, 17), 0,
                                   cfg.vocab_size, jax.numpy.int32)
        outs = {}
        for accum in (1, 4):
            tc = TrainConfig(batch_size=8, seq_len=16, steps=1,
                             warmup_steps=1, grad_accum_steps=accum)
            tr = Trainer(cfg, tc, seed=0)
            p, _, m = tr.step_fn(tr.params, tr.opt_state, batch)
            outs[accum] = (np.asarray(p["layers"]["wq"]), float(m["loss"]))
        np.testing.assert_allclose(outs[1][0], outs[4][0], atol=1e-5)
        assert abs(outs[1][1] - outs[4][1]) < 1e-4

    def test_indivisible_accum_rejected(self):
        import jax
        import pytest
        from k8s_runpod_kubelet_tpu.workloads.train import TrainConfig, Trainer
        cfg = self._cfg()
        tc = TrainConfig(batch_size=6, seq_len=16, steps=1, warmup_steps=1,
                         grad_accum_steps=4)
        tr = Trainer(cfg, tc)
        batch = jax.numpy.zeros((6, 17), jax.numpy.int32)
        with pytest.raises(ValueError, match="divisible"):
            tr.step_fn(tr.params, tr.opt_state, batch)

    def test_evaluate_reports_ppl_and_improves_with_training(self):
        import numpy as np
        from k8s_runpod_kubelet_tpu.workloads.train import TrainConfig, Trainer
        cfg = self._cfg()
        tc = TrainConfig(batch_size=4, seq_len=16, steps=6, warmup_steps=1,
                         learning_rate=3e-3)
        tr = Trainer(cfg, tc)
        before = tr.evaluate(steps=3)
        assert before["eval_ppl"] > 1.0
        assert np.isclose(before["eval_ppl"], np.exp(before["eval_loss"]),
                          rtol=1e-5)
        # eval is deterministic: same batches, same params -> same number
        assert tr.evaluate(steps=3)["eval_loss"] == before["eval_loss"]
        # uniform tokens are AT entropy (nothing to learn), so improvement
        # needs a learnable stream: memorize one fixed batch and eval on it
        import itertools
        import jax
        fixed = jax.random.randint(jax.random.PRNGKey(42), (4, 17), 0,
                                   cfg.vocab_size, jax.numpy.int32)
        fixed_stream = lambda: itertools.repeat(fixed)
        b0 = tr.evaluate(batches=fixed_stream(), steps=1)
        tr.run(steps=6, batches=fixed_stream())
        b1 = tr.evaluate(batches=fixed_stream(), steps=1)
        assert b1["eval_loss"] < b0["eval_loss"], (b1, b0)


class TestAsyncCheckpoint:
    def test_async_save_is_durable_at_boundaries(self, tmp_path):
        """Async save returns after staging; wait_pending() makes it
        durable for a successor process (a crash before the write lands
        loses that checkpoint BY DESIGN — orbax commit markers keep the
        directory consistent; checkpoint_every bounds the loss)."""
        tc = TrainConfig(batch_size=2, seq_len=16, steps=2,
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         checkpoint_every=100, async_checkpoint=True)
        t1 = Trainer(CFG, tc)
        t1.run(steps=2)
        t1.save(block=False)           # staged; write in background
        t1.wait_pending()              # what run()'s boundary does
        t2 = Trainer(CFG, tc)
        assert t2.restore() is True
        assert t2.step == t1.step
        np.testing.assert_allclose(np.asarray(t1.params["final_norm"]),
                                   np.asarray(t2.params["final_norm"]))

    def test_run_boundary_makes_loop_saves_durable(self, tmp_path):
        """Saves triggered INSIDE run() by checkpoint_every are durable
        when run() returns — a successor restores with no extra waiting."""
        tc = TrainConfig(batch_size=2, seq_len=16, steps=4,
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         checkpoint_every=2, async_checkpoint=True)
        t1 = Trainer(CFG, tc)
        t1.run(steps=4)                # saves at steps 2 and 4, waits at end
        t2 = Trainer(CFG, tc)
        assert t2.restore() is True
        assert t2.step == 4

    def test_blocking_save_still_available(self, tmp_path):
        tc = TrainConfig(batch_size=2, seq_len=16, steps=1,
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         checkpoint_every=100, async_checkpoint=False)
        t1 = Trainer(CFG, tc)
        t1.run(steps=1)
        t1.save()                      # default: blocks until durable
        t2 = Trainer(CFG, tc)
        assert t2.restore() is True
