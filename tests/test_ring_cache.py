"""Ring KV cache for uniformly-windowed models (Mistral-style serving).

The ring stores only ~window + write-slack positions per slot; ``abs_pos``
records which absolute position each ring slot holds and attention masks on
it. These tests pin the three hard invariants:
- decode parity with the full (windowed) forward PAST the wraparound point,
- chunked prefill + speculative rejections never corrupt visible entries,
- the engine picks the ring automatically for windowed models and its
  greedy output is identical to the linear-cache engine's.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_runpod_kubelet_tpu.models import LlamaModel, init_params, tiny_llama
from k8s_runpod_kubelet_tpu.workloads.serving import ServingConfig, ServingEngine

import pytest as _pytest

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = _pytest.mark.slow

# W=8 window, ring R=16 (slack 8): positions wrap after 16 tokens
WCFG = tiny_llama(name="tiny-window", vocab_size=128, embed_dim=64,
                  n_layers=2, n_heads=4, n_kv_heads=2, mlp_dim=128,
                  max_seq_len=256, sliding_window=8,
                  dtype=jnp.float32, param_dtype=jnp.float32)
RING = 16


@pytest.fixture(scope="module")
def params():
    return init_params(WCFG, jax.random.PRNGKey(0))


class TestRingCacheModel:
    def test_requires_uniform_window(self):
        model = LlamaModel(tiny_llama(vocab_size=64, embed_dim=32, n_layers=2,
                                      n_heads=2, n_kv_heads=1, mlp_dim=48))
        with pytest.raises(ValueError, match="uniform sliding_window"):
            model.init_ring_cache(1, 64)
        g2 = tiny_llama(vocab_size=64, embed_dim=32, n_layers=2, n_heads=2,
                        n_kv_heads=1, mlp_dim=48, sliding_window=8,
                        sliding_window_pattern=2)
        with pytest.raises(ValueError, match="uniform sliding_window"):
            LlamaModel(g2).init_ring_cache(1, 64)
        with pytest.raises(ValueError, match="exceed the window"):
            LlamaModel(WCFG).init_ring_cache(1, 8)

    def test_decode_matches_forward_past_wraparound(self, params):
        """Logical position runs to 40 on a 16-slot ring (2.5 wraps); every
        decoded logit must match the windowed full forward."""
        model = LlamaModel(WCFG)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 40), 0, 128)
        full = model.forward(params, toks)
        cache = model.init_ring_cache(2, RING)
        last, cache = model.prefill(params, toks[:, :6], cache)
        np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, 5]),
                                   rtol=2e-3, atol=2e-3)
        for i in range(6, 40):
            logits, cache = model.decode_step(params, toks[:, i], cache)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[:, i]),
                rtol=2e-3, atol=2e-3, err_msg=f"position {i}")

    def test_ring_equals_linear_cache_decode(self, params):
        """Same token stream through ring and linear caches: identical."""
        model = LlamaModel(WCFG)
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, 30), 0, 128)
        ring = model.init_ring_cache(1, RING)
        lin = model.init_cache(1, 64)
        l_r, ring = model.prefill(params, toks[:, :4], ring)
        l_l, lin = model.prefill(params, toks[:, :4], lin)
        np.testing.assert_allclose(np.asarray(l_r), np.asarray(l_l),
                                   rtol=1e-5, atol=1e-5)
        for i in range(4, 30):
            o_r, ring = model.decode_step(params, toks[:, i], ring)
            o_l, lin = model.decode_step(params, toks[:, i], lin)
            np.testing.assert_allclose(np.asarray(o_r), np.asarray(o_l),
                                       rtol=1e-5, atol=1e-5)

    def test_padded_prefill_stamps_only_real_positions(self, params):
        model = LlamaModel(WCFG)
        cache = model.init_ring_cache(1, RING)
        toks = jnp.asarray([[5, 6, 7, 0, 0, 0, 0, 0]], jnp.int32)
        _, cache = model.prefill(params, toks, cache,
                                 true_length=jnp.asarray([3], jnp.int32))
        abs_pos = np.asarray(cache["abs_pos"][0])
        np.testing.assert_array_equal(abs_pos[:3], [0, 1, 2])
        np.testing.assert_array_equal(abs_pos[3:], -1)

    def test_verify_rejection_then_decode_stays_exact(self, params):
        """Speculative shape: verify writes K=4 tokens, only 1 commits
        (worst-case rejection), then plain decode continues across the
        wraparound — logits must still match the full forward."""
        model = LlamaModel(WCFG)
        verify = jax.jit(model.verify_step)
        toks = jax.random.randint(jax.random.PRNGKey(3), (1, 36), 0, 128)
        full = model.forward(params, toks)
        cache = model.init_ring_cache(1, RING)
        _, cache = model.prefill(params, toks[:, :6], cache)
        i = 6
        # alternate: one verify call with 3 junk drafts (rejected), commit 1,
        # then two plain decode steps; repeat
        while i < 33:
            tin = jnp.concatenate(
                [toks[:, i:i + 1],
                 jnp.full((1, 3), 99, jnp.int32)], axis=1)  # junk drafts
            logits, cache = verify(params, tin, cache)
            np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                       np.asarray(full[:, i]),
                                       rtol=2e-3, atol=2e-3,
                                       err_msg=f"verify at {i}")
            cache = dict(cache)
            cache["index"] = cache["index"] + 1  # commit only token 0
            i += 1
            for _ in range(2):
                logits, cache = model.decode_step(params, toks[:, i], cache)
                np.testing.assert_allclose(np.asarray(logits),
                                           np.asarray(full[:, i]),
                                           rtol=2e-3, atol=2e-3,
                                           err_msg=f"decode at {i}")
                i += 1

    def test_insert_into_slot_carries_abs_pos(self, params):
        model = LlamaModel(WCFG)
        big = model.init_ring_cache(2, RING)
        single = model.init_ring_cache(1, RING)
        _, single = model.prefill(params, jnp.asarray([[1, 2, 3]], jnp.int32),
                                  single)
        big = LlamaModel.insert_into_slot(big, single, 1)
        np.testing.assert_array_equal(np.asarray(big["abs_pos"][1]),
                                      np.asarray(single["abs_pos"][0]))
        np.testing.assert_array_equal(np.asarray(big["abs_pos"][0]), -1)


class TestRingCacheEngine:
    def _engine(self, params, ring, **kw):
        sc = ServingConfig(slots=2, max_prefill_len=16, cache_len=256,
                           max_new_tokens=24, ring_cache=ring, **kw)
        return ServingEngine(WCFG, params, sc).start()

    def test_auto_on_for_windowed_model_and_matches_linear(self, params):
        # paged_decode=False: since the uniform-window paged loop (ISSUE
        # 13) the paged slot table wins ring_cache=None by default (its
        # page recycling IS the memory win) — the contiguous ring is the
        # paged-off path this test pins
        e_ring = self._engine(params, ring=None, paged_decode=False)
        e_lin = self._engine(params, ring=False, paged_decode=False)
        try:
            # 8 window + 16 slack -> rounds up to one 128 lane tile, and
            # 128 < cache_len 256 so auto enables
            assert e_ring._ring_len == 128
            assert "abs_pos" in e_ring._cache
            assert "abs_pos" not in e_lin._cache
            prompts = [[(7 * j + i) % 128 for j in range(1 + 3 * i)]
                       for i in range(4)]
            outs_r = [e_ring.submit(p, max_new_tokens=24).result(timeout=60)
                      for p in prompts]
            outs_l = [e_lin.submit(p, max_new_tokens=24).result(timeout=60)
                      for p in prompts]
            for r, l in zip(outs_r, outs_l):
                assert r["tokens"] == l["tokens"]
        finally:
            e_ring.stop()
            e_lin.stop()

    def test_speculative_on_ring_matches_linear(self, params):
        e_ring = self._engine(params, ring=True, speculate_k=3)
        e_lin = self._engine(params, ring=False, speculate_k=3)
        try:
            prompt = [3, 1, 4, 1, 5, 9, 2, 6, 3, 1, 4, 1, 5]  # repeats help PLD
            r = e_ring.submit(prompt, max_new_tokens=24).result(timeout=60)
            l = e_lin.submit(prompt, max_new_tokens=24).result(timeout=60)
            assert r["tokens"] == l["tokens"]
        finally:
            e_ring.stop()
            e_lin.stop()

    def test_forcing_ring_on_unwindowed_model_raises(self):
        cfg = tiny_llama(vocab_size=64, embed_dim=32, n_layers=2, n_heads=2,
                         n_kv_heads=1, mlp_dim=48, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        p = init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="sliding window"):
            ServingEngine(cfg, p, ServingConfig(slots=1, ring_cache=True))

    def test_auto_off_when_no_memory_win(self, params):
        sc = ServingConfig(slots=1, max_prefill_len=16, cache_len=64,
                           ring_cache=None, paged_decode=False)
        e = ServingEngine(WCFG, params, sc)
        # ring would be 128 >= cache_len 64 -> linear (paged_decode=False
        # so the contiguous cache exists to inspect at all)
        assert e._ring_len is None and "abs_pos" not in e._cache

    def test_paged_loop_wins_ring_auto(self, params):
        """ring_cache=None on a paged-eligible windowed engine: the paged
        slot table takes the window's memory win (page recycling), the
        contiguous ring never builds."""
        e = self._engine(params, ring=None)
        try:
            assert e._paged_loop and e._ring_len is None
            assert e._cache is None
            assert e._window == WCFG.sliding_window
        finally:
            e.stop()
