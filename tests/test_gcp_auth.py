"""OAuth2/ADC token refresh (VERDICT r2 item 5): the transport must survive
GCP's ~1h token expiry — rotating-token fake server, 401-refresh-retry,
ADC refresh-token exchange, and provider resolution order."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qs

import pytest

from k8s_runpod_kubelet_tpu.cloud import (AdcUserTokenProvider, AuthError,
                                          HttpTransport,
                                          MetadataTokenProvider,
                                          StaticTokenProvider,
                                          TransportError,
                                          default_token_provider)
from k8s_runpod_kubelet_tpu.cloud import gcp_auth


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class _CountingProvider(gcp_auth._CachingProvider):
    """Deterministic provider: token-N with a fixed lifetime."""

    def __init__(self, lifetime=3600.0, now=None):
        super().__init__(now or _Clock())
        self.lifetime = lifetime
        self.fetches = 0

    def _fetch(self):
        self.fetches += 1
        return f"token-{self.fetches}", self.lifetime


def _serve(handler_cls):
    srv = HTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestCachingProvider:
    def test_caches_until_near_expiry(self):
        clock = _Clock()
        p = _CountingProvider(lifetime=3600.0, now=clock)
        assert p() == "token-1"
        assert p() == "token-1"          # cached
        clock.t += 3600.0 - gcp_auth.EXPIRY_SLACK_S - 1
        assert p() == "token-1"          # still inside the slack margin
        clock.t += 2
        assert p() == "token-2"          # refreshed before true expiry
        assert p.fetches == 2

    def test_invalidate_forces_refetch(self):
        p = _CountingProvider()
        assert p() == "token-1"
        p.invalidate()
        assert p() == "token-2"

    def test_static_provider_has_no_invalidate(self):
        # no invalidate() => the transport's 401-refresh gate skips it and
        # a deterministic 401 fails fast with no duplicate request
        p = StaticTokenProvider("fixed")
        assert p() == "fixed"
        assert not hasattr(p, "invalidate")


class TestAdcUserTokenProvider:
    def test_refresh_token_exchange(self):
        seen = {}

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                form = parse_qs(
                    self.rfile.read(int(self.headers["Content-Length"]))
                    .decode())
                seen.update({k: v[0] for k, v in form.items()})
                body = json.dumps({"access_token": "fresh-at",
                                   "expires_in": 3599}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = _serve(H)
        try:
            p = AdcUserTokenProvider(
                {"client_id": "cid", "client_secret": "cs",
                 "refresh_token": "rt"},
                token_url=f"http://127.0.0.1:{srv.server_port}/token")
            assert p() == "fresh-at"
            assert seen == {"grant_type": "refresh_token", "client_id": "cid",
                            "client_secret": "cs", "refresh_token": "rt"}
        finally:
            srv.shutdown()

    def test_missing_fields_rejected(self):
        with pytest.raises(AuthError, match="refresh_token"):
            AdcUserTokenProvider({"client_id": "x", "client_secret": "y"})


class TestMetadataTokenProvider:
    def test_fetch_requires_flavor_header(self):
        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.headers.get("Metadata-Flavor") != "Google":
                    self.send_response(403)
                    self.end_headers()
                    return
                body = json.dumps({"access_token": "md-token",
                                   "expires_in": 1800}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = _serve(H)
        try:
            p = MetadataTokenProvider(
                url=f"http://127.0.0.1:{srv.server_port}/token")
            assert p() == "md-token"
        finally:
            srv.shutdown()

    def test_unreachable_is_auth_error(self):
        p = MetadataTokenProvider(url="http://127.0.0.1:1/token",
                                  timeout_s=0.2)
        with pytest.raises(AuthError, match="metadata"):
            p()


class _RotatingAuthAPI:
    """API fake whose accepted bearer token can be rotated out from under
    the client — the GCP expiry scenario."""

    def __init__(self):
        self.valid = "epoch-1"
        self.requests = []

        fake = self

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                auth = self.headers.get("Authorization", "")
                fake.requests.append(auth)
                if auth != f"Bearer {fake.valid}":
                    body = b'{"error": "invalid token"}'
                    self.send_response(401)
                else:
                    body = b'{"ok": true}'
                    self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.srv = _serve(H)
        self.url = f"http://127.0.0.1:{self.srv.server_port}"


class TestTransport401Refresh:
    def test_refreshes_once_and_succeeds(self):
        api = _RotatingAuthAPI()

        class P(gcp_auth._CachingProvider):
            def _fetch(self):
                return api.valid, 3600.0  # "the token the IdP would mint now"

        try:
            p = P()
            t = HttpTransport(api.url, token_provider=p, sleep=lambda s: None)
            assert t.request("GET", "/x") == {"ok": True}
            api.valid = "epoch-2"  # server-side expiry: cached token now dead
            assert t.request("GET", "/x") == {"ok": True}
            # stale 401 -> invalidate -> fresh token -> success, one retry
            assert api.requests == ["Bearer epoch-1", "Bearer epoch-1",
                                    "Bearer epoch-2"]
        finally:
            api.srv.shutdown()

    def test_second_401_gives_up(self):
        api = _RotatingAuthAPI()

        class P(gcp_auth._CachingProvider):
            def _fetch(self):
                return "always-wrong", 3600.0

        try:
            t = HttpTransport(api.url, token_provider=P(),
                              sleep=lambda s: None)
            with pytest.raises(TransportError) as ei:
                t.request("GET", "/x")
            assert ei.value.status == 401
            assert len(api.requests) == 2  # original + exactly one refresh
        finally:
            api.srv.shutdown()

    def test_token_fetch_failure_is_retried_as_transport_error(self):
        # a transient provider blip must ride the normal retry/backoff and
        # surface as TransportError (the contract TpuClient wraps), never
        # as a naked AuthError with zero retries
        calls = []

        def flaky_provider():
            calls.append(1)
            raise AuthError("metadata server blip")

        sleeps = []
        t = HttpTransport("http://127.0.0.1:1", token_provider=flaky_provider,
                          sleep=sleeps.append)
        with pytest.raises(TransportError, match="token fetch failed"):
            t.request("GET", "/x")
        assert len(calls) == 3 and len(sleeps) == 2  # full retry ladder

    def test_static_token_401_fails_fast(self):
        api = _RotatingAuthAPI()
        try:
            t = HttpTransport(api.url, token="stale", sleep=lambda s: None)
            with pytest.raises(TransportError) as ei:
                t.request("GET", "/x")
            assert ei.value.status == 401
            assert len(api.requests) == 1  # nothing to refresh
        finally:
            api.srv.shutdown()


class TestGoogleEndpointGate:
    def test_host_match_only(self):
        from k8s_runpod_kubelet_tpu.cloud import is_google_api_endpoint
        assert is_google_api_endpoint("https://tpu.googleapis.com")
        assert is_google_api_endpoint("https://googleapis.com/v2")
        # substring tricks must NOT attach ambient credentials
        assert not is_google_api_endpoint("https://evilgoogleapis.com/v2")
        assert not is_google_api_endpoint(
            "https://aggregator.example/googleapis.com/proxy")
        assert not is_google_api_endpoint("http://127.0.0.1:8080")
        assert not is_google_api_endpoint("")


class TestDefaultProviderResolution:
    def test_static_token_wins(self, monkeypatch, tmp_path):
        monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS",
                           str(tmp_path / "nope.json"))
        p = default_token_provider("explicit")
        assert isinstance(p, StaticTokenProvider) and p() == "explicit"

    def test_authorized_user_adc(self, monkeypatch, tmp_path):
        adc = tmp_path / "adc.json"
        adc.write_text(json.dumps({"type": "authorized_user",
                                   "client_id": "a", "client_secret": "b",
                                   "refresh_token": "c"}))
        monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS", str(adc))
        assert isinstance(default_token_provider(""), AdcUserTokenProvider)

    def test_service_account_key_is_guided_error(self, monkeypatch, tmp_path):
        adc = tmp_path / "sa.json"
        adc.write_text(json.dumps({"type": "service_account"}))
        monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS", str(adc))
        with pytest.raises(AuthError, match="workload identity"):
            default_token_provider("")

    def test_no_credentials_falls_to_metadata(self, monkeypatch, tmp_path):
        monkeypatch.delenv("GOOGLE_APPLICATION_CREDENTIALS", raising=False)
        monkeypatch.setattr(gcp_auth, "_ADC_WELL_KNOWN",
                            str(tmp_path / "missing.json"))
        assert isinstance(default_token_provider(""), MetadataTokenProvider)
