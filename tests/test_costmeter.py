"""CostMeter unit contract (ISSUE 20): per-phase chip-seconds TELESCOPE
exactly to request wall x chips, dollars come from the ONE generations.py
price table, the tenant ledger is cardinality-bounded, idle burn is
paid-minus-attributed, and the snapshot schema is pinned to what the
registry-tier FleetCostLedger (jax-free, so it duplicates the literal)
expects. No jax, no sockets — a fake clock and a real Metrics registry.
"""

from __future__ import annotations

import math

import pytest

from k8s_runpod_kubelet_tpu.fleet import registry as fleet_registry
from k8s_runpod_kubelet_tpu.generations import cost_per_chip_hr
from k8s_runpod_kubelet_tpu.metrics import Metrics
from k8s_runpod_kubelet_tpu.workloads.serving.costmeter import (
    COSTS_SCHEMA_VERSION, MAX_TENANTS, NO_TENANT, OVERFLOW_TENANT, PHASES,
    CostMeter)
from k8s_runpod_kubelet_tpu.workloads.serving.scheduler import Request


def _req(submitted=0.0, dequeued=0.0, prefill_done=0.0, prompt_len=8,
         tenant="", trace_id=""):
    return Request(prompt=list(range(prompt_len)), max_new_tokens=4,
                   rid="r", future=None, submitted_at=submitted,
                   temperature=0.0, dequeued_at=dequeued,
                   prefill_done_at=prefill_done, tenant=tenant,
                   trace_id=trace_id)


def _meter(chips=4, accelerator="v5litepod-8", clock=None, **kw):
    t = [0.0]
    clk = clock if clock is not None else (lambda: t[0])
    m = CostMeter(Metrics(), model="test-model", accelerator=accelerator,
                  chips=chips, clock=clk, **kw)
    return m, t


def test_phases_telescope_to_wall_times_chips():
    m, _ = _meter(chips=4)
    req = _req(submitted=10.0, dequeued=10.5, prefill_done=11.25)
    attr = m.meter_request(req, end_at=13.0, generated_tokens=7,
                           pages_end=3, page_tokens=16)
    cs = attr["chip_seconds"]
    assert cs["queue"] == pytest.approx(0.5 * 4)
    assert cs["prefill"] == pytest.approx(0.75 * 4)
    assert cs["decode"] == pytest.approx(1.75 * 4)
    # the acceptance identity: sum of phases == wall x chips, EXACTLY
    assert math.isclose(sum(cs.values()), (13.0 - 10.0) * 4,
                        rel_tol=0, abs_tol=1e-9)


def test_missing_boundary_stamps_still_telescope():
    # a failed prefill never stamps prefill_done_at (0.0); the monotone
    # clamp must keep the identity instead of producing a negative phase
    m, _ = _meter(chips=2)
    req = _req(submitted=5.0, dequeued=5.5, prefill_done=0.0)
    attr = m.meter_request(req, end_at=6.0, generated_tokens=0,
                           pages_end=0, page_tokens=16)
    cs = attr["chip_seconds"]
    assert all(v >= 0 for v in cs.values())
    assert sum(cs.values()) == pytest.approx((6.0 - 5.0) * 2)
    # never-dequeued either (rejected in queue)
    req = _req(submitted=7.0, dequeued=0.0, prefill_done=0.0)
    attr = m.meter_request(req, end_at=8.0, generated_tokens=0,
                           pages_end=0, page_tokens=16)
    assert sum(attr["chip_seconds"].values()) == pytest.approx(2.0)


def test_dollars_come_from_the_generations_price_table():
    m, _ = _meter(chips=8, accelerator="v5litepod-8")
    req = _req(submitted=0.0, dequeued=0.0, prefill_done=1.0)
    attr = m.meter_request(req, end_at=2.0, generated_tokens=4,
                           pages_end=1, page_tokens=16)
    # 2s wall x 8 chips = 16 chip-seconds at the v5e list price
    want = 16.0 * cost_per_chip_hr("v5litepod-8") / 3600.0
    assert attr["cost_dollars"] == pytest.approx(want)
    assert m.generation == "v5e"


def test_kv_page_seconds_trapezoid():
    m, _ = _meter(chips=1)
    # 32-token prompt / 16-token pages = 2 prefill pages; grew to 6 by end
    req = _req(submitted=0.0, dequeued=0.0, prefill_done=2.0, prompt_len=32)
    attr = m.meter_request(req, end_at=6.0, generated_tokens=64,
                           pages_end=6, page_tokens=16)
    # prefill: 2 pages x 2s; decode: mean (2+6)/2 pages x 4s
    assert attr["kv_page_seconds"] == pytest.approx(2 * 2.0 + 4.0 * 4.0)


def test_tenant_ledger_and_overflow_cap():
    m, _ = _meter(chips=1)
    kw = dict(end_at=1.0, generated_tokens=1, pages_end=1, page_tokens=16)
    m.meter_request(_req(tenant=""), **kw)          # untagged -> "-"
    m.meter_request(_req(tenant="acme"), **kw)
    m.meter_request(_req(tenant="acme"), **kw)
    snap = m.snapshot()
    assert snap["tenants"][NO_TENANT]["requests"] == 1
    assert snap["tenants"]["acme"]["requests"] == 2
    # cardinality bound: past MAX_TENANTS distinct names, new tenants fold
    # into the overflow bucket — spend still counts, just not separably
    for i in range(MAX_TENANTS + 10):
        m.meter_request(_req(tenant=f"tenant-{i:03d}"), **kw)
    snap = m.snapshot()
    assert len(snap["tenants"]) <= MAX_TENANTS + 1  # +1: overflow bucket
    assert snap["tenants"][OVERFLOW_TENANT]["requests"] >= 10
    total_reqs = sum(b["requests"] for b in snap["tenants"].values())
    assert total_reqs == snap["totals"]["requests"] == 3 + MAX_TENANTS + 10


def test_idle_burn_is_paid_minus_attributed():
    m, t = _meter(chips=4)
    t[0] = 10.0  # replica has been up 10s: paid 40 chip-seconds
    snap = m.snapshot()
    assert snap["paid_chip_seconds"] == pytest.approx(40.0)
    assert snap["idle_chip_seconds"] == pytest.approx(40.0)  # no requests
    # a request spanning the whole uptime leaves zero idle burn
    req = _req(submitted=0.0, dequeued=0.0, prefill_done=5.0)
    m.meter_request(req, end_at=10.0, generated_tokens=4,
                    pages_end=1, page_tokens=16)
    snap = m.snapshot()
    assert snap["idle_chip_seconds"] == pytest.approx(0.0)
    gauge = m.metrics.gauges[("tpu_serving_idle_chip_seconds", ())]
    assert gauge == pytest.approx(0.0)


def test_metrics_and_exemplar_emission():
    m, _ = _meter(chips=2)
    req = _req(submitted=0.0, dequeued=0.5, prefill_done=1.0,
               trace_id="ab" * 16)
    m.meter_request(req, end_at=2.0, generated_tokens=4,
                    pages_end=1, page_tokens=16)
    mm = m.metrics
    assert mm.get_counter("tpu_serving_metered_requests") == 1
    for phase in PHASES:
        assert mm.get_counter("tpu_serving_chip_seconds",
                              labels={"phase": phase}) >= 0.0
    total = sum(mm.get_counter("tpu_serving_chip_seconds",
                               labels={"phase": p}) for p in PHASES)
    assert total == pytest.approx(2.0 * 2)
    # the cost histogram carries the request's trace as an exemplar: the
    # expensive bucket on /metrics links to a replayable trace
    text = mm.render()
    assert 'trace_id="' + "ab" * 16 + '"' in text


def test_span_attrs_shape():
    m, _ = _meter(chips=1)
    req = _req(submitted=0.0, dequeued=0.1, prefill_done=0.2, tenant="acme")
    attr = m.meter_request(req, end_at=1.0, generated_tokens=3,
                           pages_end=1, page_tokens=16)
    sa = m.span_attrs(attr)
    assert set(sa) == {"cost_dollars", "chip_seconds_queue",
                       "chip_seconds_prefill", "chip_seconds_decode",
                       "kv_page_seconds", "tenant"}
    assert sa["tenant"] == "acme"
    assert sa["cost_dollars"] >= 0


def test_snapshot_schema_and_registry_literal_pinned():
    m, _ = _meter(chips=2)
    snap = m.snapshot()
    assert snap["schema_version"] == COSTS_SCHEMA_VERSION
    for key in ("model", "pool", "generation", "chips", "price_per_chip_hr",
                "elapsed_s", "paid_chip_seconds", "idle_chip_seconds",
                "handoff_bytes", "totals", "tenants"):
        assert key in snap, key
    # fleet/registry.py is jax-free by contract so it cannot import this
    # module's constant; it duplicates the literal. Pin the two equal so a
    # schema bump cannot land on one side only.
    assert fleet_registry.COSTS_SCHEMA_VERSION == COSTS_SCHEMA_VERSION


def test_handoff_bytes_accumulate():
    m, _ = _meter(chips=1)
    m.note_handoff_bytes(1024)
    m.note_handoff_bytes(4096)
    assert m.snapshot()["handoff_bytes"] == 5120
