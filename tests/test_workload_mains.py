"""Workload entrypoint tests: mnist smoke, train_main tiny, serving HTTP."""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
from k8s_runpod_kubelet_tpu.workloads.serving import ServingConfig, ServingEngine
from k8s_runpod_kubelet_tpu.workloads.serve_main import serve

import pytest as _pytest

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = _pytest.mark.slow


def test_mnist_main_learns(capsys):
    from k8s_runpod_kubelet_tpu.workloads.mnist_train import main
    rc = main(["--steps", "120", "--batch", "64"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(out)
    assert rc == 0
    assert summary["final_acc"] > 0.9
    assert summary["first_step_s"] > 0


def test_train_main_tiny(capsys):
    from k8s_runpod_kubelet_tpu.workloads.train_main import main
    rc = main(["--model", "tiny", "--steps", "2", "--batch", "2",
               "--seq-len", "32", "--tensor", "2", "--seq", "1",
               "--fused-ce-chunks", "4"])  # CLI plumb of the fused loss
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["workload"] == "pretrain"
    assert summary["mesh"]["tensor"] == 2
    assert summary["tokens_per_s_per_chip"] > 0


def test_train_main_profile_trace(capsys, tmp_path):
    """--profile-dir captures a TensorBoard-readable trace of post-warmup
    steps (SURVEY.md §5.1: profiler hooks on workers)."""
    import os
    from k8s_runpod_kubelet_tpu.workloads.train_main import main
    trace_dir = str(tmp_path / "trace")
    rc = main(["--model", "tiny", "--steps", "6", "--batch", "2",
               "--seq-len", "32", "--profile-dir", trace_dir])
    assert rc == 0
    found = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir) for f in fs]
    assert any(f.endswith((".trace.json.gz", ".xplane.pb")) for f in found), found
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["tokens_per_s_per_chip"] > 0


def test_train_main_env_driven_preemption_resume(tmp_path):
    """Checkpoint-aware preemption recovery, workload half (ISSUE 3): the
    kubelet injects TPU_CHECKPOINT_DIR + TPU_RESTART_ATTEMPT on a
    post-preemption relaunch; train_main must pick the dir up WITHOUT a
    --checkpoint-dir flag and resume from the latest orbax step — logging
    the 'resumed from checkpoint step N' marker the kubelet's
    RecoveredFromPreemption event parses. Each life runs in its own
    subprocess, exactly like a real relaunch (and unlike two mains in one
    process, which trips the known XLA-CPU-JIT heap fragility the conftest
    workaround documents)."""
    import os
    import subprocess
    import sys

    def life(attempt: int):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TPU_CHECKPOINT_DIR=str(tmp_path / "ckpt"))
        if attempt:
            env["TPU_RESTART_ATTEMPT"] = str(attempt)
        return subprocess.run(
            [sys.executable, "-m",
             "k8s_runpod_kubelet_tpu.workloads.train_main",
             "--model", "tiny", "--steps", "1", "--batch", "1",
             "--seq-len", "16"],
            env=env, capture_output=True, text=True, timeout=600)

    first = life(0)
    assert first.returncode == 0, first.stderr[-2000:]
    relaunch = life(1)
    assert relaunch.returncode == 0, relaunch.stderr[-2000:]
    assert "resumed from checkpoint step 1" in relaunch.stderr, \
        relaunch.stderr[-2000:]
    assert "attempt 1 resumes at step 1" in relaunch.stderr, \
        relaunch.stderr[-2000:]


def test_train_main_with_data_file(capsys, tmp_path):
    import numpy as np
    from k8s_runpod_kubelet_tpu.workloads.train_main import main
    corpus = tmp_path / "corpus.bin"
    np.random.default_rng(0).integers(
        0, 32000, size=16 * 1024, dtype=np.int32).tofile(corpus)
    rc = main(["--model", "tiny", "--steps", "2", "--batch", "2",
               "--seq-len", "32", "--data", str(corpus)])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["tokens_per_s_per_chip"] > 0


class TestServeHttp:
    @pytest.fixture()
    def server(self):
        cfg = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, mlp_dim=128, max_seq_len=256,
                         dtype=jnp.float32, param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        engine = ServingEngine(cfg, params, ServingConfig(
            slots=2, cache_len=64, max_new_tokens=8, max_prefill_len=32)).start()
        httpd = serve(engine, port=0)
        yield f"http://127.0.0.1:{httpd.server_address[1]}", engine
        httpd.shutdown()
        httpd.server_close()
        engine.stop()

    def test_generate_roundtrip(self, server):
        base, _ = server
        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"tokens": [5, 9], "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.load(urllib.request.urlopen(req, timeout=60))
        assert len(out["tokens"]) == 4
        assert out["latency_s"] > 0

    def test_metrics_expose_queue_depth(self, server):
        base, _ = server
        body = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "tpu_serving_queue_depth" in body

    def test_streaming_ndjson(self, server):
        base, _ = server
        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"tokens": [5, 9], "max_new_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=60)
        assert resp.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(l) for l in resp.read().decode().splitlines() if l]
        streamed = [l["token"] for l in lines if "token" in l]
        final = lines[-1]
        assert streamed == final["tokens"] and len(streamed) == 4

    def test_streaming_callback_engine_level(self, server):
        _, engine = server
        got = []
        fut = engine.submit([3, 7, 1], max_new_tokens=5,
                            on_token=got.append)
        out = fut.result(timeout=60)
        assert got == out["tokens"] and len(got) == 5

    def test_streaming_callback_raise_cancels(self, server):
        _, engine = server

        def boom(tok):
            raise ConnectionError("client gone")

        fut = engine.submit([3, 7, 1], max_new_tokens=50, on_token=boom)
        out = fut.result(timeout=60)
        # cancelled at the first emitted token: far fewer than requested
        assert 1 <= len(out["tokens"]) < 50
        # the engine must still serve subsequent requests
        again = engine.submit([2, 4], max_new_tokens=3).result(timeout=60)
        assert len(again["tokens"]) == 3

    def test_bad_requests_400(self, server):
        base, _ = server
        for payload in [b"not json", b'{"tokens": "nope"}', b'{"tokens": [1.5]}',
                        b"{}"]:
            req = urllib.request.Request(f"{base}/generate", data=payload)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400, payload


def test_serve_main_int8_int4_conflict_is_clean_exit():
    """--int8 --int4 must exit 1 with a log.error, not a ValueError
    traceback from engine construction."""
    from k8s_runpod_kubelet_tpu.workloads import serve_main
    rc = serve_main.main(["--model", "tiny", "--int8", "--int4"])
    assert rc == 1


def test_serve_main_tiny_mla_http_roundtrip():
    """`serve_main --model tiny-mla` serves over HTTP from the LATENT cache
    (VERDICT r4 item 3: MLA selectable from the CLI surface). Built at the
    engine level with the tiny-mla config — the CLI path is covered by the
    choices list + the config table, and the 16B deepseek-v2-lite is too
    big to init in a unit test."""
    from k8s_runpod_kubelet_tpu.models import tiny_mla
    cfg = tiny_mla(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                   n_kv_heads=4, head_dim=16, mla_latent_dim=32,
                   mla_rope_dim=8, mlp_dim=128, max_seq_len=256,
                   dtype=jnp.float32, param_dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = ServingEngine(cfg, params, ServingConfig(
        slots=2, cache_len=64, max_new_tokens=8, max_prefill_len=32)).start()
    assert "c" in engine._cache and "k" not in engine._cache  # latent cache
    httpd = serve(engine, port=0)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"tokens": [5, 9, 77], "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.load(urllib.request.urlopen(req, timeout=60))
        assert len(out["tokens"]) == 4
    finally:
        httpd.shutdown()
        httpd.server_close()
        engine.stop()


def test_serve_main_refuses_lora_with_mla():
    from k8s_runpod_kubelet_tpu.workloads import serve_main
    rc = serve_main.main(["--model", "tiny-mla", "--lora-rank", "4"])
    assert rc == 1
