"""Sparse MoE (Mixtral-family) tests on the 8-device virtual CPU mesh.

The reference has no model code (SURVEY.md §2.4 absence table); expert
parallelism is net-new TPU capability — these tests pin its semantics:
routing math vs a dense all-experts reference, capacity-drop behavior,
end-to-end training with the aux losses, and expert-axis sharding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_runpod_kubelet_tpu.models import (LlamaModel, init_params,
                                           mixtral_8x7b, moe_capacity,
                                           moe_mlp, moe_mlp_dense_reference,
                                           param_logical_axes, tiny_moe)
from k8s_runpod_kubelet_tpu.parallel import (MeshConfig, make_mesh,
                                             param_shardings)
from k8s_runpod_kubelet_tpu.workloads.train import (TrainConfig, Trainer,
                                                    synthetic_batches)

import pytest as _pytest

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = _pytest.mark.slow

# capacity_factor = n_experts ⇒ capacity ≥ any possible expert load, so the
# batched forward never drops tokens and decode/prefill agree with it exactly
# (capacity drops are the one legitimate divergence between the two paths)
MOE_CFG = tiny_moe(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                   n_kv_heads=2, mlp_dim=96, max_seq_len=128,
                   n_experts=4, n_experts_per_tok=2, capacity_factor=4.0,
                   dtype=jnp.float32, param_dtype=jnp.float32)


def _moe_weights(key, e=32, m=48, x=4):
    ks = jax.random.split(key, 4)
    return {
        "router": jax.random.normal(ks[0], (e, x), jnp.float32) * 0.5,
        "we_gate": jax.random.normal(ks[1], (x, e, m), jnp.float32) * 0.05,
        "we_up": jax.random.normal(ks[2], (x, e, m), jnp.float32) * 0.05,
        "we_down": jax.random.normal(ks[3], (x, m, e), jnp.float32) * 0.05,
    }


class TestMoeMlp:
    def test_matches_dense_reference_when_capacity_is_ample(self):
        """With capacity high enough that nothing drops, the sparse dispatch
        path must agree with running every expert densely."""
        w = _moe_weights(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32), jnp.float32)
        kw = dict(n_experts_per_tok=2, activation=jax.nn.silu,
                  dtype=jnp.float32)
        y, aux, z = moe_mlp(h, w["router"], w["we_gate"], w["we_up"],
                            w["we_down"], capacity_factor=4.0, **kw)
        y_ref = moe_mlp_dense_reference(h, w["router"], w["we_gate"],
                                        w["we_up"], w["we_down"],
                                        n_experts_per_tok=2,
                                        activation=jax.nn.silu,
                                        dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
        assert float(aux) > 0 and float(z) >= 0

    def test_capacity_drop_zeroes_overflow_not_crash(self):
        """A tiny capacity factor forces drops: output stays finite and
        dropped tokens contribute zero (shrinking the output norm)."""
        w = _moe_weights(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32), jnp.float32)
        kw = dict(n_experts_per_tok=2, activation=jax.nn.silu,
                  dtype=jnp.float32)
        y_full, _, _ = moe_mlp(h, w["router"], w["we_gate"], w["we_up"],
                               w["we_down"], capacity_factor=8.0, **kw)
        y_tight, _, _ = moe_mlp(h, w["router"], w["we_gate"], w["we_up"],
                                w["we_down"], capacity_factor=0.25, **kw)
        assert bool(jnp.all(jnp.isfinite(y_tight)))
        assert float(jnp.linalg.norm(y_tight)) < float(jnp.linalg.norm(y_full))

    def test_capacity_formula(self):
        assert moe_capacity(1024, 8, 2, 1.25) == 320
        assert moe_capacity(2, 8, 2, 1.0) == 4  # floor

    def test_uniform_router_aux_loss_is_one(self):
        """A perfectly uniform router scores aux == 1.0 (the Switch norm)."""
        from k8s_runpod_kubelet_tpu.models.moe import load_balance_loss
        g, x, k = 64, 4, 2
        probs = jnp.full((g, x), 1.0 / x)
        # assignments round-robin so counts are exactly uniform
        idx = jnp.stack([jnp.arange(g) % x, (jnp.arange(g) + 1) % x], axis=1)
        aux = load_balance_loss(probs, idx, x, k)
        assert float(aux) == pytest.approx(1.0, rel=1e-6)

    def test_gradients_flow_to_router_and_experts(self):
        w = _moe_weights(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 32), jnp.float32)

        def loss(w):
            y, aux, z = moe_mlp(h, w["router"], w["we_gate"], w["we_up"],
                                w["we_down"], n_experts_per_tok=2,
                                capacity_factor=2.0, activation=jax.nn.silu,
                                dtype=jnp.float32)
            return jnp.sum(y ** 2) + 0.01 * aux + 0.001 * z

        grads = jax.grad(loss)(w)
        for name, g in grads.items():
            assert bool(jnp.any(g != 0)), f"zero grad for {name}"
            assert bool(jnp.all(jnp.isfinite(g))), f"non-finite grad for {name}"


class TestMoeModel:
    def test_forward_shapes_and_aux(self):
        model = LlamaModel(MOE_CFG)
        params = init_params(MOE_CFG, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits, aux = model.forward(params, tokens, with_aux=True)
        assert logits.shape == (2, 16, 128)
        assert bool(jnp.all(jnp.isfinite(logits)))
        assert float(aux) > 0  # load-balance + z losses are live

    def test_causality(self):
        model = LlamaModel(MOE_CFG)
        params = init_params(MOE_CFG, jax.random.PRNGKey(0))
        t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        t2 = t1.at[0, 6].set(99)
        l1 = model.forward(params, t1)
        l2 = model.forward(params, t2)
        np.testing.assert_allclose(np.asarray(l1[0, :6]), np.asarray(l2[0, :6]),
                                   rtol=1e-4, atol=1e-4)

    def test_decode_matches_forward(self):
        """MoE prefill + decode must reproduce the full forward (routing is
        per-token, so decode sees identical expert choices)."""
        cfg = MOE_CFG
        model = LlamaModel(cfg)
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 128)
        full_logits = model.forward(params, tokens)
        cache = model.init_cache(batch=2, max_len=32)
        last, cache = model.prefill(params, tokens[:, :8], cache)
        np.testing.assert_allclose(np.asarray(last),
                                   np.asarray(full_logits[:, 7]),
                                   rtol=2e-3, atol=2e-3)
        for i in range(8, 12):
            logits, cache = model.decode_step(params, tokens[:, i], cache)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full_logits[:, i]),
                                       rtol=2e-3, atol=2e-3)

    def test_mixtral_param_count(self):
        assert mixtral_8x7b().param_count == pytest.approx(46.7e9, rel=0.05)


class TestMoeSharded:
    def test_train_step_on_expert_parallel_mesh(self):
        """Full training step with experts sharded over the expert axis and
        mlp over tensor: loss decreases, expert weights actually sharded."""
        mesh = make_mesh(MeshConfig(data=-1, expert=2, tensor=2))
        tc = TrainConfig(batch_size=4, seq_len=32, steps=4, warmup_steps=1,
                         learning_rate=1e-3)
        trainer = Trainer(MOE_CFG, tc, mesh)
        shardings = param_shardings(mesh, param_logical_axes(MOE_CFG))
        we_spec = shardings["layers"]["we_gate"].spec
        assert "expert" in str(we_spec) and "tensor" in str(we_spec)
        losses = []
        batches = synthetic_batches(MOE_CFG, tc, mesh)
        for _ in range(4):
            batch = next(batches)
            trainer.params, trainer.opt_state, m = trainer.step_fn(
                trainer.params, trainer.opt_state, batch)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestQuantizedExperts:
    def test_sparse_matches_dense_reference_int8(self):
        """The {q8, scale} expert path through BOTH the sparse dispatch and
        the dense reference (including the dense path's (x, m)-aligned
        scale broadcast) — same ample-capacity parity as the fp test."""
        from k8s_runpod_kubelet_tpu.models.quant import _quantize_leaf
        w = _moe_weights(jax.random.PRNGKey(0))
        qw = {name: (_quantize_leaf(np.asarray(w[name]))
                     if name.startswith("we_") else w[name])
              for name in w}
        h = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32), jnp.float32)
        kw = dict(n_experts_per_tok=2, activation=jax.nn.silu,
                  dtype=jnp.float32)
        y, _, _ = moe_mlp(h, qw["router"], qw["we_gate"], qw["we_up"],
                          qw["we_down"], capacity_factor=4.0, **kw)
        y_ref = moe_mlp_dense_reference(h, qw["router"], qw["we_gate"],
                                        qw["we_up"], qw["we_down"], **kw)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
        # and both stay close to the full-precision output (int8 tolerance)
        y_fp, _, _ = moe_mlp(h, w["router"], w["we_gate"], w["we_up"],
                             w["we_down"], capacity_factor=4.0, **kw)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_fp),
                                   rtol=0.1, atol=0.05)

    def test_int4_experts_accuracy_parity(self):
        """Group-wise int4 expert weights through the per-expert unpack
        path (moe._expert_matmul -> ops.int4_matmul.int4_expert_matmul):
        the ACCURACY-PARITY threshold test that replaced the old loud
        'expert weights are int8-only' error. 4-bit resolution is lossy
        by construction, so the pin is a relative-Frobenius-error budget
        against the full-precision output, not exactness. Budget
        calibration: absmax int4 on gaussian weights has a ~0.4sigma
        quantization step -> ~12% per-weight error -> ~0.2 relative
        output error through the three matmuls (measured 0.19-0.20
        across geometries); 0.25 pins that with margin while catching
        any packing/scale-alignment regression (which lands >0.5). int8
        must sit an order of magnitude inside it (the ladder ordering)."""
        from k8s_runpod_kubelet_tpu.models.quant import (_quantize_leaf,
                                                         _quantize_leaf_int4)
        w = _moe_weights(jax.random.PRNGKey(0), e=64, m=128)
        h = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 64), jnp.float32)
        kw = dict(n_experts_per_tok=2, capacity_factor=4.0,
                  activation=jax.nn.silu, dtype=jnp.float32)

        def quantized(leaf_fn):
            q = {name: (jax.tree_util.tree_map(jnp.asarray,
                                               leaf_fn(np.asarray(w[name])))
                        if name.startswith("we_") else w[name])
                 for name in w}
            y, _, _ = moe_mlp(h, q["router"], q["we_gate"], q["we_up"],
                              q["we_down"], **kw)
            return np.asarray(y)

        y_fp, _, _ = moe_mlp(h, w["router"], w["we_gate"], w["we_up"],
                             w["we_down"], **kw)
        y_fp = np.asarray(y_fp)

        def rel_err(y):
            return (np.linalg.norm(y - y_fp)
                    / max(np.linalg.norm(y_fp), 1e-9))

        err4 = rel_err(quantized(_quantize_leaf_int4))
        err8 = rel_err(quantized(_quantize_leaf))
        assert err4 < 0.25, f"int4 expert rel error {err4:.4f} over budget"
        assert err8 < err4 / 10, (err8, err4)

    def test_int4_experts_dense_reference_rejects(self):
        """The dense reference does not cover int4 leaves — it must say so
        loudly instead of KeyError'ing into a misleading trace."""
        from k8s_runpod_kubelet_tpu.models.quant import _quantize_leaf_int4
        w = _moe_weights(jax.random.PRNGKey(0), e=64, m=128)
        q4 = jax.tree_util.tree_map(
            jnp.asarray, _quantize_leaf_int4(np.asarray(w["we_gate"])))
        h = jax.random.normal(jax.random.PRNGKey(1), (1, 4, 64), jnp.float32)
        with pytest.raises(ValueError, match="dense MoE reference"):
            moe_mlp_dense_reference(h, w["router"], q4, q4, q4,
                                    n_experts_per_tok=2,
                                    activation=jax.nn.silu,
                                    dtype=jnp.float32)

    def test_expert_parallel_shard_map_matches_unsharded(self):
        """The serving EP island (_expert_ffn_sharded under a mesh with an
        expert axis) computes the same MoE output as the meshless einsum
        path — per-expert math is untouched by the partitioning."""
        w = _moe_weights(jax.random.PRNGKey(0), e=64, m=128)
        h = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 64), jnp.float32)
        kw = dict(n_experts_per_tok=2, capacity_factor=4.0,
                  activation=jax.nn.silu, dtype=jnp.float32)
        y_ref, _, _ = moe_mlp(h, w["router"], w["we_gate"], w["we_up"],
                              w["we_down"], **kw)
        mesh = make_mesh(MeshConfig(data=1, expert=2, tensor=2),
                         jax.devices()[:4])
        y_ep = jax.jit(lambda h: moe_mlp(
            h, w["router"], w["we_gate"], w["we_up"], w["we_down"],
            mesh=mesh, **kw)[0])(h)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
