"""Streaming `kubectl exec` over the WebSocket channel protocol, e2e against
the kubelet API server with the SSH-path fakes (docker-lite worker host).

The reference stubs exec entirely (main.go:220-225, kubelet.go:2027-2066);
this covers the net-new interactive path: stdin/stdout bridging, exit-status
propagation on the error channel, auth gating, and bad-request handling.
"""

import base64
import json
import os
import socket
import struct

import pytest

from k8s_runpod_kubelet_tpu.node import KubeletApiServer
from k8s_runpod_kubelet_tpu.node import ws
from k8s_runpod_kubelet_tpu.provider.annotations import Annotations as A
from k8s_runpod_kubelet_tpu.kube import objects as ko

from harness import make_ssh_harness, make_pod


# -- minimal RFC6455 client (client->server frames masked, per spec) ----------

class _WsReader:
    """File-like over the socket that first drains bytes received past the
    handshake boundary — a fast-exiting exec can deliver its first frames in
    the same recv() chunk as the 101 headers."""

    def __init__(self, sock, leftover: bytes):
        self._buf = leftover
        self._f = sock.makefile("rb")

    def read(self, n: int) -> bytes:
        if self._buf:
            out, self._buf = self._buf[:n], self._buf[n:]
            return out
        return self._f.read(n)


def ws_connect(port, path, token=None):
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    key = base64.b64encode(os.urandom(16)).decode()
    req = (f"GET {path} HTTP/1.1\r\nHost: 127.0.0.1\r\n"
           "Upgrade: websocket\r\nConnection: Upgrade\r\n"
           f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n"
           "Sec-WebSocket-Protocol: v4.channel.k8s.io\r\n")
    if token:
        req += f"Authorization: Bearer {token}\r\n"
    req += "\r\n"
    sock.sendall(req.encode())
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            break
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    return sock, head.decode(errors="replace"), _WsReader(sock, rest)


def send_channel(sock, channel, data: bytes):
    payload = bytes([channel]) + data
    mask = os.urandom(4)
    n = len(payload)
    header = bytes([0x80 | ws.BINARY])
    if n < 126:
        header += bytes([0x80 | n])
    else:
        header += bytes([0x80 | 126]) + struct.pack(">H", n)
    masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    sock.sendall(header + mask + masked)


def read_until_close(f):
    """Returns (stdout_bytes, error_channel_payloads)."""
    out, errs = b"", []
    while True:
        opcode, payload = ws.read_frame(f)
        if opcode == ws.CLOSE:
            return out, errs
        if opcode != ws.BINARY or not payload:
            continue
        channel, data = payload[0], payload[1:]
        if channel == ws.STDOUT:
            out += data
        elif channel == ws.ERROR:
            errs.append(json.loads(data))


@pytest.fixture()
def rig():
    h = make_ssh_harness()
    pod = h.kube.create_pod(make_pod(chips=16))
    h.provider.create_pod(pod)
    h.provider.update_all_pod_statuses()  # launches the workload containers
    srv = KubeletApiServer(h.provider, address="127.0.0.1", port=0).start()
    yield h, srv
    srv.stop()
    h.close()


def exec_path(cmd_args, worker=0):
    from urllib.parse import quote
    q = "&".join(f"command={quote(c)}" for c in cmd_args)
    return f"/exec/default/train/main?{q}&worker={worker}&stdout=true&stdin=true"


class TestExecWebSocket:
    def test_stdin_stdout_roundtrip_and_success_status(self, rig):
        _, srv = rig
        sock, head, f = ws_connect(srv.port, exec_path(
            ["sh", "-c", "read line; echo got:$line"]))
        assert "101" in head and "v4.channel.k8s.io" in head
        send_channel(sock, ws.STDIN, b"hello\n")
        out, errs = read_until_close(f)
        sock.close()
        assert b"got:hello" in out
        assert errs and errs[-1]["status"] == "Success"

    def test_nonzero_exit_reported_on_error_channel(self, rig):
        _, srv = rig
        sock, head, f = ws_connect(srv.port, exec_path(["sh", "-c", "exit 3"]))
        assert "101" in head
        _, errs = read_until_close(f)
        sock.close()
        st = errs[-1]
        assert st["status"] == "Failure" and st["reason"] == "NonZeroExitCode"
        assert st["details"]["causes"][0]["message"] == "3"

    def test_streaming_is_incremental_not_buffered(self, rig):
        """Output must arrive as produced (streamed), not after exit."""
        _, srv = rig
        sock, _, f = ws_connect(srv.port, exec_path(
            ["sh", "-c", "echo first; read line; echo second:$line"]))
        opcode, payload = ws.read_frame(f)
        assert payload[0] == ws.STDOUT and b"first" in payload[1:]
        # the process is still alive waiting on stdin — now feed it
        send_channel(sock, ws.STDIN, b"go\n")
        out = b""
        while b"second:go" not in out:
            opcode, payload = ws.read_frame(f)
            if opcode == ws.BINARY and payload and payload[0] == ws.STDOUT:
                out += payload[1:]
        sock.close()

    def test_exec_requires_auth_when_token_set(self, rig):
        h, _ = rig
        srv2 = KubeletApiServer(h.provider, address="127.0.0.1", port=0,
                                auth_token="s3cret").start()
        try:
            sock, head, _ = ws_connect(srv2.port, exec_path(["true"]))
            assert head.startswith("HTTP/1.1 401")
            sock.close()
            sock, head, f = ws_connect(srv2.port, exec_path(
                ["sh", "-c", "exit 0"]), token="s3cret")
            assert "101" in head
            _, errs = read_until_close(f)
            assert errs[-1]["status"] == "Success"
            sock.close()
        finally:
            srv2.stop()

    def test_plain_get_is_400_and_unknown_pod_404(self, rig):
        import urllib.error
        import urllib.request
        _, srv = rig
        base = f"http://127.0.0.1:{srv.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/exec/default/train/main?command=ls",
                                   timeout=5)
        assert ei.value.code == 400  # no websocket upgrade
        sock, head, _ = ws_connect(srv.port,
                                   "/exec/default/nope/main?command=ls")
        assert head.startswith("HTTP/1.1 404")
        sock.close()


class TestExecChannelFixes:
    def test_stderr_arrives_on_its_own_channel(self, rig):
        """ssh diagnostics / command stderr must not corrupt binary stdout:
        the channel protocol has a dedicated STDERR channel (2)."""
        _, srv = rig
        sock, head, f = ws_connect(srv.port, exec_path(
            ["sh", "-c", "echo out; echo err >&2"]))
        assert "101" in head
        out, err = b"", b""
        while True:
            opcode, payload = ws.read_frame(f)
            if opcode == ws.CLOSE:
                break
            if opcode != ws.BINARY or not payload:
                continue
            if payload[0] == ws.STDOUT:
                out += payload[1:]
            elif payload[0] == ws.STDERR:
                err += payload[1:]
        sock.close()
        assert b"out" in out and b"err" not in out
        assert b"err" in err

    def test_negative_worker_is_rejected(self, rig):
        """worker=-1 must error, not silently exec on the last worker."""
        _, srv = rig
        sock, head, _ = ws_connect(srv.port, exec_path(["true"], worker=-1))
        assert head.startswith("HTTP/1.1 5") or head.startswith("HTTP/1.1 4")
        sock.close()

    def test_unsupported_subprotocol_rejected_before_exec(self, rig):
        """A client offering only an unknown protocol is rejected with 400
        BEFORE the command is spawned (exec has side effects on the worker)."""
        h, srv = rig
        calls_before = len(h.transport.calls)
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        key = base64.b64encode(os.urandom(16)).decode()
        req = ("GET " + exec_path(["true"]) + " HTTP/1.1\r\nHost: x\r\n"
               "Upgrade: websocket\r\nConnection: Upgrade\r\n"
               f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n"
               "Sec-WebSocket-Protocol: v9.future.k8s.io\r\n\r\n")
        sock.sendall(req.encode())
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(4096)
            if not chunk:
                break
            buf += chunk
        head = buf.split(b"\r\n\r\n")[0].decode()
        assert head.startswith("HTTP/1.1 400")
        assert "v9.future.k8s.io" not in head
        assert len(h.transport.calls) == calls_before  # nothing ran
        sock.close()

    def test_keepalive_survives_unauthorized_post_with_body(self):
        """Under HTTP/1.1 an early-401 POST with an unread body must not
        desync the connection for the next request (connection closes)."""
        import http.client
        h = make_ssh_harness()
        try:
            srv = KubeletApiServer(h.provider, address="127.0.0.1", port=0,
                                   auth_token="tok").start()
            try:
                conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                  timeout=5)
                conn.request("POST", "/run/default/p/c",
                             body=json.dumps({"cmd": ["ls"]}),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 401
                resp.read()
                # server signalled close — a fresh connection must work fine
                conn2 = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                   timeout=5)
                conn2.request("GET", "/healthz")
                assert conn2.getresponse().status == 200
                conn2.close()
                conn.close()
            finally:
                srv.stop()
        finally:
            h.close()


class TestRemoteReapDecision:
    """Exit 255 is ambiguous (r3 advisor): ssh's OWN transport failures
    exit 255, but so can the remote command itself. Only the former —
    identified by ssh's stderr complaint — may fire the remote kill."""

    def test_transport_failure_255_reaps(self):
        from k8s_runpod_kubelet_tpu.node.api_server import _should_reap_remote
        for msg in (b"client_loop: send disconnect: Broken pipe",
                    b"Connection to 10.0.0.1 closed by remote host.",
                    b"Connection closed by 10.0.0.1 port 22",  # kex/auth form
                    b"ssh: connect to host 10.0.0.1 port 22: "
                    b"Connection timed out",
                    b"Timeout, server 10.0.0.1 not responding",
                    b"kex_exchange_identification: read: "
                    b"Connection reset by peer"):
            assert _should_reap_remote(255, msg), msg

    def test_remote_commands_own_255_is_normal_completion(self):
        from k8s_runpod_kubelet_tpu.node.api_server import _should_reap_remote
        # remote tool printed its own diagnostics and exited 255: no reap
        assert not _should_reap_remote(255, b"fatal: retry budget exhausted")
        assert not _should_reap_remote(255, b"")
        # generic fragments shared with common tool output are deliberately
        # NOT signatures (a nested tool timing out must not TERM a recycled
        # pid); ssh's unprefixed mid-session reset line rides this tradeoff
        assert not _should_reap_remote(255, b"curl: (28) Connection timed "
                                            b"out after 5000 ms")
        assert not _should_reap_remote(255,
                                       b"Connection reset by 10.0.0.1 port 22")

    def test_abort_and_signal_kill_always_reap(self):
        from k8s_runpod_kubelet_tpu.node.api_server import _should_reap_remote
        assert _should_reap_remote(None, b"")     # client abort, ssh alive
        assert _should_reap_remote(-15, b"")      # local ssh TERMed
        assert not _should_reap_remote(0, b"")    # clean exit
        assert not _should_reap_remote(1, b"")    # normal failure
