"""int8 KV cache: per-(position, kv-head) scales, decode-path accuracy.

Decode reads the whole KV cache every step (HBM-bandwidth-bound), so int8
halves the traffic and doubles slot capacity. These tests pin: logits stay
close to the f32-cache path, greedy generations match on the tiny model,
and the quantized cache composes with the ring cache and speculation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_runpod_kubelet_tpu.models import LlamaModel, init_params, tiny_llama
from k8s_runpod_kubelet_tpu.workloads.serving import ServingConfig, ServingEngine

import pytest as _pytest

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = _pytest.mark.slow

CFG = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, mlp_dim=128, max_seq_len=256,
                 dtype=jnp.float32, param_dtype=jnp.float32)
WCFG = tiny_llama(name="tiny-window", vocab_size=128, embed_dim=64,
                  n_layers=2, n_heads=4, n_kv_heads=2, mlp_dim=128,
                  max_seq_len=256, sliding_window=8,
                  dtype=jnp.float32, param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


class TestKvQuantModel:
    def test_cache_dtypes_and_shapes(self, params):
        model = LlamaModel(CFG)
        cache = model.init_cache(2, 32, quantize=True)
        assert cache["k"].dtype == jnp.int8
        assert cache["k_scale"].shape == (2, 2, 32, 2)
        assert cache["k_scale"].dtype == jnp.float32

    def test_decode_close_to_f32_cache(self, params):
        """Logits through the int8 cache track the f32-cache logits."""
        model = LlamaModel(CFG)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, 128)
        qc = model.init_cache(2, 32, quantize=True)
        fc = model.init_cache(2, 32)
        lq, qc = model.prefill(params, toks[:, :8], qc)
        lf, fc = model.prefill(params, toks[:, :8], fc)
        np.testing.assert_allclose(np.asarray(lq), np.asarray(lf),
                                   rtol=0.05, atol=0.05)
        for i in range(8, 24):
            oq, qc = model.decode_step(params, toks[:, i], qc)
            of, fc = model.decode_step(params, toks[:, i], fc)
            np.testing.assert_allclose(np.asarray(oq), np.asarray(of),
                                       rtol=0.08, atol=0.08,
                                       err_msg=f"position {i}")

    def test_greedy_generation_matches_f32_cache(self, params):
        """On the pinned tiny model, int8-KV greedy decode picks the same
        tokens as the f32 cache (the perturbation is far below the argmax
        margins of a random-init model)."""
        model = LlamaModel(CFG)
        prompt = jnp.asarray([[5, 17, 99, 3, 42, 7]], jnp.int32)
        outs = {}
        for name, quant in (("f32", False), ("int8", True)):
            cache = model.init_cache(1, 64, quantize=quant)
            logits, cache = model.prefill(params, prompt, cache)
            toks = [int(jnp.argmax(logits[0]))]
            for _ in range(20):
                logits, cache = model.decode_step(
                    params, jnp.asarray([toks[-1]], jnp.int32), cache)
                toks.append(int(jnp.argmax(logits[0])))
            outs[name] = toks
        assert outs["f32"] == outs["int8"]

    def test_composes_with_ring(self):
        wparams = init_params(WCFG, jax.random.PRNGKey(0))
        model = LlamaModel(WCFG)
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, 30), 0, 128)
        rq = model.init_ring_cache(1, 16, quantize=True)
        assert rq["k"].dtype == jnp.int8 and "abs_pos" in rq
        full = model.forward(wparams, toks)
        _, rq = model.prefill(wparams, toks[:, :6], rq)
        for i in range(6, 30):
            logits, rq = model.decode_step(wparams, toks[:, i], rq)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, i]),
                                       rtol=0.08, atol=0.08,
                                       err_msg=f"position {i}")

    def test_inactive_slots_untouched(self, params):
        model = LlamaModel(CFG)
        cache = model.init_cache(2, 32, quantize=True)
        _, cache = model.prefill(params, jnp.asarray([[1, 2, 3], [4, 5, 6]],
                                                     jnp.int32), cache)
        before_k = np.asarray(cache["k"][:, 1])
        before_s = np.asarray(cache["k_scale"][:, 1])
        active = jnp.asarray([True, False])
        _, cache = model.decode_step(params, jnp.asarray([7, 8], jnp.int32),
                                     cache, active)
        np.testing.assert_array_equal(np.asarray(cache["k"][:, 1]), before_k)
        np.testing.assert_array_equal(np.asarray(cache["k_scale"][:, 1]),
                                      before_s)
        assert int(cache["index"][1]) == 3  # frozen


class TestKvQuantEngine:
    def test_engine_greedy_matches_unquantized(self, params):
        sc_q = ServingConfig(slots=2, max_prefill_len=16, cache_len=64,
                             max_new_tokens=16, quantize_kv_int8=True)
        sc_f = ServingConfig(slots=2, max_prefill_len=16, cache_len=64,
                             max_new_tokens=16)
        e_q = ServingEngine(CFG, params, sc_q).start()
        e_f = ServingEngine(CFG, params, sc_f).start()
        try:
            if e_q._paged_loop:
                # ISSUE 10: int8-KV engines run the paged decode loop —
                # the slots' int8 storage IS the shared arena (no
                # contiguous batch cache exists), scales paged alongside
                assert e_q._kv_store.arena["k"].dtype == jnp.int8
                assert "k_scale" in e_q._kv_store.arena
            else:
                assert e_q._cache["k"].dtype == jnp.int8
            prompts = [[(11 * j + i) % 128 for j in range(2 + 3 * i)]
                       for i in range(4)]
            for p in prompts:
                q = e_q.submit(p, max_new_tokens=16).result(timeout=60)
                f = e_f.submit(p, max_new_tokens=16).result(timeout=60)
                assert q["tokens"] == f["tokens"]
        finally:
            e_q.stop()
            e_f.stop()

    def test_speculative_on_quantized_cache(self, params):
        sc = ServingConfig(slots=2, max_prefill_len=16, cache_len=64,
                           max_new_tokens=16, quantize_kv_int8=True,
                           speculate_k=3)
        e = ServingEngine(CFG, params, sc).start()
        try:
            prompt = [3, 1, 4, 1, 5, 9, 2, 6, 3, 1, 4, 1, 5]
            out = e.submit(prompt, max_new_tokens=16).result(timeout=60)
            assert len(out["tokens"]) == 16
        finally:
            e.stop()
