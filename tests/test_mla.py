"""Multi-head Latent Attention (ops/mla.py): absorbed-decode vs
full-sequence parity, direct-vs-absorbed equivalence, latent-cache
compression arithmetic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_runpod_kubelet_tpu.ops.mla import (init_mla_cache, init_mla_params,
                                            kv_bytes_per_token,
                                            mla_attention, mla_decode_step)
from k8s_runpod_kubelet_tpu.ops.rope import rope_frequencies

pytestmark = pytest.mark.slow

E, H, DH, DR, R = 64, 4, 16, 8, 24
S, B = 12, 2


@pytest.fixture(scope="module")
def setup():
    params = init_mla_params(jax.random.PRNGKey(0), embed_dim=E, n_heads=H,
                             head_dim=DH, latent_dim=R, rope_dim=DR)
    cos, sin = rope_frequencies(DR, max_seq_len=64, theta=10000.0)
    h = jax.random.normal(jax.random.PRNGKey(1), (B, S, E), jnp.float32)
    return params, cos, sin, h


class TestMLA:
    def test_decode_matches_full_sequence(self, setup):
        """Token-by-token absorbed decode reproduces the causal
        full-sequence outputs at every position."""
        params, cos, sin, h = setup
        full, _ = mla_attention(h, params, cos, sin)
        cache = init_mla_cache(B, 32, latent_dim=R, rope_dim=DR)
        step = jax.jit(mla_decode_step)
        for t in range(S):
            out, cache = step(h[:, t:t + 1], params, cache, cos, sin)
            np.testing.assert_allclose(np.asarray(out[:, 0]),
                                       np.asarray(full[:, t]),
                                       rtol=2e-4, atol=2e-4)
        assert [int(x) for x in cache["index"]] == [S] * B

    def test_absorbed_equals_direct(self, setup):
        """The absorbed form (attention in latent space) must equal the
        direct form (materialize per-head K/V from the same cache)."""
        params, cos, sin, h = setup
        # prefill the cache via the full pass
        _, kv = mla_attention(h, params, cos, sin)
        cache = init_mla_cache(B, 32, latent_dim=R, rope_dim=DR)
        cache["c"] = cache["c"].at[:, :S].set(kv["c"])
        cache["kr"] = cache["kr"].at[:, :S].set(kv["kr"])
        cache["index"] = jnp.full((B,), S, jnp.int32)
        h1 = jax.random.normal(jax.random.PRNGKey(2), (B, 1, E), jnp.float32)
        absorbed, cache2 = mla_decode_step(h1, params, cache, cos, sin)

        # direct reference: materialize k/v for live positions and attend
        from k8s_runpod_kubelet_tpu.ops.mla import _project
        pos = jnp.full((B, 1), S, jnp.int32)
        q_nope, q_rope, c1, kr1 = _project(h1, params, cos, sin, pos)
        c = cache["c"].at[:, S].set(c1[:, 0])
        kr = cache["kr"].at[:, S].set(kr1[:, 0])
        k_nope = jnp.einsum("blr,rhd->blhd", c, params["w_uk"])
        v = jnp.einsum("blr,rhd->blhd", c, params["w_uv"])
        scale = (DH + DR) ** -0.5
        scores = (jnp.einsum("bohd,blhd->bhol", q_nope, k_nope)
                  + jnp.einsum("bohd,bld->bhol", q_rope, kr)) * scale
        live = (jnp.arange(c.shape[1]) <= S)[None, None, None, :]
        scores = jnp.where(live, scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhol,blhd->bohd", p, v).reshape(B, 1, H * DH)
        direct = o @ params["w_o"]
        np.testing.assert_allclose(np.asarray(absorbed), np.asarray(direct),
                                   rtol=2e-5, atol=2e-5)

    def test_cache_compression_claim(self):
        """DeepSeek-V2 geometry: 128 heads x 128 dh vs latent 512 + rope 64
        = 10.2/1 fewer KV bytes per token."""
        std, mla = kv_bytes_per_token(n_heads=128, head_dim=128,
                                      latent_dim=512, rope_dim=64)
        assert std / mla == pytest.approx(32768 / 576)  # 56.9x
        # and this test file's tiny geometry still compresses
        std, mla = kv_bytes_per_token(n_heads=H, head_dim=DH,
                                      latent_dim=R, rope_dim=DR)
        assert mla < std

    def test_rope_positions_actually_used(self, setup):
        """_project must rotate by the CALLER's positions: the same input
        at position 0 vs position 5 produces different q_rope/kr (a
        hardcoded-zero-position bug would make these equal)."""
        from k8s_runpod_kubelet_tpu.ops.mla import _project
        params, cos, sin, h = setup
        p0 = jnp.zeros((B, 1), jnp.int32)
        p5 = jnp.full((B, 1), 5, jnp.int32)
        _, qr0, _, kr0 = _project(h[:, :1], params, cos, sin, p0)
        _, qr5, _, kr5 = _project(h[:, :1], params, cos, sin, p5)
        assert not np.allclose(np.asarray(qr0), np.asarray(qr5))
        assert not np.allclose(np.asarray(kr0), np.asarray(kr5))

    def test_per_row_index_rows_advance_independently(self, setup):
        """Engine-contract cache: rows at DIFFERENT lengths decode
        correctly in one batch — row 0 continuing a 4-token history must
        match what it would produce in a batch of its own."""
        params, cos, sin, h = setup
        # batch run: row 0 has 4 committed tokens, row 1 has 7
        cache = init_mla_cache(B, 32, latent_dim=R, rope_dim=DR)
        lens = [4, 7]
        for t in range(max(lens)):
            live_rows = [t < n for n in lens]
            out, cache = mla_decode_step(h[:, t:t + 1], params, cache,
                                         cos, sin)
            # freeze rows past their length (caller-side active handling)
            cache["index"] = jnp.asarray(
                [min(int(i), n) for i, n in zip(cache["index"], lens)],
                jnp.int32)
        mixed_out, _ = mla_decode_step(h[:, 10:11], params, cache, cos, sin)

        # solo run of row 0's exact history
        solo = init_mla_cache(1, 32, latent_dim=R, rope_dim=DR)
        for t in range(lens[0]):
            _, solo = mla_decode_step(h[:1, t:t + 1], params, solo, cos, sin)
        solo_out, _ = mla_decode_step(h[:1, 10:11], params, solo, cos, sin)
        np.testing.assert_allclose(np.asarray(mixed_out[0]),
                                   np.asarray(solo_out[0]),
                                   rtol=2e-5, atol=2e-5)


# -- engine integration (VERDICT r4 item 3: MLA consumed by a model config
# -- and the serving engine, not just an exported op) -------------------------

from k8s_runpod_kubelet_tpu.models import LlamaModel, init_params, tiny_mla
from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                      ServingEngine)

MCFG = tiny_mla(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                n_kv_heads=4, head_dim=16, mla_latent_dim=32, mla_rope_dim=8,
                mlp_dim=128, max_seq_len=256,
                dtype=jnp.float32, param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def mla_params():
    return init_params(MCFG, jax.random.PRNGKey(0))


def _greedy_reference(params, prompt, n_new):
    model = LlamaModel(MCFG)
    tokens = list(prompt)
    for _ in range(n_new):
        logits = model.forward(params, jnp.asarray([tokens], jnp.int32))
        tokens.append(int(jnp.argmax(logits[0, -1])))
    return tokens[len(prompt):]


class TestMlaModel:
    def test_prefill_decode_parity(self, mla_params):
        """Latent-cache prefill + absorbed decode == full forward, greedily."""
        model = LlamaModel(MCFG)
        prompt = [5, 17, 99, 3, 42]
        ref = _greedy_reference(mla_params, prompt, 6)
        cache = model.init_cache(1, 64)
        assert "c" in cache and "k" not in cache  # latent sections, no K/V
        logits, cache = model.prefill(
            mla_params, jnp.asarray([prompt], jnp.int32), cache)
        out = []
        tok = jnp.argmax(logits, -1)
        for _ in range(6):
            out.append(int(tok[0]))
            logits, cache = model.decode_step(mla_params, tok, cache)
            tok = jnp.argmax(logits, -1)
        assert out == ref

    def test_verify_step_matches_sequential_decode(self, mla_params):
        """K-token absorbed verify == K sequential decode_steps."""
        model = LlamaModel(MCFG)
        prompt = [7, 3, 11, 19]
        cache = model.init_cache(1, 64)
        logits, cache = model.prefill(
            mla_params, jnp.asarray([prompt], jnp.int32), cache)
        drafts = [int(jnp.argmax(logits, -1)[0]), 23, 56]   # token0 + 2 draft
        seq_cache = jax.tree_util.tree_map(lambda x: x, cache)
        seq_logits = []
        for t in drafts:
            lg, seq_cache = model.decode_step(
                mla_params, jnp.asarray([t], jnp.int32), seq_cache)
            seq_logits.append(np.asarray(lg[0]))
        ver_logits, _ = model.verify_step(
            mla_params, jnp.asarray([drafts], jnp.int32), cache)
        for j in range(len(drafts)):
            np.testing.assert_allclose(np.asarray(ver_logits[0, j]),
                                       seq_logits[j], rtol=2e-4, atol=2e-4)

    def test_inactive_slots_frozen(self, mla_params):
        model = LlamaModel(MCFG)
        cache = model.init_cache(2, 64)
        logits, cache = model.prefill(
            mla_params, jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32), cache)
        active = jnp.asarray([True, False])
        before_c = np.asarray(cache["c"][:, 1])
        before_idx = int(cache["index"][1])
        _, cache = model.decode_step(mla_params, jnp.asarray([9, 9]), cache,
                                     active=active)
        np.testing.assert_array_equal(np.asarray(cache["c"][:, 1]), before_c)
        assert int(cache["index"][1]) == before_idx
        assert int(cache["index"][0]) == 4

    def test_int8_latent_cache_close(self, mla_params):
        """int8 latent cache: same greedy tokens on the tiny model."""
        model = LlamaModel(MCFG)
        prompt = [5, 17, 99, 3, 42]
        ref = _greedy_reference(mla_params, prompt, 5)
        cache = model.init_cache(1, 64, quantize=True)
        assert "c_scale" in cache and cache["c"].dtype == jnp.int8
        logits, cache = model.prefill(
            mla_params, jnp.asarray([prompt], jnp.int32), cache)
        out = []
        tok = jnp.argmax(logits, -1)
        for _ in range(5):
            out.append(int(tok[0]))
            logits, cache = model.decode_step(mla_params, tok, cache)
            tok = jnp.argmax(logits, -1)
        assert out == ref

    def test_mla_excludes_windows_and_ring(self, mla_params):
        model = LlamaModel(MCFG)
        with pytest.raises(ValueError, match="sliding_window"):
            model.init_ring_cache(1, 128)
        with pytest.raises(ValueError, match="MLA does not compose"):
            init_params(tiny_mla(sliding_window=64), jax.random.PRNGKey(0))

    def test_cache_is_smaller_than_kv(self):
        """The point of MLA: latent bytes/token < K/V bytes/token."""
        kv, mla = kv_bytes_per_token(n_heads=MCFG.n_heads,
                                     head_dim=MCFG.head_dim_,
                                     latent_dim=MCFG.mla_latent_dim,
                                     rope_dim=MCFG.mla_rope_dim)
        assert mla < kv


class TestMlaEngine:
    def test_engine_generates_greedy_parity(self, mla_params):
        e = ServingEngine(MCFG, mla_params,
                          ServingConfig(slots=2, max_prefill_len=32,
                                        cache_len=64,
                                        max_new_tokens=8)).start()
        try:
            prompt = [5, 17, 99, 3]
            ref = _greedy_reference(mla_params, prompt, 6)
            got = e.submit(prompt, max_new_tokens=6).result(timeout=120)
            assert got["tokens"] == ref
        finally:
            e.stop()

    def test_engine_kv_int8_and_speculation(self, mla_params):
        """int8 latent cache + speculative decoding through the engine."""
        e = ServingEngine(MCFG, mla_params,
                          ServingConfig(slots=2, max_prefill_len=32,
                                        cache_len=64, max_new_tokens=8,
                                        quantize_kv_int8=True,
                                        speculate_k=3)).start()
        try:
            prompt = [5, 17, 99, 3, 5, 17, 99]  # repetitive: lookup drafts
            ref = _greedy_reference(mla_params, prompt, 6)
            got = e.submit(prompt, max_new_tokens=6).result(timeout=120)
            assert got["tokens"] == ref
        finally:
            e.stop()

    def test_engine_refuses_lora_on_mla(self, mla_params):
        with pytest.raises(ValueError, match="MLA"):
            ServingEngine(MCFG, mla_params,
                          ServingConfig(slots=1, lora_rank=4))


class TestMlaTraining:
    def test_grads_flow_and_finite(self, mla_params):
        """MLA trains: loss grads reach every MLA projection (direct-form
        flash path) and are finite."""
        model = LlamaModel(MCFG)
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                                  MCFG.vocab_size)

        def loss(p):
            logits = model.forward(p, toks[:, :-1])
            tgt = jax.nn.one_hot(toks[:, 1:], MCFG.vocab_size)
            return -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * tgt, axis=-1))

        grads = jax.grad(loss)(mla_params)
        for name in ("wq", "w_dkv", "w_uk", "w_uv", "wo"):
            g = np.asarray(grads["layers"][name])
            assert np.isfinite(g).all(), name
            assert np.abs(g).max() > 0, f"{name} got zero grads"

    def test_param_count_matches_tree(self, mla_params):
        n = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(mla_params))
        assert n == MCFG.param_count

    def test_quantized_mla_greedy_parity(self, mla_params):
        """int8 weights (wq/w_dkv/wo quantized, w_uk/w_uv compute-dtype)
        keep greedy decode identical on the tiny pinned model."""
        from k8s_runpod_kubelet_tpu.models.quant import quantize_params
        q = quantize_params(MCFG, mla_params, bits=8)
        assert "q8" in q["layers"]["w_dkv"]
        assert not isinstance(q["layers"]["w_uk"], dict)
        model = LlamaModel(MCFG)
        prompt = [5, 17, 99, 3]
        cache = model.init_cache(1, 64)
        logits, cache = model.prefill(
            q, jnp.asarray([prompt], jnp.int32), cache)
        ref = _greedy_reference(mla_params, prompt, 4)
        out, tok = [], jnp.argmax(logits, -1)
        for _ in range(4):
            out.append(int(tok[0]))
            logits, cache = model.decode_step(q, tok, cache)
            tok = jnp.argmax(logits, -1)
        assert out == ref


class TestMlaGuards:
    def test_validate_rejects_softcap_and_scalar(self):
        with pytest.raises(ValueError, match="attn_logit_softcap"):
            init_params(tiny_mla(attn_logit_softcap=50.0),
                        jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="query_pre_attn_scalar"):
            init_params(tiny_mla(query_pre_attn_scalar=256.0),
                        jax.random.PRNGKey(0))

    def test_hf_low_rank_q_fails_fast(self):
        """MLA HF import exists now (test_hf_convert.py proves parity);
        the remaining unsupported variant — DeepSeek-V2 full's low-rank q
        — still errors before any heavy lifting."""
        from k8s_runpod_kubelet_tpu.models.convert import load_hf
        sd = {f"model.layers.{i}.input_layernorm.weight":
              np.ones((MCFG.embed_dim,), np.float32)
              for i in range(MCFG.n_layers)}
        sd["model.layers.0.self_attn.q_a_proj.weight"] = \
            np.ones((8, MCFG.embed_dim), np.float32)
        with pytest.raises(NotImplementedError, match="q_lora_rank"):
            load_hf(MCFG, sd)


class TestMlaSharded:
    def test_sharded_training_step_matches_single_device(self):
        """MLA training over fsdp x tensor x seq (the direct-form flash
        path under GSPMD + the padded-V ring for the seq axis): loss and
        grads equal the unsharded step's — shardings never change values.
        Serving TP was already pinned; this covers the TRAINING mesh."""
        from k8s_runpod_kubelet_tpu.parallel import (MeshConfig, make_mesh,
                                                     param_shardings)
        from k8s_runpod_kubelet_tpu.models import param_logical_axes
        cfg = MCFG
        params = init_params(cfg, jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(5), (4, 33), 0,
                                  cfg.vocab_size)  # t[:, :-1] -> S=32 (seq=2)

        def loss_fn(model):
            def f(p, t):
                logits = model.forward(p, t[:, :-1])
                tgt = jax.nn.one_hot(t[:, 1:], cfg.vocab_size)
                return -jnp.mean(jnp.sum(
                    jax.nn.log_softmax(logits) * tgt, axis=-1))
            return f

        ref_loss, ref_grads = jax.value_and_grad(
            loss_fn(LlamaModel(cfg)))(params, toks)

        mesh = make_mesh(MeshConfig(fsdp=2, tensor=2, seq=2),
                         jax.devices()[:8])
        sh_params = jax.device_put(
            params, param_shardings(mesh, param_logical_axes(cfg)))
        sh_loss, sh_grads = jax.jit(jax.value_and_grad(
            loss_fn(LlamaModel(cfg, mesh))))(sh_params, toks)

        np.testing.assert_allclose(float(sh_loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-5)
        for name in ("wq", "w_dkv", "c_norm", "w_uk", "w_uv", "wo"):
            np.testing.assert_allclose(
                np.asarray(sh_grads["layers"][name]),
                np.asarray(ref_grads["layers"][name]),
                rtol=5e-4, atol=5e-4, err_msg=name)

    def test_train_main_tiny_mla_cli(self):
        """`train_main --model tiny-mla` runs end to end (CLI surface)."""
        from k8s_runpod_kubelet_tpu.workloads import train_main
        rc = train_main.main(["--model", "tiny-mla", "--steps", "2",
                              "--batch", "2", "--seq-len", "32"])
        assert rc == 0


class TestMlaPrefixEngine:
    def test_engine_serves_dense_prefix_config(self):
        """The serving engine runs a first_k_dense_replace-shaped model
        (prefix_layers stack + MoE body) end to end: greedy output equals
        the no-cache forward reference."""
        cfg = tiny_mla(vocab_size=128, embed_dim=64, n_layers=3, n_heads=4,
                       n_kv_heads=4, head_dim=16, mla_latent_dim=32,
                       mla_rope_dim=8, mlp_dim=48, max_seq_len=256,
                       n_experts=4, n_experts_per_tok=2, n_shared_experts=2,
                       router_norm_topk=False, n_dense_prefix=1,
                       dense_prefix_mlp_dim=112, capacity_factor=2.0,
                       dtype=jnp.float32, param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        model = LlamaModel(cfg)

        def ref(prompt, n_new):
            tokens = list(prompt)
            for _ in range(n_new):
                lg = model.forward(params, jnp.asarray([tokens], jnp.int32))
                tokens.append(int(jnp.argmax(lg[0, -1])))
            return tokens[len(prompt):]

        e = ServingEngine(cfg, params,
                          ServingConfig(slots=2, max_prefill_len=32,
                                        cache_len=64, max_new_tokens=8,
                                        quantize_kv_int8=True,
                                        speculate_k=2)).start()
        try:
            prompt = [5, 17, 99, 3, 5, 17]
            got = e.submit(prompt, max_new_tokens=6).result(timeout=120)
            assert got["tokens"] == ref(prompt, 6)
        finally:
            e.stop()


class TestMlaQLora:
    QCFG = tiny_mla(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                    n_kv_heads=4, head_dim=16, mla_latent_dim=32,
                    mla_rope_dim=8, mla_q_lora_rank=24, mlp_dim=128,
                    max_seq_len=256, dtype=jnp.float32,
                    param_dtype=jnp.float32)

    def test_absorbed_decode_and_int8_weights(self):
        """Low-rank q through the ABSORBED decode path with int8 weights
        (w_qa/w_qb quantize via _LAYER_WEIGHTS): engine greedy output
        equals the full-precision no-cache forward."""
        from k8s_runpod_kubelet_tpu.models.quant import quantize_params
        params = init_params(self.QCFG, jax.random.PRNGKey(2))
        model = LlamaModel(self.QCFG)

        def ref(prompt, n_new):
            toks = list(prompt)
            for _ in range(n_new):
                lg = model.forward(params, jnp.asarray([toks], jnp.int32))
                toks.append(int(jnp.argmax(lg[0, -1])))
            return toks[len(prompt):]

        q = quantize_params(self.QCFG, params, bits=8)
        assert "q8" in q["layers"]["w_qa"] and "q8" in q["layers"]["w_qb"]
        prompt = [5, 17, 99, 3]
        want = ref(prompt, 5)
        cache = model.init_cache(1, 64)
        logits, cache = model.prefill(q, jnp.asarray([prompt], jnp.int32),
                                      cache)
        out, tok = [], jnp.argmax(logits, -1)
        for _ in range(5):
            out.append(int(tok[0]))
            logits, cache = model.decode_step(q, tok, cache)
            tok = jnp.argmax(logits, -1)
        assert out == want

    def test_q_lora_requires_mla(self):
        from k8s_runpod_kubelet_tpu.models import tiny_llama
        with pytest.raises(ValueError, match="mla_q_lora_rank requires"):
            init_params(tiny_llama(mla_q_lora_rank=24),
                        jax.random.PRNGKey(0))


def test_prefix_cache_composes_with_latent_cache(mla_params):
    """MLA latent caches PAGE like any K/V layout (the arena is generic
    over cache sections, so c/kr page alongside k/v): a registered prefix
    pins latent pages, later prompts gather them, outputs equal the cold
    path's. kv_page_tokens=4 so the 10-token prefix spans full pages."""
    e = ServingEngine(MCFG, mla_params,
                      ServingConfig(slots=2, max_prefill_len=16,
                                    cache_len=64, max_new_tokens=8,
                                    kv_page_tokens=4)).start()
    cold = ServingEngine(MCFG, mla_params,
                         ServingConfig(slots=2, max_prefill_len=16,
                                       cache_len=64,
                                       max_new_tokens=8)).start()
    try:
        prefix = [7, 21, 3, 99, 14, 2, 81, 5, 40, 11]
        e.register_prefix(prefix)
        a = e.submit(prefix + [42], max_new_tokens=6).result(timeout=120)
        b = cold.submit(prefix + [42], max_new_tokens=6).result(timeout=120)
        assert a["tokens"] == b["tokens"]
        assert "tpu_serving_prefix_hits_total 1" in e.metrics.render()
    finally:
        e.stop()
        cold.stop()
