"""Multi-head Latent Attention (ops/mla.py): absorbed-decode vs
full-sequence parity, direct-vs-absorbed equivalence, latent-cache
compression arithmetic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_runpod_kubelet_tpu.ops.mla import (init_mla_cache, init_mla_params,
                                            kv_bytes_per_token,
                                            mla_attention, mla_decode_step)
from k8s_runpod_kubelet_tpu.ops.rope import rope_frequencies

pytestmark = pytest.mark.slow

E, H, DH, DR, R = 64, 4, 16, 8, 24
S, B = 12, 2


@pytest.fixture(scope="module")
def setup():
    params = init_mla_params(jax.random.PRNGKey(0), embed_dim=E, n_heads=H,
                             head_dim=DH, latent_dim=R, rope_dim=DR)
    cos, sin = rope_frequencies(DR, max_seq_len=64, theta=10000.0)
    h = jax.random.normal(jax.random.PRNGKey(1), (B, S, E), jnp.float32)
    return params, cos, sin, h


class TestMLA:
    def test_decode_matches_full_sequence(self, setup):
        """Token-by-token absorbed decode reproduces the causal
        full-sequence outputs at every position."""
        params, cos, sin, h = setup
        full, _ = mla_attention(h, params, cos, sin)
        cache = init_mla_cache(B, 32, latent_dim=R, rope_dim=DR)
        step = jax.jit(mla_decode_step)
        for t in range(S):
            out, cache = step(h[:, t:t + 1], params, cache, cos, sin)
            np.testing.assert_allclose(np.asarray(out[:, 0]),
                                       np.asarray(full[:, t]),
                                       rtol=2e-4, atol=2e-4)
        assert [int(x) for x in cache["index"]] == [S] * B

    def test_absorbed_equals_direct(self, setup):
        """The absorbed form (attention in latent space) must equal the
        direct form (materialize per-head K/V from the same cache)."""
        params, cos, sin, h = setup
        # prefill the cache via the full pass
        _, kv = mla_attention(h, params, cos, sin)
        cache = init_mla_cache(B, 32, latent_dim=R, rope_dim=DR)
        cache["c"] = cache["c"].at[:, :S].set(kv["c"])
        cache["kr"] = cache["kr"].at[:, :S].set(kv["kr"])
        cache["index"] = jnp.full((B,), S, jnp.int32)
        h1 = jax.random.normal(jax.random.PRNGKey(2), (B, 1, E), jnp.float32)
        absorbed, cache2 = mla_decode_step(h1, params, cache, cos, sin)

        # direct reference: materialize k/v for live positions and attend
        from k8s_runpod_kubelet_tpu.ops.mla import _project
        pos = jnp.full((B, 1), S, jnp.int32)
        q_nope, q_rope, c1, kr1 = _project(h1, params, cos, sin, pos)
        c = cache["c"].at[:, S].set(c1[:, 0])
        kr = cache["kr"].at[:, S].set(kr1[:, 0])
        k_nope = jnp.einsum("blr,rhd->blhd", c, params["w_uk"])
        v = jnp.einsum("blr,rhd->blhd", c, params["w_uv"])
        scale = (DH + DR) ** -0.5
        scores = (jnp.einsum("bohd,blhd->bhol", q_nope, k_nope)
                  + jnp.einsum("bohd,bld->bhol", q_rope, kr)) * scale
        live = (jnp.arange(c.shape[1]) <= S)[None, None, None, :]
        scores = jnp.where(live, scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhol,blhd->bohd", p, v).reshape(B, 1, H * DH)
        direct = o @ params["w_o"]
        np.testing.assert_allclose(np.asarray(absorbed), np.asarray(direct),
                                   rtol=2e-5, atol=2e-5)

    def test_cache_compression_claim(self):
        """DeepSeek-V2 geometry: 128 heads x 128 dh vs latent 512 + rope 64
        = 10.2/1 fewer KV bytes per token."""
        std, mla = kv_bytes_per_token(n_heads=128, head_dim=128,
                                      latent_dim=512, rope_dim=64)
        assert std / mla == pytest.approx(32768 / 576)  # 56.9x
        # and this test file's tiny geometry still compresses
        std, mla = kv_bytes_per_token(n_heads=H, head_dim=DH,
                                      latent_dim=R, rope_dim=DR)
        assert mla < std

    def test_rope_positions_actually_used(self, setup):
        """_project must rotate by the CALLER's positions: the same input
        at position 0 vs position 5 produces different q_rope/kr (a
        hardcoded-zero-position bug would make these equal)."""
        from k8s_runpod_kubelet_tpu.ops.mla import _project
        params, cos, sin, h = setup
        p0 = jnp.zeros((B, 1), jnp.int32)
        p5 = jnp.full((B, 1), 5, jnp.int32)
        _, qr0, _, kr0 = _project(h[:, :1], params, cos, sin, p0)
        _, qr5, _, kr5 = _project(h[:, :1], params, cos, sin, p5)
        assert not np.allclose(np.asarray(qr0), np.asarray(qr5))
        assert not np.allclose(np.asarray(kr0), np.asarray(kr5))

    def test_per_row_index_rows_advance_independently(self, setup):
        """Engine-contract cache: rows at DIFFERENT lengths decode
        correctly in one batch — row 0 continuing a 4-token history must
        match what it would produce in a batch of its own."""
        params, cos, sin, h = setup
        # batch run: row 0 has 4 committed tokens, row 1 has 7
        cache = init_mla_cache(B, 32, latent_dim=R, rope_dim=DR)
        lens = [4, 7]
        for t in range(max(lens)):
            live_rows = [t < n for n in lens]
            out, cache = mla_decode_step(h[:, t:t + 1], params, cache,
                                         cos, sin)
            # freeze rows past their length (caller-side active handling)
            cache["index"] = jnp.asarray(
                [min(int(i), n) for i, n in zip(cache["index"], lens)],
                jnp.int32)
        mixed_out, _ = mla_decode_step(h[:, 10:11], params, cache, cos, sin)

        # solo run of row 0's exact history
        solo = init_mla_cache(1, 32, latent_dim=R, rope_dim=DR)
        for t in range(lens[0]):
            _, solo = mla_decode_step(h[:1, t:t + 1], params, solo, cos, sin)
        solo_out, _ = mla_decode_step(h[:1, 10:11], params, solo, cos, sin)
        np.testing.assert_allclose(np.asarray(mixed_out[0]),
                                   np.asarray(solo_out[0]),
                                   rtol=2e-5, atol=2e-5)
