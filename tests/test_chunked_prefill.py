"""Chunked prefill + engine-level streamed handoff (ISSUE 10).

What this file pins:

- chunked prefill is TOKEN-IDENTICAL to monolithic (greedy and seeded
  sampling, prefix-cache hits included) — chunking is a scheduling
  change, never a math change;
- the ITL-protection regression: a long prompt admitted next to an
  active decode stream keeps that stream's worst inter-token gap bounded
  with chunking ON (decode steps interleave between chunks — counted by
  tpu_serving_chunk_interleaved_steps), and the monolithic engine
  reproduces the spike chunking removes;
- export_handoff_stream -> adopt_handoff_chunk between REAL engines:
  adopted pages decode token-identically, frames arrive in strict order,
  a mid-stream sender death (emit raising after k frames) fails the
  export loudly, adopts NOTHING on the decode side, and leaks zero pages
  on either arena — the engine half of the chunk-stream kill soak.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_runpod_kubelet_tpu.fleet.handoff import (HandoffError,
                                                  serialize_chunk_frame,
                                                  serialize_pages)
from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                      ServingEngine)
from k8s_runpod_kubelet_tpu.workloads.serving.scheduler import ChunkArbiter

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = pytest.mark.slow

CFG = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, mlp_dim=128, max_seq_len=512,
                 dtype=jnp.float32, param_dtype=jnp.float32)
SEED = 20260804
T = 8  # kv_page_tokens


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def _engine(params, chunk: int, **kw) -> ServingEngine:
    sc = ServingConfig(slots=4, max_prefill_len=32, cache_len=256,
                       max_new_tokens=16, kv_page_tokens=T,
                       serving_chunk_tokens=chunk, **kw)
    return ServingEngine(CFG, params, sc).start()


def _prompt(n: int, salt: int) -> list:
    return [((j * 7 + salt * 131) % 120) + 1 for j in range(n)]


def _stream_frames(engine, tokens, sink, stream="s", fail_after=None):
    """Drive export_handoff_stream, serializing each fragment into a real
    chunk frame and handing it to ``sink(blob)`` synchronously (strict
    order by construction). ``fail_after``: emit raises after that many
    fragments — the mid-stream sender death."""
    n_emitted = [0]

    def emit(frag):
        if fail_after is not None and n_emitted[0] >= fail_after:
            raise OSError("injected mid-stream death")
        n_emitted[0] += 1
        payload = b""
        if frag["sections"]:
            n = len(frag["tokens"]) // T
            sections = {name: np.asarray(a)[:, :n]
                        for name, a in frag["sections"].items()}
            payload = serialize_pages(frag["tokens"], T, sections,
                                      model=CFG.name)
        sink(serialize_chunk_frame(stream, frag["seq"], payload,
                                   final=frag["final"],
                                   total_tokens=frag.get("total_tokens")))

    return engine.export_handoff_stream(tokens, emit)


def _assert_no_leaks(engine, what: str):
    stats = engine.prefix_cache_stats()
    assert stats["pages_free"] + stats["nodes"] == stats["pages_total"], \
        f"[seed={SEED}] {what}: leaked pages — {stats}"
    store = engine._kv_store
    for node in store.trie._nodes.values():
        assert store.pool.refcount(node.page) == 1, \
            f"[seed={SEED}] {what}: dangling reference on page {node.page}"


class TestChunkedTokenIdentity:
    def test_chunked_equals_monolithic(self, params):
        """Greedy and seeded-sampled outputs are byte-identical across
        chunk sizes — including prompts that hit the prefix cache and
        prompts spanning several max_prefill_len buckets."""
        rng = np.random.default_rng(SEED)
        e_mono = _engine(params, chunk=0)
        e_c8 = _engine(params, chunk=8)
        e_c20 = _engine(params, chunk=20)  # deliberately page-misaligned
        engines = [e_mono, e_c8, e_c20]
        try:
            shared = _prompt(96, salt=1)
            for e in engines:
                e.register_prefix(shared)
            prompts = [shared + [1, 2, 3],          # prefix hit + tail
                       _prompt(100, salt=2),        # long miss
                       _prompt(5, salt=3),          # under one chunk
                       shared[:40] + [9, 9]]        # partial-prefix hit
            for i in range(6):
                prompts.append(_prompt(int(rng.integers(3, 120)),
                                       salt=10 + i))
            for i, p in enumerate(prompts):
                kw = dict(max_new_tokens=10)
                if i % 3 == 2:
                    kw.update(temperature=0.9, seed=1000 + i)
                outs = [e.submit(p, **kw).result(timeout=300)
                        for e in engines]
                assert outs[0]["tokens"] == outs[1]["tokens"] \
                    == outs[2]["tokens"], \
                    f"[seed={SEED}] prompt {i}: chunked != monolithic"
            assert e_c8.metrics.get_counter(
                "tpu_serving_prefill_chunks") > 0
        finally:
            for e in engines:
                e.stop()


class TestItlUnderLongPrefill:
    def _drive(self, params, chunk: int) -> tuple[list, float]:
        """One engine: start a decode stream, admit a long prompt while
        it decodes, return (stream's inter-token gaps, interleaved-step
        count)."""
        e = _engine(params, chunk=chunk)
        try:
            # warm every jit (prefill buckets + chunk steps + decode) so
            # measured gaps are work, not compilation
            e.submit(_prompt(100, salt=99), max_new_tokens=2).result(
                timeout=300)
            gaps, last = [], [None]

            def on_token(_t):
                import time
                now = time.perf_counter()
                if last[0] is not None:
                    gaps.append(now - last[0])
                last[0] = now

            stream = e.submit(_prompt(6, salt=5), max_new_tokens=60,
                              on_token=on_token)
            while len(gaps) < 3:     # genuinely mid-decode
                import time
                time.sleep(0.002)
            e.submit(_prompt(100, salt=7), max_new_tokens=2).result(
                timeout=300)
            stream.result(timeout=300)
            return gaps, e.metrics.get_counter(
                "tpu_serving_chunk_interleaved_steps")
        finally:
            e.stop()

    def test_chunked_bounds_the_spike_monolithic_reproduces(self, params):
        gaps_c, interleaved = self._drive(params, chunk=8)
        gaps_m, _ = self._drive(params, chunk=0)
        assert interleaved > 0, \
            f"[seed={SEED}] no decode steps interleaved between chunks"
        # the structural claim: with chunking the engine decoded BETWEEN
        # chunks, so the stream's worst gap is bounded by ~a chunk, not
        # the whole prefill; the monolithic engine's worst gap contains
        # the full 100-token prefill. Compare the two (comparative, not
        # absolute — CI boxes are noisy).
        assert max(gaps_c) < max(gaps_m), \
            (f"[seed={SEED}] chunked max gap {max(gaps_c):.4f}s not below "
             f"monolithic {max(gaps_m):.4f}s (interleaved={interleaved})")


class TestStreamedHandoffBetweenEngines:
    def test_stream_adopts_and_decodes_identically(self, params):
        e_pre = _engine(params, chunk=8)
        e_dec = _engine(params, chunk=0)
        try:
            prompt = _prompt(100, salt=21)
            frames: list = []
            out = _stream_frames(e_pre, prompt, frames.append)
            assert out["pages"] == len(prompt) // T
            assert out["frames"] == len(frames)
            assert out["chunks"] == len(frames) - 1 >= 3
            res = None
            for blob in frames:
                res = e_dec.adopt_handoff_chunk(blob)
            assert res["final"] and res["pages"] == out["pages"]
            # counters moved only at the final adoption
            assert e_dec.metrics.get_counter(
                "tpu_serving_kv_handoff_pages") == out["pages"]
            a = e_pre.submit(prompt, max_new_tokens=8).result(timeout=300)
            b = e_dec.submit(prompt, max_new_tokens=8).result(timeout=300)
            assert a["tokens"] == b["tokens"], \
                f"[seed={SEED}] adopted KV decoded differently"
            assert e_dec.metrics.get_counter(
                "tpu_serving_prefix_cache_hits") == 1
            _assert_no_leaks(e_pre, "prefill arena")
            _assert_no_leaks(e_dec, "decode arena")
        finally:
            e_pre.stop()
            e_dec.stop()

    def test_mid_stream_death_adopts_nothing_and_leaks_nothing(self,
                                                               params):
        e_pre = _engine(params, chunk=8)
        e_dec = _engine(params, chunk=0)
        try:
            prompt = _prompt(100, salt=22)
            frames: list = []
            fails0 = e_pre.metrics.get_counter(
                "tpu_serving_kv_handoff_failures")
            with pytest.raises(OSError, match="injected"):
                _stream_frames(e_pre, prompt, frames.append, fail_after=2)
            assert e_pre.metrics.get_counter(
                "tpu_serving_kv_handoff_failures") == fails0 + 1
            # the decode side got a PARTIAL stream: frames buffer but the
            # final frame never arrives — nothing touches the arena, and
            # the half-open stream expires instead of pinning memory
            free0 = e_dec.prefix_cache_stats()["pages_free"]
            for blob in frames:
                e_dec.adopt_handoff_chunk(blob)
            assert e_dec.prefix_cache_stats()["pages_free"] == free0, \
                f"[seed={SEED}] partial stream touched the arena"
            assert e_dec.metrics.get_counter(
                "tpu_serving_kv_handoff_pages") == 0
            # a later stream with the same id must not resume the corpse:
            # the stream id is fresh per hop, and a stale-seq frame is
            # rejected outright
            with pytest.raises(HandoffError, match="duplicate|reordered"):
                e_dec.adopt_handoff_chunk(frames[-1])
            assert e_dec.metrics.get_counter(
                "tpu_serving_kv_handoff_stream_rejects") >= 1
            _assert_no_leaks(e_pre, "prefill arena after kill")
            _assert_no_leaks(e_dec, "decode arena after kill")
            # both engines still serve (the fallback request completes)
            out = e_dec.submit(prompt, max_new_tokens=4).result(timeout=300)
            assert len(out["tokens"]) == 4
        finally:
            e_pre.stop()
            e_dec.stop()

    def test_streamed_requires_chunked_prefill(self, params):
        e = _engine(params, chunk=0)
        try:
            with pytest.raises(HandoffError, match="chunked prefill"):
                e.export_handoff_stream(_prompt(40, salt=1), lambda f: None)
        finally:
            e.stop()


class TestChunkArbiter:
    """Host-only arbitration contract (no jax in these assertions)."""

    def test_idle_yield_is_free(self):
        arb = ChunkArbiter()
        assert arb.yield_for_decode(lambda: False) == 0

    def test_yield_waits_for_a_step(self):
        arb = ChunkArbiter()
        ran = []

        def prefiller():
            ran.append(arb.yield_for_decode(lambda: True, timeout_s=5.0))

        th = threading.Thread(target=prefiller)
        th.start()
        import time
        time.sleep(0.05)
        assert not ran, "yield returned before any decode step"
        arb.decode_step_done()
        th.join(timeout=5.0)
        assert ran == [1]

    def test_yield_unblocks_when_slots_empty(self):
        arb = ChunkArbiter()
        active = [True]
        ran = []

        def prefiller():
            ran.append(arb.yield_for_decode(lambda: active[0],
                                            timeout_s=0.2))

        th = threading.Thread(target=prefiller)
        th.start()
        active[0] = False   # last slot completed without a step
        th.join(timeout=5.0)
        assert ran == [0]
