"""Speculative decoding: verify_step exactness vs sequential decode_step,
and the serving engine's greedy output invariance with speculation on."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from k8s_runpod_kubelet_tpu.models import LlamaModel, init_params, tiny_llama

import pytest as _pytest

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = _pytest.mark.slow


def _cfg(**kw):
    base = dict(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                n_kv_heads=2, mlp_dim=96, max_seq_len=64,
                dtype=jnp.float32, param_dtype=jnp.float32, remat=False)
    base.update(kw)
    return tiny_llama(**base)


class TestVerifyStep:
    def test_matches_sequential_decode(self):
        """verify_step's K logits == K sequential decode_step logits, and the
        caches agree on every committed position."""
        cfg = _cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        model = LlamaModel(cfg)
        b, kk = 2, 4
        prompt = jnp.asarray([[5, 6, 7], [9, 8, 7]], jnp.int32)
        cache0 = model.init_cache(b, 32)
        _, cache0 = model.prefill(params, prompt, cache0)
        toks = jax.random.randint(jax.random.PRNGKey(1), (b, kk), 0,
                                  cfg.vocab_size, jnp.int32)

        # sequential reference
        seq_cache = jax.tree_util.tree_map(lambda x: x, cache0)
        seq_logits = []
        for j in range(kk):
            lg, seq_cache = model.decode_step(params, toks[:, j], seq_cache)
            seq_logits.append(np.asarray(lg))

        ver_logits, ver_cache = model.verify_step(params, toks, cache0)
        for j in range(kk):
            np.testing.assert_allclose(np.asarray(ver_logits[:, j]),
                                       seq_logits[j], atol=2e-4, rtol=2e-4)
        # KV written at idx..idx+K-1 must match the sequential cache
        idx0 = np.asarray(cache0["index"])
        for row in range(b):
            sl = slice(idx0[row], idx0[row] + kk)
            np.testing.assert_allclose(
                np.asarray(ver_cache["k"][:, row, sl]),
                np.asarray(seq_cache["k"][:, row, sl]), atol=1e-5)
        # verify_step does NOT advance the index (caller commits)
        np.testing.assert_array_equal(np.asarray(ver_cache["index"]), idx0)

    def test_inactive_slots_untouched(self):
        cfg = _cfg()
        params = init_params(cfg, jax.random.PRNGKey(2))
        model = LlamaModel(cfg)
        cache = model.init_cache(2, 32)
        _, cache = model.prefill(params, jnp.asarray([[1, 2], [3, 4]]), cache)
        before_k = np.asarray(cache["k"]).copy()
        toks = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        active = jnp.asarray([True, False])
        _, cache2 = model.verify_step(params, toks, cache, active)
        np.testing.assert_array_equal(np.asarray(cache2["k"][:, 1]),
                                      before_k[:, 1])  # frozen slot intact
        assert not np.array_equal(np.asarray(cache2["k"][:, 0]),
                                  before_k[:, 0])      # live slot wrote


class TestSpeculativeServing:
    def _run_engine(self, spec_k, prompts, cfg=None, new_toks=12):
        from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                              ServingEngine)
        cfg = cfg or _cfg()
        params = init_params(cfg, jax.random.PRNGKey(3))
        eng = ServingEngine(cfg, params, ServingConfig(
            slots=2, cache_len=64, max_new_tokens=new_toks,
            max_prefill_len=16, speculate_k=spec_k)).start()
        try:
            futs = [eng.submit(p, max_new_tokens=new_toks) for p in prompts]
            outs = [f.result(timeout=300)["tokens"] for f in futs]
            stats = dict(eng.metrics.counters) if hasattr(eng.metrics,
                                                          "counters") else {}
            return outs, eng
        finally:
            eng.stop()

    def test_greedy_output_identical_with_speculation(self):
        """The load-bearing exactness property: speculation must change WHEN
        tokens are produced, never WHICH tokens."""
        prompts = [[1, 2, 3, 1, 2], [7, 8, 9, 7, 8, 9, 7]]
        base, _ = self._run_engine(0, prompts)
        spec, eng = self._run_engine(3, prompts)
        assert base == spec, (base, spec)

    def test_acceptance_metric_present(self):
        prompts = [[4, 4, 4, 4, 4, 4]]
        _, eng = self._run_engine(3, prompts)
        text = eng.metrics.render()
        assert "tpu_serving_spec_proposed" in text
        assert "tpu_serving_spec_accepted" in text

    def test_incremental_propose_matches_naive_scan(self):
        """The amortized-O(1) bigram index must propose exactly what the
        original O(context) backward scan proposed, across growing
        contexts (index built lazily over prompt+generated)."""
        import numpy as np
        from k8s_runpod_kubelet_tpu.workloads.serving import (Request, _Slot,
                                                              ServingEngine)

        def naive(ctx, k):
            draft = []
            if len(ctx) >= 3:
                big = (ctx[-2], ctx[-1])
                for i in range(len(ctx) - 3, -1, -1):
                    if (ctx[i], ctx[i + 1]) == big:
                        draft = ctx[i + 2:i + 2 + k]
                        break
            last = ctx[-1]
            while len(draft) < k:
                draft.append(last)
            return draft[:k]

        rng = np.random.default_rng(0)
        for trial in range(20):
            prompt = [int(t) for t in rng.integers(0, 5, rng.integers(3, 30))]
            slot = _Slot(request=Request(
                prompt=prompt, max_new_tokens=64, rid="t", future=None,
                submitted_at=0.0, temperature=0.0), generated=[])
            # grow the generated tail one token at a time, proposing at each
            # length — exercises the lazy indexing against every prefix
            for t in rng.integers(0, 5, 40):
                slot.generated.append(int(t))
                ctx = prompt + slot.generated
                k = int(rng.integers(1, 5))
                got = ServingEngine._propose(None, slot, k)
                assert got == naive(ctx, k), (trial, ctx, k)


class TestChunkedPrefill:
    def test_chunked_cache_matches_full_prefill(self):
        """Model-level: prefill(16) + verify-appended chunks must build the
        same KV cache and next-token logits as one full prefill — compared
        with float tolerances, since the two paths use different (equally
        valid) attention kernels."""
        cfg = _cfg()
        params = init_params(cfg, jax.random.PRNGKey(5))
        model = LlamaModel(cfg)
        prompt = np.random.default_rng(0).integers(
            1, cfg.vocab_size, 37).astype(np.int32)

        full_cache = model.init_cache(1, 64)
        full_logits, full_cache = model.prefill(
            params, jnp.asarray([prompt]), full_cache)

        cache = model.init_cache(1, 64)
        logits, cache = model.prefill(params, jnp.asarray([prompt[:16]]),
                                      cache)
        for start in (16, 32):
            chunk = prompt[start:start + 16]
            lk, cache = model.verify_step(params, jnp.asarray([chunk]), cache)
            cache = dict(cache)
            cache["index"] = cache["index"] + len(chunk)
            logits = lk[:, len(chunk) - 1]
        assert int(cache["index"][0]) == int(full_cache["index"][0]) == 37
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full_logits),
                                   atol=3e-4, rtol=3e-4)
        np.testing.assert_allclose(np.asarray(cache["k"][:, 0, :37]),
                                   np.asarray(full_cache["k"][:, 0, :37]),
                                   atol=3e-4, rtol=3e-4)

    def test_long_prompt_serves_end_to_end(self):
        """Engine-level smoke: a 3-chunk prompt admits and generates."""
        from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                              ServingEngine)
        cfg = _cfg()
        params = init_params(cfg, jax.random.PRNGKey(5))
        prompt = list(np.random.default_rng(0).integers(
            1, cfg.vocab_size, 37))
        eng = ServingEngine(cfg, params, ServingConfig(
            slots=1, cache_len=64, max_new_tokens=6,
            max_prefill_len=16)).start()
        try:
            out = eng.submit(prompt, max_new_tokens=6).result(timeout=300)
            assert len(out["tokens"]) == 6
        finally:
            eng.stop()

    def test_prompt_beyond_cache_budget_rejected(self):
        from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                              ServingEngine)
        cfg = _cfg()
        params = init_params(cfg, jax.random.PRNGKey(6))
        eng = ServingEngine(cfg, params, ServingConfig(
            slots=1, cache_len=32, max_prefill_len=16))
        fut = eng.submit([1] * 40)
        assert isinstance(fut.exception(), ValueError)


class TestSlidingWindowDecode:
    def test_windowed_decode_matches_forward_rollout(self):
        """Decode with a sliding window must equal a full windowed forward:
        the cache mask (<= idx AND within window) is the decode-side of the
        same mask the training kernels apply."""
        cfg = _cfg(sliding_window=6)
        params = init_params(cfg, jax.random.PRNGKey(7))
        model = LlamaModel(cfg)
        prompt = [3, 9, 4, 1, 5, 9, 2, 6]  # longer than the window
        cache = model.init_cache(1, 32)
        logits, cache = model.prefill(params, jnp.asarray([prompt]), cache)
        toks = [int(np.argmax(np.asarray(logits[0])))]
        for _ in range(5):
            lg, cache = model.decode_step(
                params, jnp.asarray(toks[-1:], jnp.int32), cache)
            toks.append(int(np.argmax(np.asarray(lg[0]))))
        # reference: rerun the whole sequence through forward each step
        ref = []
        cur = list(prompt)
        for _ in range(6):
            fl = model.forward(params, jnp.asarray([cur], jnp.int32))
            nxt = int(np.argmax(np.asarray(fl[0, -1])))
            ref.append(nxt)
            cur.append(nxt)
        assert toks == ref, (toks, ref)
