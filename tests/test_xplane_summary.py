"""tools/xplane_summary.py: raw wire-format xplane parsing against a trace
captured in-test (no TF dependency anywhere)."""

import glob
import os
import sys

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
from tools.xplane_summary import main, summarize  # noqa: E402

pytestmark = pytest.mark.slow


def test_summarize_real_trace(tmp_path, capsys):
    jax.profiler.start_trace(str(tmp_path))
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    f(x).block_until_ready()
    f(x).block_until_ready()
    jax.profiler.stop_trace()
    pbs = glob.glob(str(tmp_path / "**" / "*.xplane.pb"), recursive=True)
    assert pbs, "profiler wrote no xplane.pb"
    planes = summarize(pbs[0], top=10)
    assert planes, "no planes parsed"
    names = {p["plane"] for p in planes}
    assert any("CPU" in n or "TPU" in n or "host" in n for n in names), names
    for p in planes:
        assert p["busy_ms"] > 0
        for nm, ms, c, share in p["top"]:
            assert ms >= 0 and c >= 1 and 0 <= share <= 1
    # CLI end to end on the directory (picks the newest capture)
    assert main([str(tmp_path), "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "== plane:" in out and "total_ms" in out


def test_cli_errors():
    assert main([]) == 2
    assert main(["/nonexistent-dir-xyz"]) == 1
    assert main(["--top"]) == 2          # missing value
    assert main(["--top", "abc"]) == 2   # non-numeric
    assert main(["--top", "5"]) == 2     # no path left
