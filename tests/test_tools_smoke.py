"""Every tools/*_summary.py must render its committed fixture (ISSUE 20):
``main()`` returns 0 and prints a non-empty table. The fixtures live in
tests/fixtures/ and are REGENERATED (never hand-edited) with:

    python tests/test_tools_smoke.py --write-fixture

so a reader-side format change ships with its fixture in the same diff,
and a producer-side schema change that breaks a reader fails tier-1
instead of some operator's terminal three weeks later.
"""

from __future__ import annotations

import json
import pathlib
import sys

import pytest

FIXDIR = pathlib.Path(__file__).parent / "fixtures"
TOOLS_DIR = pathlib.Path(__file__).parent.parent / "tools"
sys.path.insert(0, str(TOOLS_DIR))

_T0 = 1000.0  # fixture epoch (fixtures are committed: no wall-clock reads)


def _span(name, start, dur, trace="0" * 31 + "1", span_id="s1",
          parent="", **attrs):
    return {"trace_id": trace, "span_id": span_id, "parent_id": parent,
            "name": name, "start": start, "duration_s": dur, "attrs": attrs}


def _gen_trace_spans() -> str:
    """serve_main --trace-export shape: serving.request trees."""
    lines = []
    for i in range(3):
        trace = f"{i + 1:032x}"
        t0 = _T0 + i * 10
        lat = 0.8 + 0.3 * i
        lines += [
            _span("serving.request", t0, lat, trace=trace, span_id="root",
                  rid=f"r-{i}", ttft_s=0.1 + 0.05 * i, latency_s=lat,
                  prompt_tokens=16, tokens=8, cost_dollars=0.00021,
                  tenant="acme" if i else "-"),
            _span("serving.queue_wait", t0, 0.05, trace=trace,
                  span_id="q", parent="root"),
            _span("serving.prefill", t0 + 0.05, 0.1, trace=trace,
                  span_id="p", parent="root"),
            _span("serving.decode", t0 + 0.15, lat - 0.15, trace=trace,
                  span_id="d", parent="root", tokens=8),
        ]
    return "\n".join(json.dumps(s) for s in lines) + "\n"


def _gen_goodput_spans() -> str:
    """train_main --trace-export shape: training.* span families."""
    hosts = {"0": {"step": 40, "mean_step_s": 0.21, "age_s": 1.0,
                   "flagged": ""},
             "1": {"step": 38, "mean_step_s": 0.34, "age_s": 1.2,
                   "flagged": "slow"}}
    lines = [
        _span("training.run", _T0, 30.0, span_id="run0", attempt=0,
              step=25, goodput=0.72, mfu=0.31, tokens_per_sec=15000.0,
              wall_s=30.0, buckets={"productive": 21.5, "compile": 6.0,
                                    "checkpoint_save": 2.5}),
        _span("training.run", _T0 + 40, 20.0, span_id="run1", attempt=1,
              step=40, goodput=0.55, mfu=0.29, tokens_per_sec=14000.0,
              wall_s=20.0, hosts=hosts,
              buckets={"productive": 11.0, "restart_lost": 8.0,
                       "checkpoint_restore": 1.0}),
        _span("training.restore", _T0 + 40.5, 1.0, span_id="re", step=25),
        _span("training.straggler", _T0 + 50, 0.0, span_id="st", host=1,
              kind="slow", last_step=38, lag_s=2.4),
    ]
    lines += [_span("training.step", _T0 + 41 + 0.25 * i, 0.2 + 0.01 * i,
                    span_id=f"step{i}", step=26 + i, host=i % 2)
              for i in range(8)]
    return "\n".join(json.dumps(s) for s in lines) + "\n"


def _gen_fleet_jsonl() -> str:
    """Router span export + appended /debug/fleet registry snapshots."""
    lines = [
        _span("fleet.route", _T0 + i, 0.2, trace=f"{i + 1:032x}",
              span_id=f"rt{i}", replica_id=f"rep-{i % 2}",
              reason="least_loaded", attempts=1, status=200)
        for i in range(4)
    ]
    lines.append(_span("fleet.scale", _T0 + 9, 0.0, span_id="sc",
                       direction="up", **{"from": 2, "to": 3},
                       reason="queue_depth 9.0 > target", target=3))
    snap = {"schema_version": 1, "now": _T0 + 10, "replicas": [
        {"replica_id": f"rep-{i}", "state": "ready", "role": "unified",
         "heartbeat_age_s": 1.0,
         "stats": {"active_slots": i, "max_slots": 4, "queue_depth": i,
                   "kv_cache_tokens": 100 * i, "ttft_p95_s": 0.2}}
        for i in range(2)]}
    return "\n".join(json.dumps(s) for s in (*lines, snap)) + "\n"


def _gen_slo_jsonl() -> str:
    """/debug/slo + /debug/steps appends, plus fleet.slo_burn spans."""
    def slo_snap(t, burning):
        return {
            "schema_version": 1, "enabled": True, "burn_threshold": 2.0,
            "budget_frac": 0.05,
            "windows": {"short_s": 300, "long_s": 3600},
            "signals": {"ttft": {
                "objective": 0.5, "burning": burning,
                "short_burn": 3.1 if burning else 0.4,
                "long_burn": 2.2 if burning else 0.3, "crossings": 1,
                "samples_short": 40, "samples_long": 300}},
            "history": [{"t": t - 60, "burn": {"ttft": 0.4}},
                        {"t": t, "burn": {"ttft": 3.1 if burning
                                          else 0.5}}]}
    steps = {"schema_version": 1, "steps": [
        {"seq": i, "wall_s": 0.004 + 0.001 * i,
         "phases": {"schedule_s": 0.0005, "kernel_s": 0.0025,
                    "sample_s": 0.0007, "commit_s": 0.0003 + 0.001 * i},
         "batch": {"active": 3, "mode": "paged"}} for i in range(6)],
        "rollup": {"steps": 6, "tokens_total": 18, "spec_steps": 0,
                   "bytes": 2048, "max_bytes": 262144, "dropped": 0,
                   "wall_ms_p50": 4.5, "schedule_ms_p50": 0.5,
                   "kernel_ms_p50": 2.5, "sample_ms_p50": 0.7,
                   "commit_ms_p50": 0.8},
        "recompiles": {"decode_step": {"compiles": 1, "recompiles": 0,
                                       "budget": 2, "warned": False}}}
    burn = _span("fleet.slo_burn", _T0 + 120, 0.0, span_id="bu",
                 signal="ttft", short_burn=3.1, long_burn=2.2,
                 threshold=2.0, objective=0.5, replicas=3)
    rows = [slo_snap(_T0, False), burn, slo_snap(_T0 + 120, True), steps]
    return "\n".join(json.dumps(r) for r in rows) + "\n"


def _gen_costs_jsonl() -> str:
    """Router /debug/costs rollup + one replica ledger + /debug/train."""
    totals = {"requests": 42, "tokens": 8400, "prompt_tokens": 2100,
              "chip_seconds": {"queue": 4.2, "prefill": 21.0,
                               "decode": 310.8},
              "kv_page_seconds": 5100.0, "cost_dollars": 0.112}
    replica = {"schema_version": 1, "model": "fixture-13b", "pool": "v5e",
               "generation": "v5e", "chips": 4, "price_per_chip_hr": 1.2,
               "elapsed_s": 100.0, "paid_chip_seconds": 400.0,
               "idle_chip_seconds": 64.0, "handoff_bytes": 1048576,
               "totals": totals,
               "tenants": {"acme": totals, "-": totals}}
    fleet = {"schema_version": 1, "groups": [{
        "model": "fixture-13b", "pool": "v5e", "generation": "v5e",
        "replicas": 2, "requests": 84, "tokens": 16800,
        "chip_seconds": {"queue": 8.4, "prefill": 42.0, "decode": 621.6},
        "cost_dollars": 0.224, "paid_chip_seconds": 800.0,
        "idle_chip_seconds": 128.0, "handoff_bytes": 2097152,
        "utilization": 0.84, "tokens_per_sec_per_chip": 21.0,
        "dollars_per_mtok": 13.33}],
        "tenants": {"acme": {**totals, "dollars_per_mtok": 13.33},
                    "-": {**totals, "dollars_per_mtok": 13.33}},
        "replicas": {"rep-0": replica}, "schema_skews": [],
        "ingested": {"rep-0": 12}}
    train = {"schema_version": 1, "stall_timeout_s": 300.0, "pods": {
        "default/train-0": {"last_step": 120, "stalled": False,
                            "accelerator_type": "v5litepod-8",
                            "generation": "v5e", "chips": 8,
                            "chip_seconds": 960.0,
                            "cost_dollars": 0.32}}}
    return "\n".join(json.dumps(r)
                     for r in (replica, fleet, train)) + "\n"


def _pb_varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _pb_len(field: int, payload: bytes) -> bytes:
    return _pb_varint(field << 3 | 2) + _pb_varint(len(payload)) + payload


def _pb_int(field: int, v: int) -> bytes:
    return _pb_varint(field << 3 | 0) + _pb_varint(v)


def _gen_xplane_pb() -> bytes:
    """A minimal tsl XSpace on the public wire schema xplane_summary.py
    parses: one plane, two ops, three events."""
    events = (_pb_len(4, _pb_int(1, 1) + _pb_int(3, 2_000_000_000))
              + _pb_len(4, _pb_int(1, 1) + _pb_int(3, 1_000_000_000))
              + _pb_len(4, _pb_int(1, 2) + _pb_int(3, 500_000_000)))
    line = _pb_len(2, b"ops") + events
    meta = (_pb_len(4, _pb_int(1, 1)
                    + _pb_len(2, _pb_int(1, 1) + _pb_len(2, b"fusion.1")))
            + _pb_len(4, _pb_int(1, 2)
                      + _pb_len(2, _pb_int(1, 2) + _pb_len(2, b"copy.2"))))
    plane = _pb_len(2, b"/device:TPU:0") + _pb_len(3, line) + meta
    return _pb_len(1, plane)


FIXTURES = {
    "trace_spans.jsonl": _gen_trace_spans,
    "goodput_spans.jsonl": _gen_goodput_spans,
    "fleet.jsonl": _gen_fleet_jsonl,
    "slo.jsonl": _gen_slo_jsonl,
    "costs.jsonl": _gen_costs_jsonl,
    "profile.xplane.pb": _gen_xplane_pb,
}

# (tool module, fixture, extra argv, strings the table must contain)
CASES = [
    ("trace_summary", "trace_spans.jsonl", [],
     ["ttft_s", "serving.request"]),
    ("goodput_summary", "goodput_spans.jsonl", ["--steps"],
     ["goodput waterfall", "restart_lost", "straggler"]),
    ("fleet_summary", "fleet.jsonl", [],
     ["rep-", "scale up"]),
    ("slo_summary", "slo.jsonl", [],
     ["BURNING", "step waterfall", "decode_step"]),
    ("cost_summary", "costs.jsonl", [],
     ["cost headline", "fixture-13b", "acme", "train-0"]),
    ("xplane_summary", "profile.xplane.pb", [],
     ["TPU:0", "fusion.1"]),
]


def write_fixtures() -> list[str]:
    FIXDIR.mkdir(exist_ok=True)
    written = []
    for name, gen in FIXTURES.items():
        content = gen()
        path = FIXDIR / name
        if isinstance(content, bytes):
            path.write_bytes(content)
        else:
            path.write_text(content, encoding="utf-8")
        written.append(str(path))
    return written


@pytest.mark.parametrize("tool,fixture,extra,expect",
                         CASES, ids=[c[0] for c in CASES])
def test_summary_tool_renders_fixture(tool, fixture, extra, expect,
                                      capsys):
    path = FIXDIR / fixture
    assert path.exists(), (
        f"missing fixture {path} — regenerate with "
        f"`python tests/test_tools_smoke.py --write-fixture`")
    mod = __import__(tool)
    rc = mod.main([str(path), *extra])
    out = capsys.readouterr().out
    assert rc == 0, f"{tool} exited {rc} on its committed fixture"
    assert out.strip(), f"{tool} printed nothing"
    for needle in expect:
        assert needle in out, (
            f"{tool} output lost {needle!r}:\n{out}")


def test_fixtures_match_generators():
    """Committed fixtures are generator OUTPUT, not hand edits: a format
    change regenerates them (--write-fixture) in the same diff."""
    for name, gen in FIXTURES.items():
        path = FIXDIR / name
        assert path.exists(), f"missing fixture {path}"
        want = gen()
        got = path.read_bytes() if isinstance(want, bytes) \
            else path.read_text(encoding="utf-8")
        assert got == want, (
            f"{path} drifted from its generator — regenerate with "
            f"`python tests/test_tools_smoke.py --write-fixture`")


if __name__ == "__main__":
    if "--write-fixture" in sys.argv:
        for p in write_fixtures():
            print(f"wrote {p}")
    else:
        print(__doc__)
        raise SystemExit(2)
