"""HF checkpoint import: logits parity against the `transformers` reference
implementations on randomly-initialized tiny models of every supported family.

This is the strongest architecture-fidelity test in the repo: it pins the RoPE
convention, GQA layout, norm placement/centering, activation, embedding
scaling/tying, qkv bias, and MoE routing all at once — any mismatch shows up
as diverged logits. (The reference framework has no model code to compare
against, SURVEY.md §2.4; `transformers` is the de-facto ground truth for these
architectures.)
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from k8s_runpod_kubelet_tpu.models import LlamaModel, tiny_llama, tiny_moe
from k8s_runpod_kubelet_tpu.models.convert import (from_hf_state_dict, load_hf,
                                                   to_hf_state_dict)

import pytest as _pytest

# ML tier: jax compiles dominate runtime; excluded by -m 'not slow'
pytestmark = _pytest.mark.slow

B, S = 2, 16


def _f32(cfg):
    return dataclasses.replace(cfg, dtype=jnp.float32,
                               param_dtype=jnp.float32, remat=False)


def _tokens(vocab):
    rng = np.random.default_rng(0)
    return rng.integers(0, vocab, (B, S)).astype(np.int32)


def _compare(cfg, hf_model, atol=3e-4):
    hf_model.eval()
    toks = _tokens(cfg.vocab_size)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    params = load_hf(cfg, hf_model)
    ours = np.asarray(LlamaModel(cfg).forward(params, jnp.asarray(toks)))
    np.testing.assert_allclose(ours, ref, atol=atol, rtol=3e-4)


class TestLogitsParity:
    def test_llama_gqa(self):
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-5, tie_word_embeddings=False,
            attn_implementation="eager"))
        cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                              n_heads=4, n_kv_heads=2, mlp_dim=112,
                              max_seq_len=64, rope_theta=10_000.0))
        _compare(cfg, hf)

    def test_llama31_ntk_rope_scaling(self):
        """Pins ops/rope.py's NTK frequency warp against HF's llama3 rope
        scaling — S=48 spans positions past original_max_position/4 so the
        warped low frequencies actually matter."""
        torch.manual_seed(7)
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, rope_theta=500_000.0,
            rope_scaling={"rope_type": "llama3", "factor": 8.0,
                          "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                          "original_max_position_embeddings": 64},
            rms_norm_eps=1e-5, tie_word_embeddings=False,
            attn_implementation="eager"))
        cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                              n_heads=4, n_kv_heads=2, mlp_dim=112,
                              max_seq_len=128, rope_theta=500_000.0,
                              rope_scaling={"factor": 8.0,
                                            "low_freq_factor": 1.0,
                                            "high_freq_factor": 4.0,
                                            "original_max_position": 64}))
        hf.eval()
        rng = np.random.default_rng(1)
        toks = rng.integers(0, 128, (2, 48)).astype(np.int32)
        with torch.no_grad():
            ref = hf(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        params = load_hf(cfg, hf)
        ours = np.asarray(LlamaModel(cfg).forward(params, jnp.asarray(toks)))
        np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-4)

    def test_qwen2_with_qkv_bias(self):
        torch.manual_seed(1)
        hf = transformers.Qwen2ForCausalLM(transformers.Qwen2Config(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-6, tie_word_embeddings=False,
            attn_implementation="eager"))
        # Qwen2 puts bias on q/k/v projections — make sure the checkpoint
        # really has them, then require our config to carry them over
        assert "model.layers.0.self_attn.q_proj.bias" in hf.state_dict()
        cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                              n_heads=4, n_kv_heads=2, mlp_dim=112,
                              max_seq_len=64, rope_theta=10_000.0,
                              norm_eps=1e-6, qkv_bias=True))
        _compare(cfg, hf)

    def test_gemma_tied_gelu_zero_centered_norm(self):
        torch.manual_seed(2)
        hf = transformers.GemmaForCausalLM(transformers.GemmaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
            head_dim=16, max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-6, hidden_activation="gelu_pytorch_tanh",
            attn_implementation="eager"))
        cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                              n_heads=4, n_kv_heads=4, head_dim=16,
                              mlp_dim=112, max_seq_len=64,
                              rope_theta=10_000.0, norm_eps=1e-6,
                              tie_embeddings=True, mlp_activation="gelu_tanh",
                              embed_scale=True, norm_zero_centered=True))
        _compare(cfg, hf, atol=1e-3)  # sqrt(E)-scaled embeddings amplify eps

    def test_gemma2_interleave_softcaps_sandwich_norms(self):
        """Gemma-2 pins the hardest feature set at once: alternating
        local/global attention (layer 0 sliding in HF), tanh soft caps on
        attention scores and final logits, query_pre_attn_scalar scaling,
        and pre+post sandwich norms. S=16 > W=8 so the window binds."""
        torch.manual_seed(4)
        hf = transformers.Gemma2ForCausalLM(transformers.Gemma2Config(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-6, hidden_activation="gelu_pytorch_tanh",
            query_pre_attn_scalar=32.0, sliding_window=8,
            attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
            attn_implementation="eager"))
        cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=4,
                              n_heads=4, n_kv_heads=2, head_dim=16,
                              mlp_dim=112, max_seq_len=64,
                              rope_theta=10_000.0, norm_eps=1e-6,
                              tie_embeddings=True, mlp_activation="gelu_tanh",
                              embed_scale=True, norm_zero_centered=True,
                              attn_logit_softcap=50.0, logit_softcap=30.0,
                              query_pre_attn_scalar=32.0, sliding_window=8,
                              sliding_window_pattern=2, post_norms=True))
        _compare(cfg, hf, atol=1e-3)

    def test_gemma3_qk_norm_dual_rope(self):
        """Gemma-3 pins qk-norm (RMSNorm on q/k before RoPE), per-kind RoPE
        bases (local vs global), linear rope scaling on global layers, and
        the 5:1 local/global interleave."""
        torch.manual_seed(5)
        hf = transformers.Gemma3ForCausalLM(transformers.Gemma3TextConfig(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=6, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, max_position_embeddings=64,
            rope_theta=100_000.0, rope_local_base_freq=10_000.0,
            rope_scaling={"rope_type": "linear", "factor": 2.0},
            rms_norm_eps=1e-6, hidden_activation="gelu_pytorch_tanh",
            query_pre_attn_scalar=32.0, sliding_window=8,
            layer_types=["sliding_attention"] * 5 + ["full_attention"],
            attn_implementation="eager"))
        cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=6,
                              n_heads=4, n_kv_heads=2, head_dim=16,
                              mlp_dim=112, max_seq_len=64,
                              rope_theta=100_000.0, rope_local_theta=10_000.0,
                              rope_scaling={"rope_type": "linear",
                                            "factor": 2.0},
                              norm_eps=1e-6, tie_embeddings=True,
                              mlp_activation="gelu_tanh",
                              embed_scale=True, norm_zero_centered=True,
                              query_pre_attn_scalar=32.0, sliding_window=8,
                              sliding_window_pattern=6, post_norms=True,
                              qk_norm=True))
        _compare(cfg, hf, atol=1e-3)

    def test_mixtral_sparse_moe(self):
        torch.manual_seed(3)
        hf = transformers.MixtralForCausalLM(transformers.MixtralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            num_local_experts=4, num_experts_per_tok=2,
            max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-5, tie_word_embeddings=False,
            attn_implementation="eager"))
        # capacity n_experts/k = no token ever drops — required for exact
        # parity with HF's dense expert loop
        cfg = _f32(tiny_moe(vocab_size=128, embed_dim=64, n_layers=2,
                            n_heads=4, n_kv_heads=2, mlp_dim=96,
                            max_seq_len=64, rope_theta=10_000.0,
                            n_experts=4, n_experts_per_tok=2,
                            capacity_factor=2.0))
        _compare(cfg, hf)


class TestRoundTrip:
    def test_export_import_identity(self):
        import jax
        from k8s_runpod_kubelet_tpu.models import init_params
        cfg = _f32(tiny_llama(vocab_size=64, embed_dim=32, n_layers=2,
                              n_heads=2, n_kv_heads=1, mlp_dim=48,
                              qkv_bias=True))
        params = init_params(cfg, jax.random.PRNGKey(0))
        sd = to_hf_state_dict(cfg, params)
        back = from_hf_state_dict(cfg, sd)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            params, back)

    def test_tied_checkpoint_into_untied_config(self):
        """A tied-embedding checkpoint (no lm_head key) must load into an
        untied config by materializing the tie."""
        torch.manual_seed(4)
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48,
            num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
            tie_word_embeddings=True, attn_implementation="eager"))
        sd = {k: v for k, v in hf.state_dict().items() if k != "lm_head.weight"}
        cfg = _f32(tiny_llama(vocab_size=64, embed_dim=32, n_layers=1,
                              n_heads=2, n_kv_heads=2, mlp_dim=48))
        params = from_hf_state_dict(cfg, sd)
        np.testing.assert_allclose(np.asarray(params["lm_head"]),
                                   np.asarray(params["tok_embed"]).T)


class TestDirectoryLoading:
    def test_load_from_safetensors_dir(self, tmp_path):
        """load_hf(path): a save_pretrained directory (safetensors) loads and
        produces the same logits as the in-memory state dict."""
        torch.manual_seed(5)
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48,
            num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
            tie_word_embeddings=False, attn_implementation="eager"))
        hf.save_pretrained(tmp_path, safe_serialization=True)
        cfg = _f32(tiny_llama(vocab_size=64, embed_dim=32, n_layers=2,
                              n_heads=2, n_kv_heads=1, mlp_dim=48))
        from_dir = load_hf(cfg, str(tmp_path))
        from_mem = load_hf(cfg, hf)
        import jax
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6),
            from_dir, from_mem)


class TestHostPlacement:
    def test_load_hf_returns_host_arrays(self):
        """Leaves must stay numpy (host): a model bigger than one chip's HBM
        must never materialize on device 0 before the caller shards it."""
        torch.manual_seed(6)
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48,
            num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
            tie_word_embeddings=False, attn_implementation="eager"))
        import jax
        params = load_hf(_f32(tiny_llama(vocab_size=64, embed_dim=32,
                                         n_layers=1, n_heads=2, n_kv_heads=2,
                                         mlp_dim=48)), hf)
        for leaf in jax.tree_util.tree_leaves(params):
            assert isinstance(leaf, np.ndarray), type(leaf)

    def test_trainer_initial_params_sharded_onto_mesh(self):
        """Trainer(initial_params=...) commits the host tree with the same
        shardings init_params would use, and trains from it."""
        import jax
        from k8s_runpod_kubelet_tpu.parallel import MeshConfig, make_mesh
        from k8s_runpod_kubelet_tpu.workloads.train import TrainConfig, Trainer
        torch.manual_seed(7)
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48,
            num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
            tie_word_embeddings=False, attn_implementation="eager"))
        cfg = _f32(tiny_llama(vocab_size=64, embed_dim=32, n_layers=2,
                              n_heads=2, n_kv_heads=2, mlp_dim=48,
                              max_seq_len=64))
        mesh = make_mesh(MeshConfig(data=-1, fsdp=2, tensor=2))
        host = load_hf(cfg, hf)
        tr = Trainer(cfg, TrainConfig(batch_size=4, seq_len=16, steps=2),
                     mesh=mesh, initial_params=host)
        ref = Trainer(cfg, TrainConfig(batch_size=4, seq_len=16, steps=2),
                      mesh=mesh)
        shard_of = lambda t: jax.tree_util.tree_map(lambda x: x.sharding, t)
        assert shard_of(tr.params) == shard_of(ref.params)
        out = tr.run(steps=2)
        assert np.isfinite(out["final_loss"])


class TestMistralSlidingWindow:
    def test_mistral_window_logits_parity(self):
        """Window (8) < sequence (16): parity proves the sliding-window mask
        matches HF Mistral's, not just the weight mapping."""
        torch.manual_seed(8)
        hf = transformers.MistralForCausalLM(transformers.MistralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-5, sliding_window=8, tie_word_embeddings=False,
            attn_implementation="eager"))
        cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                              n_heads=4, n_kv_heads=2, mlp_dim=112,
                              max_seq_len=64, rope_theta=10_000.0,
                              sliding_window=8))
        _compare(cfg, hf)


class TestDeepseekV2Parity:
    """MLA + DeepSeek-MoE fidelity, proven against transformers'
    DeepseekV2ForCausalLM: pair-interleaved RoPE -> rotate-half
    permutation, kv_a_layernorm (latent norm), kv_b split into
    w_uk/w_uv, softmax-without-topk-renorm routing, fused shared
    experts. first_k_dense_replace=0 here — the real Lite checkpoint's
    single leading dense layer is the documented config divergence and
    the loader rejects it loudly."""

    def _tiny(self, n_experts=0, n_shared=0):
        from transformers.models.deepseek_v2 import DeepseekV2Config
        from transformers.models.deepseek_v2.modeling_deepseek_v2 import (
            DeepseekV2ForCausalLM)
        from k8s_runpod_kubelet_tpu.models import tiny_mla
        torch.manual_seed(3)
        hf = DeepseekV2ForCausalLM(DeepseekV2Config(
            vocab_size=128, hidden_size=64,
            intermediate_size=112, moe_intermediate_size=48,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, kv_lora_rank=32, q_lora_rank=None,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            n_routed_experts=n_experts or 1, n_shared_experts=n_shared,
            num_experts_per_tok=2, first_k_dense_replace=0 if n_experts
            else 99, norm_topk_prob=False, routed_scaling_factor=1.0,
            max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-6, tie_word_embeddings=False,
            attention_bias=False, attn_implementation="eager"))
        if n_experts:
            # decisive routing: a freshly-initialized gate scores experts
            # within ~1e-6 of each other, so torch and jax pick DIFFERENT
            # top-k on f32 noise (observed: 15/32 tokens agreed, sorted
            # weights within 5e-7). Scaling the gate separates the scores;
            # the parity claim is about semantics, not tie-breaking.
            # (the gate Parameter is torch.empty — never initialized by
            # _init_weights — so its garbage values can be near-uniform)
            with torch.no_grad():
                for layer in hf.model.layers:
                    layer.mlp.gate.weight.normal_(0.0, 1.0,
                                                  generator=torch.Generator()
                                                  .manual_seed(11))
        cfg = _f32(tiny_mla(
            vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
            n_kv_heads=4, head_dim=16, mla_latent_dim=32, mla_rope_dim=8,
            mlp_dim=48 if n_experts else 112, max_seq_len=64,
            rope_theta=10_000.0, norm_eps=1e-6,
            n_experts=n_experts, n_experts_per_tok=2,
            n_shared_experts=n_shared, router_norm_topk=False))
        return cfg, hf

    def test_mla_dense_mlp(self):
        # first_k_dense_replace=99 => every layer dense: isolates the MLA
        # attention mapping (rope permute, latent norm, kv_b split)
        cfg, hf = self._tiny()
        _compare(cfg, hf)

    def test_mla_moe_shared_experts(self):
        """Routing near-ties are legitimate divergence: when two experts
        score within f32 noise, torch and jax may pick different ones and
        BOTH are correct — so this comparison allows a couple of flipped
        TOKEN ROWS and requires tight parity everywhere else (the routed
        module itself matches to 2.6e-4 standalone; see git history)."""
        cfg, hf = self._tiny(n_experts=4, n_shared=2)
        hf.eval()
        toks = _tokens(cfg.vocab_size)
        with torch.no_grad():
            ref = hf(torch.from_numpy(
                toks.astype(np.int64))).logits.numpy()
        params = load_hf(cfg, hf)
        ours = np.asarray(LlamaModel(cfg).forward(params, jnp.asarray(toks)))
        bad = np.abs(ours - ref) > 3e-3          # (B, S, V)
        flipped_rows = np.any(bad, axis=-1).sum()
        assert flipped_rows <= 2, (
            f"{flipped_rows} token rows diverged — more than routing "
            "near-ties explain")
        ok = ~np.any(bad, axis=-1)
        np.testing.assert_allclose(ours[ok], ref[ok], atol=5e-4, rtol=5e-4)

    def test_mla_decode_from_imported_weights(self):
        """Imported weights drive the ABSORBED latent-cache decode:
        greedy continuation matches the HF reference's."""
        cfg, hf = self._tiny()
        params = load_hf(cfg, hf)
        model = LlamaModel(cfg)
        toks = _tokens(cfg.vocab_size)[:1]
        cache = model.init_cache(1, 48)
        logits, cache = model.prefill(params, jnp.asarray(toks), cache)
        ours = []
        tok = jnp.argmax(logits, -1)
        for _ in range(5):
            ours.append(int(tok[0]))
            logits, cache = model.decode_step(params, tok, cache)
            tok = jnp.argmax(logits, -1)
        with torch.no_grad():
            ids = torch.from_numpy(toks.astype(np.int64))
            theirs = []
            for _ in range(5):
                nxt = hf(ids).logits[:, -1].argmax(-1)
                theirs.append(int(nxt[0]))
                ids = torch.cat([ids, nxt[:, None]], dim=1)
        assert ours == theirs

    def test_roundtrip_export(self):
        cfg, hf = self._tiny(n_experts=4, n_shared=2)
        params = load_hf(cfg, hf)
        sd2 = to_hf_state_dict(cfg, params)
        params2 = from_hf_state_dict(cfg, sd2)
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(params2)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=1e-6, atol=1e-6)

    def _real_shape(self):
        """The REAL V2-Lite layer layout: first_k_dense_replace=1 (dense
        layer 0 at the wide MLP), MoE above it."""
        from transformers.models.deepseek_v2 import DeepseekV2Config
        from transformers.models.deepseek_v2.modeling_deepseek_v2 import (
            DeepseekV2ForCausalLM)
        from k8s_runpod_kubelet_tpu.models import tiny_mla
        torch.manual_seed(3)
        hf = DeepseekV2ForCausalLM(DeepseekV2Config(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            moe_intermediate_size=48, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=32,
            q_lora_rank=None, qk_nope_head_dim=16, qk_rope_head_dim=8,
            v_head_dim=16, n_routed_experts=4, n_shared_experts=2,
            num_experts_per_tok=2, first_k_dense_replace=1,
            norm_topk_prob=False, routed_scaling_factor=1.0,
            max_position_embeddings=64, rope_theta=10_000.0,
            rms_norm_eps=1e-6, tie_word_embeddings=False,
            attention_bias=False, attn_implementation="eager"))
        with torch.no_grad():  # decisive routing (empty-init gate)
            for layer in hf.model.layers[1:]:
                layer.mlp.gate.weight.normal_(
                    0.0, 1.0, generator=torch.Generator().manual_seed(11))
        cfg = _f32(tiny_mla(
            vocab_size=128, embed_dim=64, n_layers=3, n_heads=4,
            n_kv_heads=4, head_dim=16, mla_latent_dim=32, mla_rope_dim=8,
            mlp_dim=48, max_seq_len=64, rope_theta=10_000.0, norm_eps=1e-6,
            n_experts=4, n_experts_per_tok=2, n_shared_experts=2,
            router_norm_topk=False, n_dense_prefix=1,
            dense_prefix_mlp_dim=112,
            # no-drop capacity so the TRAIN-mode forward (used as the
            # prefill reference below) routes like inference does
            capacity_factor=2.0))
        return cfg, hf

    def test_first_k_dense_real_shape_parity(self):
        """Real V2-Lite checkpoints LOAD now (n_dense_prefix): dense layer
        0 rides a separate prefix_layers stack scanned before the MoE
        stack; logits match the HF reference (flip-tolerant on routing
        near-ties, like the uniform-MoE test)."""
        cfg, hf = self._real_shape()
        hf.eval()
        toks = _tokens(cfg.vocab_size)
        with torch.no_grad():
            ref = hf(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        params = load_hf(cfg, hf)
        assert "prefix_layers" in params
        assert params["prefix_layers"]["w_gate"].shape == (1, 64, 112)
        ours = np.asarray(LlamaModel(cfg).forward(params, jnp.asarray(toks)))
        bad = np.abs(ours - ref) > 3e-3
        assert np.any(bad, axis=-1).sum() <= 4   # routing near-ties only
        ok = ~np.any(bad, axis=-1)
        np.testing.assert_allclose(ours[ok], ref[ok], atol=5e-4, rtol=5e-4)

    def test_first_k_dense_roundtrip_and_decode(self):
        cfg, hf = self._real_shape()
        params = load_hf(cfg, hf)
        sd2 = to_hf_state_dict(cfg, params)
        params2 = from_hf_state_dict(cfg, sd2)
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(params2)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=1e-6, atol=1e-6)
        # absorbed decode from the latent cache, prefix rows included
        model = LlamaModel(cfg)
        toks = _tokens(cfg.vocab_size)[:1]
        cache = model.init_cache(1, 48)
        # prefix layers cache in their OWN sections (donation-friendly)
        assert cache["c"].shape[0] == 2 and cache["c_pre"].shape[0] == 1
        logits, cache = model.prefill(params, jnp.asarray(toks), cache)
        full = model.forward(params, jnp.asarray(toks))
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1]),
                                   rtol=1e-3, atol=1e-3)

    def test_q_lora_rank_parity(self):
        """Low-rank q (DeepSeek-V2-full/V3's q_lora_rank): q_a_proj +
        q_a_layernorm + q_b_proj map to wq_a/q_a_norm/wq_b with the rope
        de-interleave on wq_b — logits parity against the HF reference."""
        from transformers.models.deepseek_v2 import DeepseekV2Config
        from transformers.models.deepseek_v2.modeling_deepseek_v2 import (
            DeepseekV2ForCausalLM)
        from k8s_runpod_kubelet_tpu.models import tiny_mla
        torch.manual_seed(5)
        hf = DeepseekV2ForCausalLM(DeepseekV2Config(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            moe_intermediate_size=48, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=32,
            q_lora_rank=24, qk_nope_head_dim=16, qk_rope_head_dim=8,
            v_head_dim=16, n_routed_experts=1, n_shared_experts=None,
            num_experts_per_tok=2, first_k_dense_replace=99,  # all dense
            norm_topk_prob=False, max_position_embeddings=64,
            rope_theta=10_000.0, rms_norm_eps=1e-6,
            tie_word_embeddings=False, attention_bias=False,
            attn_implementation="eager"))
        cfg = _f32(tiny_mla(
            vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
            n_kv_heads=4, head_dim=16, mla_latent_dim=32, mla_rope_dim=8,
            mla_q_lora_rank=24, mlp_dim=112, max_seq_len=64,
            rope_theta=10_000.0, norm_eps=1e-6))
        _compare(cfg, hf)
        # round-trip with the low-rank q leaves
        params = load_hf(cfg, hf)
        assert "w_qa" in params["layers"] and "wq" not in params["layers"]
        sd2 = to_hf_state_dict(cfg, params)
        params2 = from_hf_state_dict(cfg, sd2)
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(params2)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=1e-6, atol=1e-6)

    def test_q_lora_mismatch_rejected(self):
        cfg_full, hf_full = self._tiny()      # full-rank q checkpoint
        import dataclasses as _dc
        with pytest.raises(NotImplementedError, match="full-rank"):
            load_hf(_dc.replace(cfg_full, mla_q_lora_rank=24), hf_full)

    def test_prefix_mismatch_rejected_loudly(self):
        """Config says uniform MoE but the checkpoint has a dense layer 0
        (or vice versa): metadata-level rejection with the fix named."""
        cfg_real, hf_real = self._real_shape()
        cfg_uniform, _ = self._tiny(n_experts=4, n_shared=2)
        import dataclasses as _dc
        cfg3 = _dc.replace(cfg_uniform, n_layers=3)
        with pytest.raises(NotImplementedError, match="n_dense_prefix"):
            load_hf(cfg3, hf_real)          # uniform cfg, prefixed ckpt
        _, hf_uniform = self._tiny(n_experts=4, n_shared=2)
        cfg2 = _dc.replace(cfg_real, n_layers=2)
        with pytest.raises(NotImplementedError, match="n_dense_prefix"):
            load_hf(cfg2, hf_uniform)       # prefixed cfg, uniform ckpt


class TestDeepseekV3Parity:
    """V3 routing (sigmoid + e_score_correction_bias + group-limited
    top-k + renorm + routed_scaling) and the full V3 attention stack
    (MLA + low-rank q) against transformers' DeepseekV3ForCausalLM."""

    def _tiny(self, first_k_dense=0):
        from transformers.models.deepseek_v3 import DeepseekV3Config
        from transformers.models.deepseek_v3.modeling_deepseek_v3 import (
            DeepseekV3ForCausalLM)
        from k8s_runpod_kubelet_tpu.models import tiny_mla
        torch.manual_seed(6)
        hf = DeepseekV3ForCausalLM(DeepseekV3Config(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            moe_intermediate_size=48, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=32,
            q_lora_rank=24, qk_nope_head_dim=16, qk_rope_head_dim=8,
            v_head_dim=16, n_routed_experts=8, n_shared_experts=1,
            num_experts_per_tok=2, n_group=4, topk_group=2,
            norm_topk_prob=True, routed_scaling_factor=2.5,
            first_k_dense_replace=first_k_dense,
            max_position_embeddings=64, rope_theta=10_000.0,
            rope_scaling=None, rms_norm_eps=1e-6,
            tie_word_embeddings=False, attention_bias=False,
            attn_implementation="eager"))
        with torch.no_grad():
            gen = torch.Generator().manual_seed(13)
            for layer in hf.model.layers[first_k_dense:]:
                # gate weight is torch.empty; the bias buffer starts 0 —
                # give both real values so routing is decisive AND the
                # bias-corrected selection actually differs from raw
                layer.mlp.gate.weight.normal_(0.0, 1.0, generator=gen)
                layer.mlp.gate.e_score_correction_bias.normal_(
                    0.0, 0.3, generator=gen)
        cfg = _f32(tiny_mla(
            vocab_size=128, embed_dim=64, n_layers=3, n_heads=4,
            n_kv_heads=4, head_dim=16, mla_latent_dim=32, mla_rope_dim=8,
            mla_q_lora_rank=24, mlp_dim=48, max_seq_len=64,
            rope_theta=10_000.0, norm_eps=1e-6,
            n_experts=8, n_experts_per_tok=2, n_shared_experts=1,
            router_norm_topk=True, router_sigmoid_bias=True,
            router_n_group=4, router_topk_group=2,
            routed_scaling_factor=2.5, capacity_factor=4.0,
            n_dense_prefix=first_k_dense,
            dense_prefix_mlp_dim=112 if first_k_dense else None))
        return cfg, hf

    def _flip_tolerant_compare(self, cfg, hf, max_flips=4):
        hf.eval()
        toks = _tokens(cfg.vocab_size)
        with torch.no_grad():
            ref = hf(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        params = load_hf(cfg, hf)
        ours = np.asarray(LlamaModel(cfg).forward(params, jnp.asarray(toks)))
        bad = np.abs(ours - ref) > 3e-3
        assert np.any(bad, axis=-1).sum() <= max_flips
        ok = ~np.any(bad, axis=-1)
        np.testing.assert_allclose(ours[ok], ref[ok], atol=5e-4, rtol=5e-4)
        return params

    def test_v3_routing_parity(self):
        cfg, hf = self._tiny()
        params = self._flip_tolerant_compare(cfg, hf)
        assert "router_bias" in params["layers"]

    def test_v3_real_shape_with_dense_prefix(self):
        cfg, hf = self._tiny(first_k_dense=1)
        self._flip_tolerant_compare(cfg, hf)

    def test_v3_roundtrip(self):
        cfg, hf = self._tiny()
        params = load_hf(cfg, hf)
        sd2 = to_hf_state_dict(cfg, params)
        params2 = from_hf_state_dict(cfg, sd2)
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(params2)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=1e-6, atol=1e-6)

    def test_deepseek_v3_factory_param_count(self):
        from k8s_runpod_kubelet_tpu.models import deepseek_v3
        assert deepseek_v3().param_count == pytest.approx(671e9, rel=0.01)


def test_v3_checkpoint_with_lite_config_rejected_on_metadata():
    """The error a real V2-full/V3 checkpoint hits FIRST with a
    V2-Lite-shaped config (full-rank q expected, q_a_proj present):
    metadata-level NotImplementedError naming the fix, not a KeyError
    mid-conversion."""
    from k8s_runpod_kubelet_tpu.models import tiny_mla
    from k8s_runpod_kubelet_tpu.models.convert import load_hf
    cfg = _f32(tiny_mla(vocab_size=128, embed_dim=64, n_layers=1,
                        n_heads=4, n_kv_heads=4, head_dim=16,
                        mla_latent_dim=32, mla_rope_dim=8, mlp_dim=48))
    sd = {"model.layers.0.self_attn.q_a_proj.weight":
          np.ones((24, 64), np.float32)}
    with pytest.raises(NotImplementedError, match="mla_q_lora_rank"):
        load_hf(cfg, sd)


def test_v3_routing_fields_validated():
    from k8s_runpod_kubelet_tpu.models import tiny_mla
    from k8s_runpod_kubelet_tpu.models.llama import init_params
    import jax
    with pytest.raises(ValueError, match="router_n_group"):
        init_params(tiny_mla(n_experts=8, router_sigmoid_bias=True),
                    jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="n_experts > 0"):
        init_params(tiny_mla(router_sigmoid_bias=True),
                    jax.random.PRNGKey(0))


def test_deepseek_yarn_rope_parity():
    """Real DeepSeek checkpoints ship rope_scaling type 'yarn' (V2-Lite:
    factor 40 past 4k). Pin ops/rope.py's yarn branch against the HF
    reference with S well past original_max_position_embeddings, incl.
    the mscale/mscale_all_dim attention factor."""
    from transformers.models.deepseek_v2 import DeepseekV2Config
    from transformers.models.deepseek_v2.modeling_deepseek_v2 import (
        DeepseekV2ForCausalLM)
    from k8s_runpod_kubelet_tpu.models import tiny_mla
    torch.manual_seed(9)
    # no mscale keys -> attention_factor = 0.1*ln(4)+1 = 1.139: a yarn
    # branch that dropped the cos/sin scaling would fail this (DeepSeek's
    # shipped mscale == mscale_all_dim makes the factor 1.0 — covered by
    # the same formula but it would hide that bug)
    yarn = {"rope_type": "yarn", "factor": 4.0, "beta_fast": 32,
            "beta_slow": 1,
            "original_max_position_embeddings": 16}
    hf = DeepseekV2ForCausalLM(DeepseekV2Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        moe_intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=32,
        q_lora_rank=None, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16, n_routed_experts=1, n_shared_experts=None,
        num_experts_per_tok=2, first_k_dense_replace=99,
        norm_topk_prob=False, max_position_embeddings=64,
        rope_theta=10_000.0, rope_scaling=dict(yarn), rms_norm_eps=1e-6,
        tie_word_embeddings=False, attention_bias=False,
        attn_implementation="eager"))
    hf.eval()
    cfg = _f32(tiny_mla(
        vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
        n_kv_heads=4, head_dim=16, mla_latent_dim=32, mla_rope_dim=8,
        mlp_dim=112, max_seq_len=64, rope_theta=10_000.0, norm_eps=1e-6,
        rope_scaling=dict(yarn)))
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 128, (2, 48)).astype(np.int32)  # past orig=16
    with torch.no_grad():
        ref = hf(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    params = load_hf(cfg, hf)
    ours = np.asarray(LlamaModel(cfg).forward(params, jnp.asarray(toks)))
    np.testing.assert_allclose(ours, ref, atol=5e-4, rtol=5e-4)


def test_v3_yarn_mscale_attention_scale_parity():
    """YaRN's OTHER half: mscale_all_dim multiplies the attention softmax
    scale by yarn_get_mscale(factor, mscale_all_dim)^2. Pinned against
    DeepseekV3ForCausalLM (which applies it; transformers' V2 class
    omits it — we follow the original-checkpoint semantics)."""
    from transformers.models.deepseek_v3 import DeepseekV3Config
    from transformers.models.deepseek_v3.modeling_deepseek_v3 import (
        DeepseekV3ForCausalLM)
    from k8s_runpod_kubelet_tpu.models import tiny_mla
    torch.manual_seed(8)
    yarn = {"rope_type": "yarn", "factor": 4.0, "beta_fast": 32,
            "beta_slow": 1, "mscale": 1.0, "mscale_all_dim": 1.0,
            "original_max_position_embeddings": 16}
    hf = DeepseekV3ForCausalLM(DeepseekV3Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        moe_intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=32,
        q_lora_rank=24, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16, n_routed_experts=8, n_shared_experts=1,
        num_experts_per_tok=2, n_group=4, topk_group=2,
        norm_topk_prob=True, routed_scaling_factor=2.5,
        first_k_dense_replace=99,  # all dense: isolate attention scaling
        max_position_embeddings=64, rope_theta=10_000.0,
        rope_scaling=dict(yarn), rms_norm_eps=1e-6,
        tie_word_embeddings=False, attention_bias=False,
        attn_implementation="eager"))
    hf.eval()
    cfg = _f32(tiny_mla(
        vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
        n_kv_heads=4, head_dim=16, mla_latent_dim=32, mla_rope_dim=8,
        mla_q_lora_rank=24, mlp_dim=112, max_seq_len=64,
        rope_theta=10_000.0, norm_eps=1e-6, rope_scaling=dict(yarn)))
    rng = np.random.default_rng(4)
    toks = rng.integers(0, 128, (2, 48)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    params = load_hf(cfg, hf)
    ours = np.asarray(LlamaModel(cfg).forward(params, jnp.asarray(toks)))
    # mscale^2 at factor 4 is 1.139^2 = 1.30: omitting it fails loudly
    np.testing.assert_allclose(ours, ref, atol=5e-4, rtol=5e-4)


def test_qwen3_qk_norm_parity():
    """Qwen3: per-head-dim RMSNorm on q/k before RoPE, no biases — maps
    onto the qk_norm flag; logits parity against Qwen3ForCausalLM."""
    torch.manual_seed(10)
    hf = transformers.Qwen3ForCausalLM(transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rope_theta=10_000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attn_implementation="eager"))
    assert "model.layers.0.self_attn.q_norm.weight" in hf.state_dict()
    cfg = _f32(tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                          n_heads=4, n_kv_heads=2, head_dim=16,
                          mlp_dim=112, max_seq_len=64,
                          rope_theta=10_000.0, norm_eps=1e-6,
                          qk_norm=True))
    _compare(cfg, hf)


def test_qwen3_8b_config_faithful():
    from k8s_runpod_kubelet_tpu.models import qwen3_8b
    cfg = qwen3_8b()
    assert cfg.qk_norm and not cfg.qkv_bias
    assert cfg.param_count == pytest.approx(8.2e9, rel=0.02)
