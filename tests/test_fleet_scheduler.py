"""Unit tests for the heterogeneity-aware fleet scheduler (ISSUE 19):
pool-spec parsing, the throughput matrix's seed/EWMA/sibling-transfer
ladder, goodput-per-dollar placement, best-effort packing + preemption
ordering, idempotency, restart adoption, and the telemetry refinement
hooks — plus the registry's generation/pool round-trip and the
fleet_summary rendering of the new columns.
"""

from __future__ import annotations

import types

import pytest

from k8s_runpod_kubelet_tpu.fleet.scheduler import (
    DECODE, HETERO, PREFILL, ROUND_ROBIN, TRAINING, UNIFIED, FleetScheduler,
    PoolSpecError, ThroughputMatrix, parse_pools)
from k8s_runpod_kubelet_tpu.generations import GENERATIONS
from k8s_runpod_kubelet_tpu.metrics import Metrics
from k8s_runpod_kubelet_tpu.provider.annotations import Annotations as A
from k8s_runpod_kubelet_tpu.tracing import Tracer

from harness import FakeClock


# -- pool spec parsing ---------------------------------------------------------

def test_parse_pools():
    pools = parse_pools("v5e:32, v5p:64")
    assert [(p.name, p.generation, p.total_chips) for p in pools] == \
        [("v5e", "v5e", 32), ("v5p", "v5p", 64)]
    assert pools[0].spec is GENERATIONS["v5e"]


def test_parse_pools_named():
    pools = parse_pools("edge=v5e:16,bulk=v5e:64")
    assert [(p.name, p.generation) for p in pools] == \
        [("edge", "v5e"), ("bulk", "v5e")]


@pytest.mark.parametrize("spec,msg", [
    ("bogus:8", "unknown generation"),
    ("v5e:eight", "not an int"),
    ("v5e:0", "must be > 0"),
    ("v5e:8,v5e:16", "duplicate pool name"),
])
def test_parse_pools_rejects(spec, msg):
    with pytest.raises(PoolSpecError, match=msg):
        parse_pools(spec)


# -- throughput matrix ---------------------------------------------------------

def test_matrix_roofline_seeds():
    m = ThroughputMatrix()
    v5e, v5p = GENERATIONS["v5e"], GENERATIONS["v5p"]
    assert m.effective(PREFILL, "v5e") == v5e.peak_tflops_bf16
    assert m.effective(DECODE, "v5p") == v5p.peak_hbm_gbps
    assert m.effective(UNIFIED, "v5e") == pytest.approx(
        (v5e.peak_tflops_bf16 * v5e.peak_hbm_gbps) ** 0.5)
    assert m.effective(TRAINING, "v5p") == pytest.approx(
        v5p.peak_tflops_bf16 * 0.4)
    # accelerator-type names resolve through generation_of
    assert m.effective(PREFILL, "v5litepod-16") == v5e.peak_tflops_bf16


def test_matrix_ewma_refinement():
    m = ThroughputMatrix(ewma_alpha=0.5)
    m.observe(DECODE, "v5e", 100.0)
    assert m.effective(DECODE, "v5e") == 100.0
    m.observe(DECODE, "v5e", 200.0)
    assert m.effective(DECODE, "v5e") == 150.0  # 100 + 0.5*(200-100)
    # non-positive samples are dropped, other cells untouched
    m.observe(DECODE, "v5e", 0.0)
    assert m.effective(DECODE, "v5e") == 150.0
    assert m.effective(PREFILL, "v5e") == GENERATIONS["v5e"].peak_tflops_bf16


def test_matrix_sibling_transfer():
    """An unmeasured generation borrows the best-measured sibling scaled
    by roofline ratio — relative throughput transfers before absolute
    numbers exist everywhere (Gavel's trick)."""
    m = ThroughputMatrix()
    m.observe(DECODE, "v5e", 500.0)
    ratio = (GENERATIONS["v5p"].peak_hbm_gbps
             / GENERATIONS["v5e"].peak_hbm_gbps)
    assert m.effective(DECODE, "v5p") == pytest.approx(500.0 * ratio)
    # the measured cell itself is untouched by the transfer
    assert m.effective(DECODE, "v5e") == 500.0


def test_matrix_snapshot_marks_measured():
    m = ThroughputMatrix()
    m.observe(PREFILL, "v5e", 42.0)
    snap = m.snapshot()
    assert snap[PREFILL]["v5e"] == {"eff": 42.0, "measured": True,
                                    "samples": 1}
    assert snap[DECODE]["v5e"]["measured"] is False


# -- placement -----------------------------------------------------------------

def make_scheduler(spec="v5e:32,v5p:64", **kw):
    clock = kw.pop("clock", FakeClock())
    kw.setdefault("metrics", Metrics())
    return FleetScheduler(spec, clock=clock, **kw), clock


def test_prefill_lands_on_flops_per_dollar_pool():
    """v5e wins prefill per-dollar (197/1.2 = 164 vs 459/4.2 = 109); the
    reason cites the ranking for the scale-event log."""
    s, _ = make_scheduler()
    p = s.place(PREFILL, 8, "prefill-0")
    assert p.pool == "v5e" and p.generation == "v5e"
    assert "per-dollar ranking" in p.reason
    assert "->v5e" in p.reason


def test_decode_prefers_bandwidth_pool_under_contention():
    """decode per-dollar: v5e 819/1.2 = 682 vs v5p 2765/4.2 = 658 — v5e
    wins narrowly with free chips, but once v5e is full decode spills to
    the bandwidth-rich pool instead of failing."""
    s, _ = make_scheduler()
    first = s.place(DECODE, 32, "decode-0")
    assert first.pool == "v5e"
    second = s.place(DECODE, 8, "decode-1")
    assert second.pool == "v5p"


def test_measured_throughput_flips_placement():
    """Online refinement overrides the roofline seed: measured decode
    tokens/sec-per-chip showing v5p 4x better per-chip makes it the
    per-dollar winner too."""
    s, _ = make_scheduler()
    s.matrix.observe(DECODE, "v5e", 100.0)
    s.matrix.observe(DECODE, "v5p", 400.0)
    assert s.place(DECODE, 8, "d0").pool == "v5p"


def test_place_is_idempotent_by_tag():
    s, _ = make_scheduler()
    a = s.place(PREFILL, 8, "pod-1")
    b = s.place(PREFILL, 8, "pod-1")
    assert a is b
    assert s.free_chips("v5e") == 32 - 8


def test_place_validates_inputs():
    s, _ = make_scheduler()
    with pytest.raises(ValueError):
        s.place("mystery", 8, "t")
    with pytest.raises(ValueError):
        s.place(PREFILL, 0, "t")
    with pytest.raises(ValueError):
        s.place(PREFILL, 8, "")


def test_capacity_exhaustion_returns_none_and_counts():
    s, _ = make_scheduler("v5e:8")
    m = s.metrics
    assert s.place(PREFILL, 8, "a") is not None
    assert s.place(PREFILL, 8, "b") is None
    assert m.get_counter("tpu_fleet_pool_rejections",
                         labels={"kind": PREFILL}) == 1
    # the reservation survives; release frees it for the retry
    assert s.release("a") is True
    assert s.place(PREFILL, 8, "b") is not None


def test_release_is_idempotent():
    s, _ = make_scheduler()
    s.place(PREFILL, 8, "a")
    assert s.release("a") is True
    assert s.release("a") is False
    assert s.release("never-existed") is False
    assert s.free_chips("v5e") == 32


def test_best_effort_packs_and_never_preempts():
    s, _ = make_scheduler("v5e:16")
    s.place(UNIFIED, 8, "serving-0")
    # best-effort training packs onto the idle half
    be = s.place(TRAINING, 8, "be-0", best_effort=True)
    assert be is not None and be.best_effort
    # a second best-effort request can't preempt the first
    assert s.place(TRAINING, 8, "be-1", best_effort=True) is None


def test_preemption_lowest_goodput_loss_first():
    """Under crunch the victims leave lowest-unsaved-work-first; the
    preempt_fn sees each victim, the counter and placement both record
    it."""
    evicted = []
    s, _ = make_scheduler("v5e:32", preempt_fn=lambda p: evicted.append(p.tag))
    s.place(TRAINING, 8, "be-a", best_effort=True)
    s.place(TRAINING, 8, "be-b", best_effort=True)
    s.place(TRAINING, 8, "be-c", best_effort=True)
    # unsaved work: be-b cheapest, then be-c, then be-a
    s.observe_training("be-a", goodput=1.0, unsaved_work_s=300.0)
    s.observe_training("be-b", goodput=0.5, unsaved_work_s=10.0)
    s.observe_training("be-c", goodput=1.0, unsaved_work_s=60.0)
    # 16 chips wanted, 8 free -> exactly one victim needed: the cheapest
    p = s.place(UNIFIED, 16, "serving-big")
    assert p is not None and p.pool == "v5e"
    assert evicted == ["be-b"]
    assert s.metrics.get_counter("tpu_fleet_preemptions",
                                 labels={"reason": "goodput"}) == 1
    tags = {pl.tag for pl in s.placements()}
    assert tags == {"be-a", "be-c", "serving-big"}
    # needing more evicts the next-cheapest too (be-c before be-a)
    evicted.clear()
    assert s.place(UNIFIED, 8, "serving-2") is not None
    assert evicted == ["be-c"]


def test_preempt_fn_failure_does_not_kill_placement():
    def boom(placement):
        raise RuntimeError("evictor crashed")
    s, _ = make_scheduler("v5e:8", preempt_fn=boom)
    s.place(TRAINING, 8, "be-0", best_effort=True)
    assert s.place(UNIFIED, 8, "serving-0") is not None


def test_round_robin_ignores_scores():
    s, _ = make_scheduler(policy=ROUND_ROBIN)
    pools = [s.place(UNIFIED, 8, f"p{i}").pool for i in range(4)]
    assert pools == ["v5e", "v5p", "v5e", "v5p"]
    for r in (s.place(UNIFIED, 8, f"p{i}").reason for i in range(4, 6)):
        assert "round-robin" in r


def _gauge(m, name, **labels):
    return m.gauges[m._key(name, labels)]


def test_gauges_track_chip_states():
    s, _ = make_scheduler()
    s.place(PREFILL, 8, "a")
    m = s.metrics
    assert _gauge(m, "tpu_fleet_pool_chips", pool="v5e", state="reserved") == 8
    assert _gauge(m, "tpu_fleet_pool_chips", pool="v5e", state="free") == 24
    s.release("a")
    assert _gauge(m, "tpu_fleet_pool_chips", pool="v5e", state="reserved") == 0


def test_spans_cover_place_preempt_release():
    clock = FakeClock()
    tracer = Tracer(clock=clock)
    s = FleetScheduler("v5e:8", tracer=tracer, clock=clock)
    s.place(TRAINING, 8, "be-0", best_effort=True)
    s.place(UNIFIED, 8, "serving-0")     # preempts be-0
    s.release("serving-0")
    s.place(UNIFIED, 16, "too-big")      # no pool fits -> no_capacity
    actions = [sp["attrs"]["action"] for sp in tracer.recent()
               if sp["name"] == "fleet.schedule"]
    assert actions == ["place", "preempt", "place", "release", "no_capacity"]


# -- restart adoption ----------------------------------------------------------

def _pod(name, pool, kind=UNIFIED, chips=8, best_effort=False, extra=None):
    anns = {A.POOL: pool, A.POOL_KIND: kind}
    if best_effort:
        anns[A.BEST_EFFORT] = "true"
    anns.update(extra or {})
    return {"metadata": {"name": name, "annotations": anns},
            "spec": {"containers": [{"resources": {
                "limits": {"google.com/tpu": str(chips)}}}]}}


def test_adopt_rebuilds_reservations():
    s, _ = make_scheduler()
    n = s.adopt([_pod("pod-a", "v5e", PREFILL, chips=16),
                 _pod("pod-b", "v5p", TRAINING, chips=8, best_effort=True)])
    assert n == 2
    assert s.free_chips("v5e") == 16 and s.free_chips("v5p") == 56
    by_tag = {p.tag: p for p in s.placements()}
    assert by_tag["pod-a"].kind == PREFILL
    assert by_tag["pod-b"].best_effort is True
    # idempotent: a second adopt (or an adopt after place) changes nothing
    assert s.adopt([_pod("pod-a", "v5e", PREFILL, chips=16)]) == 0
    assert s.free_chips("v5e") == 16


def test_adopt_skips_unknown_pools_and_unannotated_pods():
    s, _ = make_scheduler()
    pods = [_pod("ghost", "retired-pool"),
            {"metadata": {"name": "legacy", "annotations": {}}, "spec": {}}]
    assert s.adopt(pods) == 0
    assert s.placements() == []


# -- telemetry refinement ------------------------------------------------------

class _Stats:
    def __init__(self, tokens_total):
        self.tokens_total = tokens_total


def test_observe_serving_learns_tokens_per_chip():
    s, clock = make_scheduler()
    s.place(DECODE, 8, "pod-1")
    s.observe_serving("pod-1", DECODE, "", _Stats(1000))   # baseline only
    assert s.matrix.snapshot()[DECODE]["v5e"]["measured"] is False
    clock.advance(10.0)
    s.observe_serving("pod-1", DECODE, "", _Stats(1800))
    # (1800-1000)/10s/8 chips = 10 tokens/s/chip; generation comes from
    # the placement, not the (empty) heartbeat field
    assert s.matrix.effective(DECODE, "v5e") == pytest.approx(10.0)


def test_observe_serving_unplaced_replica_uses_default_chips():
    s, clock = make_scheduler(default_serving_chips=4)
    s.observe_serving("legacy-pod", DECODE, "v5p", _Stats(100))
    clock.advance(5.0)
    s.observe_serving("legacy-pod", DECODE, "v5p", _Stats(300))
    assert s.matrix.effective(DECODE, "v5p") == pytest.approx(10.0)


def test_observe_serving_counter_reset_is_ignored():
    s, clock = make_scheduler()
    s.place(DECODE, 8, "pod-1")
    s.observe_serving("pod-1", DECODE, "", _Stats(1000))
    clock.advance(5.0)
    s.observe_serving("pod-1", DECODE, "", _Stats(200))  # engine restarted
    assert s.matrix.snapshot()[DECODE]["v5e"]["measured"] is False
    clock.advance(5.0)
    s.observe_serving("pod-1", DECODE, "", _Stats(600))  # new baseline works
    assert s.matrix.effective(DECODE, "v5e") == pytest.approx(10.0)


def test_observe_training_updates_loss_and_matrix():
    s, _ = make_scheduler()
    s.place(TRAINING, 16, "gang-0", best_effort=True)
    s.observe_training("gang-0", mfu=0.5, goodput=0.9, unsaved_work_s=100.0)
    p = s.placements()[0]
    assert p.goodput_loss == pytest.approx(100.0 * 0.9 * 16)
    assert s.matrix.effective(TRAINING, "v5e") == pytest.approx(
        0.5 * GENERATIONS["v5e"].peak_tflops_bf16)


def test_rates_and_snapshot():
    s, _ = make_scheduler()
    s.place(PREFILL, 8, "a")
    goodput, cost = s.rates()
    assert goodput == pytest.approx(
        GENERATIONS["v5e"].peak_tflops_bf16 * 8)
    assert cost == pytest.approx(GENERATIONS["v5e"].cost_per_chip_hr * 8)
    snap = s.snapshot()
    assert snap["policy"] == HETERO
    assert snap["pools"][0] == {
        "pool": "v5e", "generation": "v5e", "total_chips": 32,
        "reserved_chips": 8, "free_chips": 24, "cost_per_chip_hr": 1.2}
    assert snap["placements"][0]["tag"] == "a"
    assert PREFILL in snap["matrix"]


# -- registry round-trip (satellite: generation/pool through heartbeats) -------

def make_registry(scheduler=None):
    from k8s_runpod_kubelet_tpu.fleet.registry import ReplicaRegistry
    clock = FakeClock()
    return ReplicaRegistry(metrics=Metrics(), clock=clock,
                           transport_factory=lambda url:
                           types.SimpleNamespace(breaker=None),
                           scheduler=scheduler), clock


def test_registry_generation_pool_round_trip():
    reg, _ = make_registry()
    reg.register("rep-1", "http://r1", pod_name="pod-1", role="decode",
                 generation="v5p", pool="bulk")
    rep = reg.get("rep-1")
    assert rep.generation == "v5p" and rep.pool == "bulk"
    d = rep.to_dict(now=0.0)
    assert d["generation"] == "v5p" and d["pool"] == "bulk"
    # the /debug/fleet surface groups node pools
    snap = reg.snapshot()
    assert snap["node_pools"] == {"bulk": 1}
    assert snap["replicas"][0]["generation"] == "v5p"


def test_registry_heartbeat_feeds_scheduler_matrix():
    scheduler, sched_clock = make_scheduler()
    reg, clock = make_registry(scheduler=scheduler)
    scheduler.clock = clock  # one clock for baselines and heartbeats
    reg.register("rep-1", "http://r1", pod_name="pod-1", role="decode",
                 generation="v5e", pool="v5e")
    reg.heartbeat("rep-1", {"tokens_total": 1000})
    clock.advance(10.0)
    reg.heartbeat("rep-1", {"tokens_total": 1800})
    # default_serving_chips=8: (800/10)/8 = 10 tokens/s/chip on v5e
    assert scheduler.matrix.effective("decode", "v5e") == pytest.approx(10.0)


# -- fleet_summary rendering ---------------------------------------------------

def test_fleet_summary_renders_pool_columns(tmp_path):
    import json
    import sys
    sys.path.insert(0, str((tmp_path / "_nothing")))  # keep sys.path shape
    from tools.fleet_summary import render

    snap = {
        "replicas": [{
            "replica_id": "rep-1", "state": "ready", "role": "decode",
            "generation": "v5p", "pool": "bulk", "heartbeat_age_s": 1.0,
            "stats": {"active_slots": 1, "max_slots": 4, "queue_depth": 0,
                      "kv_cache_tokens": 10, "ttft_p95_s": 0.1,
                      "itl_p95_s": 0.01}}],
        "scheduler": {
            "policy": "hetero",
            "pools": [{"pool": "bulk", "generation": "v5p",
                       "total_chips": 64, "reserved_chips": 8,
                       "free_chips": 56, "cost_per_chip_hr": 4.2}],
            "placements": [{"tag": "pod-1", "kind": "decode",
                            "pool": "bulk", "chips": 8,
                            "best_effort": False, "goodput_loss": 0.0,
                            "reason": "x"}],
            "matrix": {"decode": {"v5p": {"eff": 2765.0, "measured": False,
                                          "samples": 0}}}},
    }
    path = tmp_path / "fleet.jsonl"
    path.write_text(json.dumps(snap) + "\n", encoding="utf-8")
    from tools.fleet_summary import load
    spans, snapshots = load(str(path))
    out = render(spans, snapshots)
    assert "v5p" in out and "bulk" in out
    assert "node pools (scheduler snapshot" in out
    assert "pod-1" in out
    assert "effective throughput" in out
