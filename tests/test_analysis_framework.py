"""Framework tests for graftlint: index caching, report formats, allowlist
staleness, and the CLI's exit-code contract (ISSUE 7 satellites)."""

import ast

import pytest

from k8s_runpod_kubelet_tpu.analysis import (Checker, Finding, PackageIndex,
                                             get_package_index, run_checkers)
from k8s_runpod_kubelet_tpu.analysis.__main__ import main as cli_main


class _StubChecker(Checker):
    name = "stub"
    description = "flags every module named bad_*.py"
    allowlist = {}

    def collect(self, index):
        for fi in index.files():
            if fi.rel.startswith("bad"):
                yield Finding(self.name, fi.rel, 1, "<module>",
                              "flagged by stub", key=(fi.rel, "<module>"))


def test_package_index_parses_each_file_once_per_process():
    """The tentpole's whole point: five lint tests + the CLI share ONE
    parse. The cached index must be the same object on every call."""
    assert get_package_index() is get_package_index()


def test_index_enclosing_lookups():
    src = ("class C:\n"
           "    def m(self):\n"
           "        x = 1\n"
           "        return x\n"
           "\n"
           "def top():\n"
           "    pass\n")
    idx = PackageIndex({"mod.py": src})
    fi = idx.file("mod.py")
    assert fi.enclosing_function(3) == "m"
    assert fi.enclosing_class(3) == "C"
    assert fi.enclosing_function(7) == "top"
    assert fi.enclosing_class(7) is None
    assert fi.enclosing_function(1) == "<module>"
    assert isinstance(fi.tree, ast.Module)


def test_report_formats():
    f = Finding("stub", "fleet/router.py", 42, "route", "the message",
                key=("fleet/router.py", "route"))
    assert f.text() == "fleet/router.py:42 (in route): the message"
    gh = f.github()
    assert gh.startswith("::error file=k8s_runpod_kubelet_tpu/fleet/"
                         "router.py,line=42,")
    assert "title=graftlint/stub" in gh and "the message" in gh


def test_live_vs_suppressed_vs_stale():
    idx = PackageIndex({"bad_one.py": "x = 1\n", "bad_two.py": "y = 2\n",
                        "ok.py": "z = 3\n"})
    checker = _StubChecker(allowlist={
        ("bad_one.py", "<module>"): "known, justified",
        ("gone.py", "<module>"): "this handler was refactored away",
    })
    result = checker.run(idx)
    assert [f.file for f in result.findings] == ["bad_two.py"]
    assert [f.file for f in result.suppressed] == ["bad_one.py"]
    # the stale entry fails LOUDLY, mirroring
    # test_allowlist_entries_still_exist
    assert result.stale_allowlist == [("gone.py", "<module>")]
    assert not result.ok


def test_stale_allowlist_fails_the_suite_even_with_zero_findings():
    idx = PackageIndex({"ok.py": "z = 3\n"})
    checker = _StubChecker(allowlist={("typo.py", "<module>"): "typo'd"})
    suite = run_checkers(idx, [checker])
    assert not suite.findings          # nothing live...
    assert not suite.ok                # ...but the suite still fails
    assert "stale allowlist entry" in suite.render()


def test_cli_clean_repo_exits_zero(capsys):
    assert cli_main([]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out and "0 stale" in out


def test_cli_exits_nonzero_on_findings(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    (pkg / "node").mkdir(parents=True)
    (pkg / "node" / "bad.py").write_text(
        "import time\n\ndef f():\n    return time.time()\n")
    rc = cli_main(["--package", str(pkg), "--repo-root", str(tmp_path)])
    assert rc == 1
    assert "raw time.time() call" in capsys.readouterr().out


def test_cli_github_format(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    (pkg / "node").mkdir(parents=True)
    (pkg / "node" / "bad.py").write_text(
        "import time\n\ndef f():\n    return time.time()\n")
    rc = cli_main(["--package", str(pkg), "--repo-root", str(tmp_path),
                   "--format=github"])
    assert rc == 1
    assert "::error file=" in capsys.readouterr().out


def test_cli_checker_selection(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    (pkg / "node").mkdir(parents=True)
    (pkg / "node" / "bad.py").write_text(
        "import time\n\ndef f():\n    return time.time()\n")
    # only thread-hygiene runs -> the determinism finding is invisible
    rc = cli_main(["--package", str(pkg), "--repo-root", str(tmp_path),
                   "--checker", "thread-hygiene"])
    assert rc == 0


def test_cli_list(capsys):
    assert cli_main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in ("determinism", "lock-discipline", "config-plumbing",
                 "observability", "thread-hygiene", "exception-hygiene"):
        assert name in out
