"""Paged KV pool + prefix trie unit tests (ISSUE 8 tier-1).

Pure host-side bookkeeping — no jax arrays, no engine — so the allocator
and trie invariants the serving engine leans on are pinned
deterministically:

- the free list never hands a page out twice, and refs balance;
- COW claims balance refcounts (shared -> fresh copy, exclusive -> same);
- trie match = longest common FULL-PAGE token prefix, capped so >= 1
  prompt token remains to compute;
- eviction is LRU-leaves-first, never a pinned node, and never FREES a
  page an in-flight match still references.
"""

import pytest

from k8s_runpod_kubelet_tpu.workloads.serving.kv_manager import (
    DensePrefixStore, PagePool, PoolExhausted, PrefixTrie)


class TestPagePool:
    def test_never_double_allocates(self):
        pool = PagePool(8)
        got = [pool.alloc() for _ in range(8)]
        assert sorted(got) == list(range(8))      # every page exactly once
        with pytest.raises(PoolExhausted):
            pool.alloc()

    def test_unref_to_zero_frees_and_refs_balance(self):
        pool = PagePool(2)
        p = pool.alloc()
        pool.ref(p)
        assert pool.refcount(p) == 2
        assert pool.unref(p) is False             # still referenced
        assert pool.unref(p) is True              # freed
        assert pool.free_count == 2
        # freed page is allocatable again, exactly once
        a, b = pool.alloc(), pool.alloc()
        assert sorted((a, b)) == [0, 1]

    def test_unref_below_zero_raises(self):
        pool = PagePool(1)
        p = pool.alloc()
        pool.unref(p)
        with pytest.raises(ValueError):
            pool.unref(p)

    def test_ref_of_free_page_raises(self):
        pool = PagePool(1)
        with pytest.raises(ValueError):
            pool.ref(0)

    def test_cow_exclusive_keeps_page(self):
        pool = PagePool(2)
        p = pool.alloc()
        q, copied = pool.cow(p)
        assert (q, copied) == (p, False)
        assert pool.refcount(p) == 1              # unchanged

    def test_cow_shared_allocates_and_balances(self):
        pool = PagePool(2)
        p = pool.alloc()
        pool.ref(p)                               # shared: two holders
        q, copied = pool.cow(p)
        assert copied and q != p
        assert pool.refcount(p) == 1              # the other holder remains
        assert pool.refcount(q) == 1              # the caller's copy
        # total references conserved: 2 before, 2 after
        pool.unref(p)
        pool.unref(q)
        assert pool.free_count == 2

    def test_shared_count(self):
        pool = PagePool(3)
        a = pool.alloc()
        pool.alloc()
        pool.ref(a)
        assert pool.shared_count == 1


def _write_noop(page_ids, start_chunk):
    pass


class TestPrefixTrie:
    def _trie(self, n_pages=16, t=4):
        pool = PagePool(n_pages)
        return PrefixTrie(pool, t), pool

    def test_match_is_longest_common_full_page_prefix(self):
        trie, _ = self._trie()
        toks = list(range(10))                    # pages: [0..3], [4..7]
        trie.insert(0, toks, _write_noop)
        assert len(trie) == 2                     # only FULL pages cached
        m = trie.match(0, list(range(10)) + [99])
        assert m.matched_tokens == 8
        trie.release(m.pages)
        m = trie.match(0, list(range(6)))         # shares page 1 only
        assert m.matched_tokens == 4
        trie.release(m.pages)
        m = trie.match(0, [7, 7, 7, 7])           # diverges at page 1
        assert m.matched_tokens == 0

    def test_match_leaves_one_token_to_compute(self):
        trie, _ = self._trie()
        toks = list(range(8))
        trie.insert(0, toks, _write_noop)
        m = trie.match(0, toks)                   # prompt == cached exactly
        assert m.matched_tokens == 4              # last page recomputes
        trie.release(m.pages)

    def test_insert_shares_common_prefix_pages(self):
        trie, pool = self._trie()
        trie.insert(0, list(range(8)), _write_noop)
        used_before = pool.n_pages - pool.free_count
        # same first page, new second page
        trie.insert(0, [0, 1, 2, 3, 9, 9, 9, 9], _write_noop)
        assert pool.n_pages - pool.free_count == used_before + 1
        assert trie.shared_pages() >= 1           # the common page is interior

    def test_adapter_roots_are_distinct(self):
        trie, _ = self._trie()
        toks = list(range(8))
        trie.insert(0, toks, _write_noop)
        assert trie.match(1, toks).matched_tokens == 0
        trie.insert(1, toks, _write_noop)
        m = trie.match(1, toks + [1])
        assert m.matched_tokens == 8
        trie.release(m.pages)
        assert trie.drop_adapter(1) == 2
        assert trie.match(1, toks).matched_tokens == 0

    def test_eviction_lru_leaves_first_never_pinned(self):
        trie, pool = self._trie(n_pages=3, t=4)
        trie.insert(0, list(range(4)), _write_noop, pin=True)     # pinned
        trie.insert(0, [8] * 4, _write_noop)                      # leaf A
        trie.insert(0, [9] * 4, _write_noop)                      # leaf B
        assert pool.free_count == 0
        # touch A so B becomes the LRU leaf
        m = trie.match(0, [8] * 4 + [0])
        trie.release(m.pages)
        added, evicted = trie.insert(0, [7] * 4 + [1], _write_noop)
        assert (added, evicted) == (1, 1)
        stats = trie.stats()
        assert stats["pinned"] == 1                               # survived
        # the LRU leaf (B) was the victim; A and the pinned page remain
        assert trie.match(0, [9] * 4 + [0]).matched_tokens == 0
        for probe in ([8] * 4 + [0], [7] * 4 + [1],
                      list(range(4)) + [99]):
            m = trie.match(0, probe)
            assert m.matched_tokens == 4, probe
            trie.release(m.pages)

    def test_eviction_never_frees_a_referenced_page(self):
        trie, pool = self._trie(n_pages=2, t=4)
        trie.insert(0, [1] * 4, _write_noop)
        trie.insert(0, [2] * 4, _write_noop)
        m = trie.match(0, [1] * 4 + [0])          # holds a ref on page A
        assert m.matched_tokens == 4
        held = m.pages[0]
        # pool is full; a new insert must evict a node — possibly A's —
        # but A's PAGE cannot return to the free list while we hold it
        trie.insert(0, [3] * 4 + [0], _write_noop)
        assert held not in pool._free
        trie.release(m.pages)                     # last ref drops -> free OK

    def test_partial_insert_when_nothing_evictable(self):
        trie, pool = self._trie(n_pages=1, t=4)
        trie.insert(0, [1] * 4, _write_noop, pin=True)
        added, evicted = trie.insert(0, [2] * 8, _write_noop)
        assert (added, evicted) == (0, 0)         # degraded, not an error
        assert pool.free_count == 0

    def test_insert_write_callback_gets_new_pages_and_offset(self):
        trie, _ = self._trie()
        calls = []
        trie.insert(0, list(range(8)),
                    lambda ids, start: calls.append((list(ids), start)))
        assert calls == [([0, 1], 0)]
        calls.clear()
        trie.insert(0, list(range(8)) + [9] * 4,
                    lambda ids, start: calls.append((list(ids), start)))
        assert calls == [([2], 2)]                # only the NEW tail chunk


class TestDensePrefixStore:
    def test_longest_registered_wins_and_variants_bounded(self):
        store = DensePrefixStore(max_adapter_variants=2)
        store.add([1, 2], "short")
        store.add([1, 2, 3, 4], "long")
        entry = store.lookup([1, 2, 3, 4, 5])
        assert entry.tokens == [1, 2, 3, 4]
        assert store.lookup([9]) is None
        # adapter variants LRU-bound at 2; base variants stay pinned
        for aid in (1, 2, 3):
            assert store.put_variant(entry, aid, f"v{aid}")
        n_vars = sum(1 for e in store._entries
                     for aid in e.variants if aid != 0)
        assert n_vars == 2
        assert 0 in entry.variants                # base never evicted
        store.drop_adapter(2)
        assert 2 not in entry.variants
