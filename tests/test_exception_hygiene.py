"""Exception-hygiene lint: no silent broad excepts (ISSUE 3 satellite).

Chaos bugs hide inside ``except Exception: pass``. This AST lint walks every
broad handler (bare ``except``, ``Exception``, ``BaseException``) in the
package and requires it to do SOMETHING visible with the failure:

- re-raise, or
- call a logger (``log.exception``/``error``/``warning`` preferred;
  ``info``/``debug`` accepted where the handler's docstring/comment justifies
  the downgrade — the lint cares about silence, not volume), or
- USE the bound exception value (``except ... as e`` with ``e`` referenced in
  the body: folding the error into a response/result/error-list is handling,
  not swallowing).

The handful of TRUE silent swallows that survive are individually allowlisted
by (file, enclosing function) with a justification — adding a new one is a
conscious, reviewed act, not an accident.
"""

import ast
import pathlib

PKG = pathlib.Path(__file__).resolve().parent.parent / "k8s_runpod_kubelet_tpu"

_LOG_METHODS = {"exception", "error", "warning", "info", "debug", "log"}

# (file, enclosing function) -> why a silent swallow is correct THERE.
# Keep this list short; every entry must carry a real justification.
ALLOWED_SILENT = {
    ("gang/exec.py", "remote_kill"):
        "best-effort disconnect-kill cleanup: worker gone / process exited",
    ("workloads/serving.py", "_fail_future"):
        "racing future.cancel(); the future already carries a result",
    ("workloads/serving.py", "_complete"):
        "future already resolved elsewhere; nothing to report",
    ("workloads/serve_main.py", "_triage_overflow"):
        "metrics bump around a raw-socket 503 must never block the reject",
    ("ops/attention.py", "_generation"):
        "backend not initialized; documented fallback to cpu kernels",
    ("logging_util.py", "_drain"):
        "the error sink must never raise; drops are counted (self.dropped)",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles(handler: ast.ExceptHandler) -> bool:
    bound = handler.name  # "e" in `except Exception as e`
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _LOG_METHODS:
                return True
        if bound and isinstance(node, ast.Name) and node.id == bound \
                and isinstance(node.ctx, ast.Load):
            return True  # the error value flows somewhere visible
    return False


def _enclosing_function(tree: ast.AST, lineno: int) -> str:
    """Name of the innermost def containing the line (or <module>)."""
    best, best_span = "<module>", float("inf")
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end and end - node.lineno < best_span:
                best, best_span = node.name, end - node.lineno
    return best


def _violations():
    out = []
    for path in sorted(PKG.rglob("*.py")):
        rel = str(path.relative_to(PKG))
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
                continue
            if _handles(node):
                continue
            func = _enclosing_function(tree, node.lineno)
            if (rel, func) in ALLOWED_SILENT:
                continue
            out.append(f"{rel}:{node.lineno} (in {func})")
    return out


def test_no_silent_broad_excepts():
    violations = _violations()
    assert not violations, (
        "broad except blocks that neither re-raise, nor log, nor use the "
        "caught error — silent swallows are how chaos bugs hide. Either "
        "surface the failure or (rarely, with justification) add the "
        f"(file, function) to ALLOWED_SILENT: {violations}")


def test_allowlist_entries_still_exist():
    """An allowlist entry whose handler was refactored away is dead weight —
    and a typo'd entry would silently fail to protect anything."""
    live: set = set()
    for path in sorted(PKG.rglob("*.py")):
        rel = str(path.relative_to(PKG))
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=rel)
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node):
                live.add((rel, _enclosing_function(tree, node.lineno)))
    stale = [k for k in ALLOWED_SILENT if k not in live]
    assert not stale, f"ALLOWED_SILENT entries with no matching handler: {stale}"
