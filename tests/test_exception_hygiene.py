"""Exception-hygiene lint: no silent broad excepts (ISSUE 3 satellite).

Now a thin shim over the shared graftlint framework (ISSUE 7): the rule,
rationale, and allowlist live in
``k8s_runpod_kubelet_tpu/analysis/checkers/exception_hygiene.py`` and run
off the ONE cached package parse every lint test shares — this file keeps
the historical test names (and the standalone CLI reports the same
findings as ``python -m k8s_runpod_kubelet_tpu.analysis``).
"""

from k8s_runpod_kubelet_tpu.analysis import get_package_index
from k8s_runpod_kubelet_tpu.analysis.checkers import ExceptionHygieneChecker

# (file, enclosing function) -> why a silent swallow is correct THERE.
# Re-exported for anything that imported it from here; the source of truth
# is the checker class.
ALLOWED_SILENT = ExceptionHygieneChecker.allowlist


def test_no_silent_broad_excepts():
    result = ExceptionHygieneChecker().run(get_package_index())
    assert not result.findings, (
        "broad except blocks that neither re-raise, nor log, nor use the "
        "caught error — silent swallows are how chaos bugs hide. Either "
        "surface the failure or (rarely, with justification) add the "
        "(file, function) to ExceptionHygieneChecker.allowlist: "
        + "; ".join(f.text() for f in result.findings))


def test_allowlist_entries_still_exist():
    """An allowlist entry whose handler was refactored away (or cleaned up
    to actually handle) is dead weight — and a typo'd entry would silently
    fail to protect anything. The framework's staleness rule is STRICTER
    than the original: the entry must suppress a live silent-swallow, not
    merely point at some broad handler."""
    result = ExceptionHygieneChecker().run(get_package_index())
    assert not result.stale_allowlist, (
        f"allowlist entries with no matching silent swallow: "
        f"{result.stale_allowlist}")
