"""Transport hardening edge cases (ISSUE 3 tentpole part 1).

Covers the retry machinery the chaos soak leans on, in isolation:
- decorrelated-jitter backoff stays within [base, cap];
- the per-request deadline budget spans retries (no hidden-sleep blowup) and
  is exhausted mid-backoff rather than overshot;
- ``Retry-After`` honored on 429/503, both delta-seconds and HTTP-date forms;
- a 401 token refresh racing a 5xx burst: the refresh does not consume a
  backoff-retry slot, and the burst still gets its full retry budget;
- circuit breaker: trip on consecutive failures, fail-fast while open,
  half-open probe that heals on success and re-trips on failure.

A scripted in-process HTTP server plays the flaky cloud; sleeps are recorded,
never slept; the breaker runs on a FakeClock.
"""

from __future__ import annotations

import email.utils
import json
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k8s_runpod_kubelet_tpu.cloud.transport import (
    CLOSED, OPEN, HALF_OPEN,
    CircuitBreaker, CircuitOpenError, HttpTransport, TransportError,
    parse_retry_after,
)

from harness import FakeClock


class ScriptedServer:
    """Serves a scripted sequence of (status, headers) responses; repeats the
    last entry forever. Records every request's Authorization header."""

    def __init__(self, script):
        self.script = list(script)
        self.hits = 0
        self.auth_seen: list[str] = []
        self.lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                with outer.lock:
                    i = min(outer.hits, len(outer.script) - 1)
                    status, headers = outer.script[i]
                    outer.hits += 1
                    outer.auth_seen.append(
                        self.headers.get("Authorization", ""))
                body = json.dumps({"ok": status == 200}).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def url(self):
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def make_transport(server, clock, sleeps, **kw):
    kw.setdefault("rng", random.Random(42))
    kw.setdefault("token", "t")

    def sleep(s):
        sleeps.append(s)
        clock.advance(s)

    return HttpTransport(server.url, sleep=sleep, clock=clock, **kw)


class TestRetryAfterParsing:
    def test_delta_seconds(self):
        assert parse_retry_after("7") == 7.0
        assert parse_retry_after(" 12.5 ") == 12.5
        assert parse_retry_after("-3") == 0.0  # never a negative sleep

    def test_http_date(self):
        now = 1_700_000_000.0
        future = email.utils.formatdate(now + 42, usegmt=True)
        got = parse_retry_after(future, now=now)
        assert got is not None and 41.0 <= got <= 43.0

    def test_http_date_in_past_is_zero(self):
        now = 1_700_000_000.0
        past = email.utils.formatdate(now - 500, usegmt=True)
        assert parse_retry_after(past, now=now) == 0.0

    def test_garbage_is_none(self):
        assert parse_retry_after(None) is None
        assert parse_retry_after("") is None
        assert parse_retry_after("soon-ish") is None


class TestBackoffAndDeadline:
    def test_jitter_within_bounds_and_decorrelated(self):
        srv = ScriptedServer([(503, {})])
        try:
            clock, sleeps = FakeClock(), []
            t = make_transport(srv, clock, sleeps, max_retries=6,
                               deadline_s=10_000.0, backoff_base_s=0.5,
                               backoff_cap_s=15.0)
            with pytest.raises(TransportError):
                t.request("GET", "/x")
            assert len(sleeps) == 5  # 6 attempts -> 5 backoffs
            assert all(0.5 <= s <= 15.0 for s in sleeps)
            assert len(set(sleeps)) > 1, "jitter produced identical sleeps"
        finally:
            srv.stop()

    def test_deadline_budget_exhausted_mid_backoff(self):
        """A 30s-timeout call must not become 90s of hidden sleeps: once the
        next backoff would cross the budget, the transport surfaces the last
        real error instead of sleeping into overtime."""
        srv = ScriptedServer([(503, {})])
        try:
            clock, sleeps = FakeClock(), []
            t = make_transport(srv, clock, sleeps, max_retries=50,
                               timeout_s=30.0, deadline_s=5.0,
                               backoff_base_s=2.0, backoff_cap_s=15.0)
            t0 = clock()
            with pytest.raises(TransportError) as ei:
                t.request("GET", "/x")
            assert "deadline budget" in str(ei.value)
            assert ei.value.status == 503  # the REAL error, not a timeout mask
            assert clock() - t0 <= 5.0 + 1e-6
            assert srv.hits < 50, "deadline did not bound the attempt count"
        finally:
            srv.stop()

    def test_success_within_budget_untouched(self):
        srv = ScriptedServer([(503, {}), (200, {})])
        try:
            clock, sleeps = FakeClock(), []
            t = make_transport(srv, clock, sleeps, max_retries=3,
                               deadline_s=100.0)
            assert t.request("GET", "/x") == {"ok": True}
            assert len(sleeps) == 1
        finally:
            srv.stop()


class TestRetryAfterHonored:
    def test_503_retry_after_stretches_the_sleep(self):
        srv = ScriptedServer([(503, {"Retry-After": "9"}), (200, {})])
        try:
            clock, sleeps = FakeClock(), []
            t = make_transport(srv, clock, sleeps, max_retries=3,
                               deadline_s=100.0, backoff_cap_s=2.0)
            assert t.request("GET", "/x") == {"ok": True}
            assert sleeps and sleeps[0] >= 9.0
        finally:
            srv.stop()

    def test_429_with_retry_after_is_retried(self):
        srv = ScriptedServer([(429, {"Retry-After": "3"}), (200, {})])
        try:
            clock, sleeps = FakeClock(), []
            t = make_transport(srv, clock, sleeps, max_retries=3,
                               deadline_s=100.0)
            assert t.request("GET", "/x") == {"ok": True}
            assert sleeps and sleeps[0] >= 3.0
        finally:
            srv.stop()

    def test_429_without_retry_after_still_fails_fast(self):
        """A bare 429 stays a deterministic failure (the QuotaError requeue
        path) — only explicit server guidance earns a retry."""
        srv = ScriptedServer([(429, {}), (200, {})])
        try:
            clock, sleeps = FakeClock(), []
            t = make_transport(srv, clock, sleeps, max_retries=3,
                               deadline_s=100.0)
            with pytest.raises(TransportError) as ei:
                t.request("GET", "/x")
            assert ei.value.status == 429
            assert srv.hits == 1 and not sleeps
        finally:
            srv.stop()


class _RefreshingProvider:
    """Token provider with invalidate(): v1 until invalidated, then v2."""

    def __init__(self):
        self.version = 1
        self.invalidations = 0

    def __call__(self):
        return f"tok-v{self.version}"

    def invalidate(self):
        self.invalidations += 1
        self.version += 1


class TestAuthRefreshUnder5xx:
    def test_401_refresh_races_a_5xx_burst(self):
        """401 -> refresh -> 503 -> backoff-retry -> 200. The refresh must
        not consume a retry slot, the retries must carry the FRESH token,
        and the whole thing stays within one request() call."""
        srv = ScriptedServer([(401, {}), (503, {}), (503, {}), (200, {})])
        try:
            clock, sleeps = FakeClock(), []
            prov = _RefreshingProvider()
            t = make_transport(srv, clock, sleeps, token="",
                               token_provider=prov, max_retries=3,
                               deadline_s=100.0)
            assert t.request("GET", "/x") == {"ok": True}
            assert prov.invalidations == 1
            assert srv.hits == 4  # 401 + 2x503 + 200: 3 "real" attempts
            assert srv.auth_seen[0] == "Bearer tok-v1"
            assert all(a == "Bearer tok-v2" for a in srv.auth_seen[1:])
        finally:
            srv.stop()

    def test_second_401_is_terminal(self):
        srv = ScriptedServer([(401, {}), (401, {})])
        try:
            clock, sleeps = FakeClock(), []
            prov = _RefreshingProvider()
            t = make_transport(srv, clock, sleeps, token="",
                               token_provider=prov, max_retries=3,
                               deadline_s=100.0)
            with pytest.raises(TransportError) as ei:
                t.request("GET", "/x")
            assert ei.value.status == 401
            assert prov.invalidations == 1  # refreshed once, not in a loop
        finally:
            srv.stop()


class TestCircuitBreaker:
    def test_trips_after_threshold_and_fails_fast(self):
        srv = ScriptedServer([(503, {})])
        try:
            clock, sleeps = FakeClock(), []
            br = CircuitBreaker(failure_threshold=4, reset_timeout_s=30.0,
                                clock=clock)
            t = make_transport(srv, clock, sleeps, max_retries=2,
                               deadline_s=100.0, breaker=br)
            with pytest.raises(TransportError):
                t.request("GET", "/x")  # 2 failures
            with pytest.raises(TransportError):
                t.request("GET", "/x")  # 4 failures -> OPEN
            assert br.state == OPEN
            hits_before = srv.hits
            with pytest.raises(CircuitOpenError):
                t.request("GET", "/x")  # rejected, no I/O
            assert srv.hits == hits_before
        finally:
            srv.stop()

    def test_half_open_probe_heals(self):
        srv = ScriptedServer([(503, {}), (503, {}), (200, {})])
        try:
            clock, sleeps = FakeClock(), []
            br = CircuitBreaker(failure_threshold=2, reset_timeout_s=30.0,
                                clock=clock)
            t = make_transport(srv, clock, sleeps, max_retries=1,
                               deadline_s=100.0, breaker=br)
            for _ in range(2):
                with pytest.raises(TransportError):
                    t.request("GET", "/x")
            assert br.state == OPEN
            clock.advance(31.0)
            assert t.request("GET", "/x") == {"ok": True}  # the probe
            assert br.state == CLOSED
        finally:
            srv.stop()

    def test_half_open_probe_retrips(self):
        srv = ScriptedServer([(503, {})])
        try:
            clock, sleeps = FakeClock(), []
            br = CircuitBreaker(failure_threshold=2, reset_timeout_s=30.0,
                                clock=clock)
            t = make_transport(srv, clock, sleeps, max_retries=1,
                               deadline_s=100.0, breaker=br)
            for _ in range(2):
                with pytest.raises(TransportError):
                    t.request("GET", "/x")
            assert br.state == OPEN
            clock.advance(31.0)
            with pytest.raises(TransportError):
                t.request("GET", "/x")  # probe fails
            assert br.state == OPEN
            # and stays rejecting until the NEXT full reset window
            with pytest.raises(CircuitOpenError):
                t.request("GET", "/x")
            clock.advance(31.0)
            assert br.allow()  # next probe window opens again
            assert br.state == HALF_OPEN
        finally:
            srv.stop()

    def test_half_open_probe_stops_after_first_failed_attempt(self):
        """One probe means ONE attempt: when the probe's first attempt
        re-opens the breaker, the remaining retries must not backoff-sleep
        and do real I/O against an API just declared dark."""
        srv = ScriptedServer([(503, {})])
        try:
            clock, sleeps = FakeClock(), []
            br = CircuitBreaker(failure_threshold=2, reset_timeout_s=30.0,
                                clock=clock)
            t = make_transport(srv, clock, sleeps, max_retries=4,
                               deadline_s=1000.0, breaker=br)
            with pytest.raises(TransportError):
                t.request("GET", "/x", max_retries=2)  # 2 failures -> OPEN
            assert br.state == OPEN
            hits, n_sleeps = srv.hits, len(sleeps)
            clock.advance(31.0)
            with pytest.raises(TransportError) as ei:
                t.request("GET", "/x")  # the probe: max_retries=4 available
            assert ei.value.status == 503  # the real error, not CircuitOpen
            assert srv.hits == hits + 1, "probe did more than one attempt"
            assert len(sleeps) == n_sleeps, "probe slept before giving up"
            assert br.state == OPEN
        finally:
            srv.stop()

    def test_half_open_probe_token_failure_releases_slot(self):
        """A probe request that dies fetching its bearer token (metadata
        blip) must release the half-open probe slot — the old path skipped
        breaker accounting entirely, wedging the breaker half-open forever
        (every later allow() refused, node degraded until restart)."""
        class FlakyTokens:
            ok = False

            def __call__(self):
                if not self.ok:
                    raise RuntimeError("metadata server down")
                return "tok"

        srv = ScriptedServer([(200, {})])
        try:
            clock, sleeps = FakeClock(), []
            tokens = FlakyTokens()
            br = CircuitBreaker(failure_threshold=2, reset_timeout_s=30.0,
                                clock=clock)
            t = make_transport(srv, clock, sleeps, token="",
                               token_provider=tokens, max_retries=1,
                               deadline_s=100.0, breaker=br)
            for _ in range(2):
                with pytest.raises(TransportError):
                    t.request("GET", "/x")
            assert br.state == OPEN
            clock.advance(31.0)
            with pytest.raises(TransportError):
                t.request("GET", "/x")  # the probe, dying on token fetch
            assert br.state == OPEN  # re-tripped, NOT wedged half-open
            tokens.ok = True
            clock.advance(31.0)
            assert t.request("GET", "/x") == {"ok": True}  # next probe heals
            assert br.state == CLOSED
        finally:
            srv.stop()

    def test_4xx_does_not_trip(self):
        srv = ScriptedServer([(404, {})])
        try:
            clock, sleeps = FakeClock(), []
            br = CircuitBreaker(failure_threshold=2, clock=clock)
            t = make_transport(srv, clock, sleeps, max_retries=1,
                               deadline_s=100.0, breaker=br)
            for _ in range(5):
                with pytest.raises(TransportError):
                    t.request("GET", "/x")
            assert br.state == CLOSED  # a response proves the API is alive
        finally:
            srv.stop()

    def test_state_change_callback_fires(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=2, reset_timeout_s=10.0,
                            clock=clock)
        changes = []
        br.on_state_change = lambda old, new: changes.append((old, new))
        br.record_failure()
        br.record_failure()
        assert changes == [(CLOSED, OPEN)]
        clock.advance(11.0)
        assert br.allow()
        br.record_success()
        assert changes == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                           (HALF_OPEN, CLOSED)]


class TestRetryObservability:
    def test_retries_counted_and_spanned(self):
        from k8s_runpod_kubelet_tpu.metrics import Metrics
        from k8s_runpod_kubelet_tpu.tracing import Tracer
        srv = ScriptedServer([(503, {}), (503, {}), (200, {})])
        try:
            clock, sleeps = FakeClock(), []
            m, tr = Metrics(), Tracer(clock=time.time)
            t = make_transport(srv, clock, sleeps, max_retries=3,
                               deadline_s=100.0, metrics=m, tracer=tr)
            assert t.request("GET", "/x") == {"ok": True}
            assert m.get_counter("tpu_cloud_request_retries",
                                 {"reason": "5xx"}) == 2
            spans = [s for s in tr.recent() if s["name"] == "cloud.retry"]
            assert len(spans) == 2
            assert spans[0]["attrs"]["status"] == 503
        finally:
            srv.stop()
