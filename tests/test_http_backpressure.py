"""HTTP-layer backpressure (r3 VERDICT weak item 7): the serving front end
bounds in-flight connections; overload gets an immediate 503 + Retry-After
on the raw socket instead of an unbounded thread pile-up.

Uses a stub engine (serve_main has no jax at module level) — this is pure
socket/threading behavior, fast tier."""

import http.client
import json
import socket
import threading
import time
import types

from k8s_runpod_kubelet_tpu.workloads.serve_main import serve


class _Metrics:
    def __init__(self):
        self.counts = {}
        self.help = {}

    def incr(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n

    def describe(self, name, help_text, buckets=None):
        self.help[name] = help_text

    def render(self):
        return "".join(f"{k}_total {v}\n" for k, v in self.counts.items())


def _stub_engine():
    return types.SimpleNamespace(metrics=_Metrics(), alive=True)


def _hold(port):
    """A connection whose handler thread blocks mid-request (slowloris)."""
    s = socket.create_connection(("127.0.0.1", port))
    s.sendall(b"POST /generate HTTP/1.1\r\n")  # never finishes the request
    return s


class TestHttpBackpressure:
    def test_overflow_rejected_with_503(self):
        eng = _stub_engine()
        httpd = serve(eng, 0, max_connections=2)
        port = httpd.server_address[1]
        holders = []
        try:
            holders = [_hold(port), _hold(port)]
            time.sleep(0.3)  # both accepted; slots full
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            c.request("POST", "/generate", body=json.dumps({"tokens": [1]}),
                      headers={"Content-Type": "application/json"})
            resp = c.getresponse()
            assert resp.status == 503
            assert resp.getheader("Retry-After") == "1"
            assert "overloaded" in json.loads(resp.read())["error"]
            c.close()
            assert eng.metrics.counts["tpu_serving_http_rejected"] >= 1
        finally:
            for s in holders:
                s.close()
            httpd.shutdown()

    def test_observability_survives_overload(self):
        # the scrape that should SEE the overload must not be shed by it:
        # /metrics and /healthz ride the reserved pool when the main pool
        # is full of slowloris holds
        eng = _stub_engine()
        httpd = serve(eng, 0, max_connections=1)
        port = httpd.server_address[1]
        holders = []
        try:
            holders = [_hold(port)]
            time.sleep(0.3)
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            c.request("GET", "/healthz")
            assert c.getresponse().status == 200
            c.close()
            # generate load is still shed while observability is served
            c2 = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            c2.request("POST", "/generate", body="{}")
            assert c2.getresponse().status == 503
            c2.close()
            c3 = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            c3.request("GET", "/metrics")
            r3 = c3.getresponse()
            assert r3.status == 200
            assert "tpu_serving_http_rejected_total 1" in r3.read().decode()
            c3.close()
        finally:
            for s in holders:
                s.close()
            httpd.shutdown()

    def test_obs_admission_cannot_smuggle_engine_work(self):
        # a connection admitted through the RESERVE by peeking GET /healthz
        # is closed after that response — keep-alive must not let it run
        # POST /generate on the reserved slot while overloaded
        eng = _stub_engine()
        httpd = serve(eng, 0, max_connections=1)
        port = httpd.server_address[1]
        holders = []
        try:
            holders = [_hold(port)]
            time.sleep(0.3)
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            c.request("GET", "/healthz")
            r = c.getresponse()
            assert r.status == 200
            assert r.getheader("Connection") == "close"
            r.read()
        finally:
            for s in holders:
                s.close()
            httpd.shutdown()

    def test_dribbling_client_cannot_stall_accepts(self):
        # the reject drain is bounded by wall time and bytes, and triage
        # runs off the accept thread: while an overflow client dribbles
        # bytes, an observability request must still be served promptly
        eng = _stub_engine()
        httpd = serve(eng, 0, max_connections=1)
        port = httpd.server_address[1]
        stop = threading.Event()

        def dribble(sock):
            try:
                while not stop.wait(0.05):
                    sock.sendall(b"x")
            except OSError:
                pass

        holders, dribblers = [], []
        try:
            holders = [_hold(port)]
            time.sleep(0.3)
            for _ in range(3):  # overflow connections that keep sending
                s = socket.create_connection(("127.0.0.1", port))
                s.sendall(b"POST /generate HTTP/1.1\r\n")
                t = threading.Thread(target=dribble, args=(s,), daemon=True)
                t.start()
                dribblers.append(s)
            time.sleep(0.2)
            t0 = time.monotonic()
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            c.request("GET", "/healthz")
            assert c.getresponse().status == 200
            assert time.monotonic() - t0 < 3.0  # served while dribbling
            c.close()
        finally:
            stop.set()
            for s in holders + dribblers:
                s.close()
            httpd.shutdown()

    def test_slot_release_restores_service(self):
        eng = _stub_engine()
        httpd = serve(eng, 0, max_connections=1)
        port = httpd.server_address[1]
        try:
            h = _hold(port)
            time.sleep(0.3)
            # full: next connection is rejected outright
            probe = socket.create_connection(("127.0.0.1", port))
            probe.settimeout(3)
            assert b"503" in probe.recv(64)
            probe.close()
            # handler finishes (client vanished) -> slot released
            h.close()
            time.sleep(0.3)
            fresh = _hold(port)
            fresh.settimeout(0.4)
            try:
                data = fresh.recv(64)  # no 503: server is waiting on us
            except socket.timeout:
                data = b""
            assert b"503" not in data
            fresh.close()
        finally:
            httpd.shutdown()
