"""Metrics-lint: every metric a call site emits must carry a describe() HELP.

Greps the package source for ``incr/set_gauge/observe/time_block`` call
sites with literal metric names and fails if any name lacks a matching
``describe()`` somewhere in the package — the README "Observability"
catalogue stays honest as metrics accumulate (ISSUE 2 satellite). Literal
names only: a dynamic name can't be linted statically, and this repo uses
none (asserted below so one can't sneak in unnoticed).
"""

import pathlib
import re

PKG = pathlib.Path(__file__).resolve().parent.parent / "k8s_runpod_kubelet_tpu"

# call sites: metrics.incr("name"...) etc., tolerant of a line break
# between the paren and the name
USE_RE = re.compile(
    r'\.(?:incr|set_gauge|observe|time_block)\(\s*"([a-zA-Z0-9_]+)"', re.S)
DESCRIBE_RE = re.compile(r'\.describe\(\s*\n?\s*"([a-zA-Z0-9_]+)"', re.S)
# a metrics call whose first argument is NOT a string literal (dynamic name);
# the receiver must literally end in "metrics" so the registry's own internal
# plumbing (e.g. _Timer's self.m.observe(self.name, ...)) stays exempt
DYNAMIC_RE = re.compile(
    r'metrics\.(?:incr|set_gauge|observe|time_block)\(\s*[^")\s]', re.S)


def _sources():
    for path in sorted(PKG.rglob("*.py")):
        yield path, path.read_text(encoding="utf-8")


def test_every_emitted_metric_is_described():
    used: dict[str, set] = {}
    described: set[str] = set()
    for path, src in _sources():
        for name in USE_RE.findall(src):
            used.setdefault(name, set()).add(path.name)
        described.update(DESCRIBE_RE.findall(src))
    assert used, "lint found no metric call sites — regex rotted?"
    missing = {n: sorted(files) for n, files in sorted(used.items())
               if n not in described}
    assert not missing, (
        "metrics emitted without a describe() HELP entry (add one next to "
        f"the other describes, and catalogue it in README): {missing}")


def test_no_dynamic_metric_names():
    """The lint above only sees literals; a computed metric name would
    silently escape it. This repo has none — keep it that way (build the
    variability into labels instead)."""
    offenders = []
    for path, src in _sources():
        for m in DYNAMIC_RE.finditer(src):
            snippet = src[m.start():m.start() + 60].splitlines()[0]
            offenders.append(f"{path.name}: {snippet}")
    assert not offenders, offenders


def test_known_metric_families_present():
    """Spot-check the SLO metrics this PR introduces are described (guards
    against a rename in one place but not the other)."""
    described = set()
    for _, src in _sources():
        described.update(DESCRIBE_RE.findall(src))
    for name in ("tpu_serving_ttft_seconds", "tpu_serving_inter_token_seconds",
                 "tpu_serving_queue_wait_seconds",
                 "tpu_serving_batch_utilization",
                 "tpu_serving_kv_cache_tokens",
                 "tpu_kubelet_schedule_to_ready_seconds",
                 # fleet tier (ISSUE 4): registry + router + autoscaler
                 "tpu_fleet_replicas", "tpu_fleet_evictions",
                 "tpu_fleet_requests", "tpu_fleet_failovers",
                 "tpu_fleet_stream_aborted", "tpu_fleet_rejected_saturated",
                 "tpu_fleet_route_seconds", "tpu_fleet_desired_replicas",
                 "tpu_fleet_scale_ups", "tpu_fleet_scale_downs",
                 "tpu_serving_draining", "tpu_serving_drain_rejected",
                 # training telemetry (ISSUE 5): workload side...
                 "tpu_training_step_seconds", "tpu_training_tokens_per_second",
                 "tpu_training_mfu_ratio", "tpu_training_goodput_ratio",
                 "tpu_training_lost_seconds", "tpu_training_last_step",
                 "tpu_training_checkpoint_seconds",
                 "tpu_training_straggler_events",
                 # ...and the kubelet's per-pod scrape re-exports
                 "tpu_training_pod_goodput", "tpu_training_pod_mfu",
                 "tpu_training_pod_tokens_per_second",
                 "tpu_training_pod_last_step", "tpu_training_pod_stalled",
                 "tpu_kubelet_training_stalls",
                 # elastic gang training (ISSUE 6): workload-side resize
                 # telemetry + the kubelet's resize counters
                 "tpu_training_resize_events", "tpu_training_resize_seconds",
                 "tpu_training_resize_dp_width",
                 "tpu_kubelet_gang_resizes",
                 "tpu_kubelet_gang_resize_failures",
                 "tpu_kubelet_host_loss_requeues"):
        assert name in described, name
