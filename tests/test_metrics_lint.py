"""Metrics-lint: every metric a call site emits must carry a describe() HELP.

Now a thin shim over the shared graftlint framework (ISSUE 7): the
AST-based observability checker subsumes the old regexes (and extends the
contract to span names + the README catalogue); this file keeps the
historical test names and the spot-check list, all off the ONE cached
package parse.
"""

import ast

from k8s_runpod_kubelet_tpu.analysis import get_package_index
from k8s_runpod_kubelet_tpu.analysis.checkers import ObservabilityChecker


def _result():
    return ObservabilityChecker().run(get_package_index())


def test_every_emitted_metric_is_described():
    bad = [f for f in _result().findings if f.key[0] == "undescribed"]
    assert not bad, (
        "metrics emitted without a describe() HELP entry (add one next to "
        "the other describes, and catalogue it in README): "
        + "; ".join(f.text() for f in bad))


def test_no_dynamic_metric_names():
    """The lint only sees literals; a computed metric/span name would
    silently escape it. Keep the set closed (build variability into labels
    instead) — the rare justified case is allowlisted on the checker."""
    bad = [f for f in _result().findings if f.key[0] == "dynamic"]
    assert not bad, "; ".join(f.text() for f in bad)


def test_known_metric_families_present():
    """Spot-check the SLO metric families accumulated across ISSUEs 2-6 are
    still described (guards against a rename in one place but not the
    other) — collected from the SHARED parse, not a private regex pass."""
    described = set()
    for fi in get_package_index().files():
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "describe" \
                    and node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                described.add(node.args[0].value)
    assert described, "lint found no describe() call sites — walker rotted?"
    for name in ("tpu_serving_ttft_seconds", "tpu_serving_inter_token_seconds",
                 "tpu_serving_queue_wait_seconds",
                 "tpu_serving_batch_utilization",
                 "tpu_serving_kv_cache_tokens",
                 "tpu_kubelet_schedule_to_ready_seconds",
                 # fleet tier (ISSUE 4): registry + router + autoscaler
                 "tpu_fleet_replicas", "tpu_fleet_evictions",
                 "tpu_fleet_requests", "tpu_fleet_failovers",
                 "tpu_fleet_stream_aborted", "tpu_fleet_rejected_saturated",
                 "tpu_fleet_route_seconds", "tpu_fleet_desired_replicas",
                 "tpu_fleet_scale_ups", "tpu_fleet_scale_downs",
                 "tpu_serving_draining", "tpu_serving_drain_rejected",
                 # training telemetry (ISSUE 5): workload side...
                 "tpu_training_step_seconds", "tpu_training_tokens_per_second",
                 "tpu_training_mfu_ratio", "tpu_training_goodput_ratio",
                 "tpu_training_lost_seconds", "tpu_training_last_step",
                 "tpu_training_checkpoint_seconds",
                 "tpu_training_straggler_events",
                 # ...and the kubelet's per-pod scrape re-exports
                 "tpu_training_pod_goodput", "tpu_training_pod_mfu",
                 "tpu_training_pod_tokens_per_second",
                 "tpu_training_pod_last_step", "tpu_training_pod_stalled",
                 "tpu_kubelet_training_stalls",
                 # elastic gang training (ISSUE 6): workload-side resize
                 # telemetry + the kubelet's resize counters
                 "tpu_training_resize_events", "tpu_training_resize_seconds",
                 "tpu_training_resize_dp_width",
                 "tpu_kubelet_gang_resizes",
                 "tpu_kubelet_gang_resize_failures",
                 "tpu_kubelet_host_loss_requeues"):
        assert name in described, name
