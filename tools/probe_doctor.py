"""Bisect the ``backend_init`` wedge probe_diag has reported since BENCH_r05.

probe_diag.py answers WHICH stage hangs (backend_init, i.e. PJRT client
creation dialing the axon relay) and captures the hang stack. This tool
answers the next question — WHY — by bisecting backend_init across the
inputs it depends on, then writing the round file the trajectory needs
(BENCH_r<NN>.json: a measured row if the chip answers, a loud
``unreachable: true`` row carrying the doctor's findings otherwise).

Bisection axes (each a probe_diag child variant under a SHORT
faulthandler budget, so five hanging variants stay under ~5 minutes):

  cpu_control         JAX_PLATFORMS=cpu — is the harness itself sound?
  default             env as-is — the baseline wedge
  no_remote_compile   remote-compile endpoint out of the dial path
  no_pool_ips         PALLAS_AXON_POOL_IPS deleted — does the dial
                      target matter, or does init wedge before it ever
                      reads the pool?
  no_ports            every explicit PALLAS_AXON_*PORT* hint deleted —
                      same question for the port plumbing

Alongside the child matrix the parent collects the cheap evidence that
decides what a wedge MEANS: is anything listening on the configured
relay ports (relay process gone vs relay up but the pool grant never
arrives), and how long the trajectory has carried this wedge (scan of
BENCH_r*.json probe_diag summaries — the "since BENCH_r05" claim is
measured, not remembered).

Usage:
  python tools/probe_doctor.py              # bisect + write BENCH round
  python tools/probe_doctor.py --no-round   # bisect only
"""

from __future__ import annotations

import json
import os
import sys
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
_RESULTS_DIR = os.path.join(_REPO, "bench_results")
sys.path.insert(0, _TOOLS)   # probe_diag is a sibling script, not a package
sys.path.insert(0, _REPO)    # bench.py, for the round-writing machinery

import probe_diag  # noqa: E402

# short per-stage budget: the doctor runs MORE variants than probe_diag,
# and a wedge that survives 45s of PJRT init is the same wedge at 120s
_STAGE_S = int(os.environ.get("PROBE_DOCTOR_STAGE_TIMEOUT_S", "45"))
_COMPILE_S = int(os.environ.get("PROBE_DOCTOR_COMPILE_TIMEOUT_S", "90"))

_PORT_VARS = ["PALLAS_AXON_RELAY_PORT", "PALLAS_AXON_PORT",
              "PALLAS_AXON_PORT_RANGE"]

# (name, env_overrides, env_deletes, expected_backend) — the bisection
# matrix; cpu_control first so a broken harness is diagnosed before five
# 45s hangs are spent on it
_BISECT = [
    ("cpu_control", {"JAX_PLATFORMS": "cpu"}, [], "cpu"),
    ("default", {"JAX_PLATFORMS": "axon"}, [], "axon"),
    ("no_remote_compile", {"JAX_PLATFORMS": "axon"},
     ["PALLAS_AXON_REMOTE_COMPILE"], "axon"),
    ("no_pool_ips", {"JAX_PLATFORMS": "axon"},
     ["PALLAS_AXON_POOL_IPS"], "axon"),
    ("no_ports", {"JAX_PLATFORMS": "axon"},
     ["PALLAS_AXON_POOL_IPS"] + _PORT_VARS, "axon"),
]


def _round_history() -> list:
    """(round, wedged stage of the default variant) from every
    BENCH_r*.json that carried a probe_diag summary — the measured
    history of the wedge this doctor is bisecting."""
    import re
    out = []
    try:
        names = sorted(os.listdir(_REPO))
    except OSError:
        return out
    for name in names:
        m = re.match(r"^BENCH_r(\d+)\.json$", name)
        if not m:
            continue
        try:
            with open(os.path.join(_REPO, name), encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = rec.get("parsed") or {}
        diag = parsed.get("probe_diag") or {}
        wedge = (diag.get("variants") or {}).get("default")
        out.append({"round": int(m.group(1)),
                    "unreachable": bool(parsed.get("unreachable")),
                    "default_wedge": wedge})
    return out


def _env_audit() -> dict:
    """The PALLAS_AXON_*/JAX_PLATFORMS surface the axon sitecustomize
    reads at interpreter start — values included verbatim because the
    diagnosis often IS a value (a stale pool IP, an odd port range)."""
    keys = sorted(k for k in os.environ
                  if k.startswith("PALLAS_AXON") or k == "JAX_PLATFORMS")
    return {k: os.environ[k] for k in keys}


def _port_evidence() -> dict:
    hints = probe_diag._relay_port_hints()
    listening = probe_diag._listening_ports()
    connect = probe_diag._tcp_connect_report(hints) if hints else {}
    return {"configured_ports": hints,
            "listening_ports": listening,
            "configured_and_listening": sorted(
                set(hints) & set(listening)),
            "connect": {str(p): v for p, v in connect.items()}}


def _findings(variants: list, ports: dict, history: list) -> list:
    """Human-readable verdicts, most load-bearing first. Each one is a
    claim the evidence above supports — the point of the doctor is that
    'wedged' stops being a mood and becomes a mechanism."""
    by_name = {v["variant"]: v for v in variants}
    out = []

    cpu = by_name.get("cpu_control")
    if cpu is not None and not cpu.get("ok"):
        out.append("harness UNSOUND: the cpu control wedged at "
                   f"{cpu.get('wedged_stage')!r} — every axon verdict "
                   "below is suspect until the control passes")
    elif cpu is not None:
        out.append("harness sound: cpu control ran all five stages")

    axon = [v for v in variants if v["variant"] != "cpu_control"]
    wedges = {v["variant"]: v.get("wedged_stage") for v in axon}
    if axon and all(w == "backend_init" for w in wedges.values()):
        errs = {v["variant"]: (v.get("stage_errors") or {})
                .get("backend_init", "") for v in axon}
        if all(errs.values()) and all(
                "not in the list of known backends" in e
                for e in errs.values()):
            out.append("axon backend NOT REGISTERED: backend_init "
                       "fast-fails under every axon variant ('axon' is "
                       "absent from jax's known backends) — the relay's "
                       "sitecustomize/PJRT plugin never registered in "
                       "this interpreter, so there is nothing to dial "
                       "and no pool/port/remote-compile knob can matter; "
                       "fix is provisioning the axon plugin, not "
                       "retrying bench")
        elif any(v.get("hang_stack") for v in axon):
            out.append("backend_init HANGS under every axon variant "
                       f"({', '.join(sorted(wedges))}) — the wedge is in "
                       "PJRT client creation itself, upstream of the "
                       "pool-IP, port and remote-compile plumbing the "
                       "variants removed; no env change on this host "
                       "can route around it")
        else:
            out.append("backend_init fails under every axon variant "
                       f"({', '.join(sorted(wedges))}): "
                       + "; ".join(sorted(set(filter(None,
                                                     errs.values()))))[:400])
    else:
        for name, wedge in sorted(wedges.items()):
            if wedge is None and by_name[name].get("ok"):
                out.append(f"variant {name} PASSED — the axes it removes "
                           "are implicated in the default wedge")
            elif wedge != "backend_init":
                out.append(f"variant {name} moved the wedge to {wedge!r} "
                           "— backend_init is past that axis")

    hints = ports.get("configured_ports") or []
    live = ports.get("configured_and_listening") or []
    if not hints:
        out.append("no relay port is configured (no PALLAS_AXON_*PORT*/"
                   "POOL_IPS hints): the PJRT dial has no explicit "
                   "target, consistent with an init that blocks waiting "
                   "for a relay that was never provisioned here")
    elif not live:
        out.append(f"relay GONE: nothing listens on configured ports "
                   f"{hints} — restarting/reprovisioning the relay is "
                   "the fix; retrying bench is not")
    else:
        out.append(f"relay LISTENING on {live} yet backend_init still "
                   "hangs — the TCP handshake succeeds but the pool "
                   "grant never arrives; the wedge is server-side "
                   "(relay up, pool empty or grant path dead)")

    wedged_rounds = [h["round"] for h in history
                     if h.get("default_wedge") == "backend_init"]
    if wedged_rounds:
        out.append("trajectory: backend_init wedge recorded on rounds "
                   f"{wedged_rounds} (first r{min(wedged_rounds):02d}) — "
                   "a persistent environment state, not a flake")

    stack = next((v.get("hang_stack") for v in axon
                  if v.get("hang_stack")), "")
    if stack:
        first = next((ln.strip() for ln in stack.splitlines()
                      if ln.strip().startswith("File")), "")
        if first:
            out.append(f"hang site (faulthandler): {first}")
    return out


def main() -> int:
    write_round = "--no-round" not in sys.argv
    budget = 2 * _STAGE_S + _COMPILE_S + 2 * _STAGE_S + 30
    child_env = {"PROBE_DIAG_STAGE_TIMEOUT_S": str(_STAGE_S),
                 "PROBE_DIAG_COMPILE_TIMEOUT_S": str(_COMPILE_S)}

    audit = _env_audit()
    ports = _port_evidence()
    history = _round_history()
    variants = []
    for name, overrides, deletes, expect in _BISECT:
        print(f"[doctor] variant {name} "
              f"(budget {budget}s)...", file=sys.stderr, flush=True)
        v = probe_diag.run_variant(name, {**overrides, **child_env},
                                   deletes, budget, expect)
        variants.append(v)
        print(f"[doctor]   -> "
              f"{'ok' if v['ok'] else 'wedged@' + str(v['wedged_stage'])} "
              f"({v['wall_s']}s)", file=sys.stderr, flush=True)
        if name == "cpu_control" and not v["ok"]:
            break  # a broken harness makes the axon matrix meaningless

    findings = _findings(variants, ports, history)
    report = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
              "stage_timeout_s": _STAGE_S,
              "env_audit": audit, "ports": ports,
              "round_history": history,
              "variants": variants, "findings": findings}
    os.makedirs(_RESULTS_DIR, exist_ok=True)
    path = os.path.join(_RESULTS_DIR, "probe_doctor.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
        f.write("\n")

    reachable = any(v["variant"] != "cpu_control" and v.get("ok")
                    for v in variants)
    print(json.dumps({"metric": "probe_doctor", "reachable": reachable,
                      "findings": findings, "path": path}), flush=True)
    if write_round:
        _write_round(reachable, findings)
    return 0


def _write_round(reachable: bool, findings: list) -> None:
    """The round file this diagnosis belongs to. Reachable: one real
    headline attempt through bench's own child runner (the measured
    row). Unreachable: bench's best-known on-chip record, stamped
    ``unreachable`` with the doctor's findings and the control-plane
    cells that need no chip — the same shape orchestrate() writes, so
    the trajectory stays uniform."""
    import bench

    if reachable:
        parsed, rc, tail = bench._run_child(quick=False, platform=None,
                                            timeout_s=1800)
        if parsed is not None and parsed.get("value") is not None:
            bench._append_tpu_record(parsed, source="probe_doctor_live")
            bench._emit(parsed)
            return
        print(f"[doctor] reachable probe but headline failed "
              f"(rc={rc}): {tail[-200:]}", file=sys.stderr)

    best = bench._best_known_record()
    if best is None:
        print("[doctor] no best-known record; nothing to anchor a round",
              file=sys.stderr)
        return
    line = dict(best["line"])
    line.update(source="best_known_record", stale=True, unreachable=True,
                measured_ts=best.get("ts"),
                measured_commit=best.get("commit"),
                measured_source=best.get("source"),
                age_h=round(bench._result_age_s(best) / 3600, 1),
                tpu_errors=["probe_doctor: backend_init bisect, "
                            "see probe_doctor"])
    diag = bench._probe_diag_summary()
    if diag is not None:
        line["probe_diag"] = diag
    line["probe_doctor"] = {"findings": findings,
                            "path": "bench_results/probe_doctor.json"}
    smoke = bench._scheduler_smoke_lines()
    if smoke is not None:
        line["scheduler_cpu_smoke"] = smoke
    bench._write_unreachable_round(line)
    bench._emit(line)


if __name__ == "__main__":
    sys.exit(main())
