"""TPU tunnel probe diagnosis: WHICH stage wedges, with the hang stack.

Four rounds of "probe hung > 300s (tunnel wedged?)" is monitoring, not
diagnosis (VERDICT r4 weak item 7). This tool decomposes the probe into
stages and runs them across env variants, capturing the Python-level stack
at the moment of a hang (faulthandler), so a wedged tunnel produces
"backend_init blocked in PJRT client creation under variant default" rather
than a bare timeout.

Stages (each is a marker line on the child's stdout):
  import_jax    -> pure import; never touches the tunnel
  backend_init  -> jax.default_backend(); creates the PJRT client, i.e.
                   dials the axon relay (the historically observed hang)
  devices       -> jax.devices(); device enumeration over the live client
  tiny_compile  -> jit((x+1).sum) on (8,8); exercises the (remote) compile
                   path — r4 observed a HALF-UP state where init works and
                   compile dies
  tiny_execute  -> second call of the jitted fn; cached-executable dispatch

Variants (parent env overrides; the axon sitecustomize reads these at
interpreter start, so a child process is the unit of variation):
  default            env as-is (JAX_PLATFORMS=axon, remote_compile per env)
  no_remote_compile  PALLAS_AXON_REMOTE_COMPILE deleted -> register() with
                     remote_compile=False; distinguishes "relay dead" from
                     "remote-compile endpoint dead"
  cpu_control        JAX_PLATFORMS=cpu; validates the harness itself

Usage:
  python tools/probe_diag.py            # full matrix, JSON to stdout,
                                        # persisted to bench_results/
  python tools/probe_diag.py --child    # internal: one variant's stages
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RESULTS_DIR = os.path.join(_HERE, "bench_results")

# (name, env_overrides, env_deletes, expected_backend)
# JAX_PLATFORMS is pinned EXPLICITLY per variant: inheriting it from the
# parent once let a pytest-env run (JAX_PLATFORMS=cpu) produce an all-pass
# "axon" diagnosis that was really three CPU runs.
_VARIANTS = [
    ("default", {"JAX_PLATFORMS": "axon"}, [], "axon"),
    ("no_remote_compile", {"JAX_PLATFORMS": "axon"},
     ["PALLAS_AXON_REMOTE_COMPILE"], "axon"),
    ("cpu_control", {"JAX_PLATFORMS": "cpu"}, [], "cpu"),
]

_STAGE_TIMEOUT_S = int(os.environ.get("PROBE_DIAG_STAGE_TIMEOUT_S", "120"))
_COMPILE_TIMEOUT_S = int(os.environ.get("PROBE_DIAG_COMPILE_TIMEOUT_S", "300"))


def _child() -> int:
    """Run the stages in-process. A faulthandler timer is armed before each
    stage and cancelled after it: if the stage hangs, the child dumps every
    thread's stack to stderr and exits, and the parent attributes the hang
    to the last stage with no ok-marker."""
    import faulthandler

    def marker(stage: str, ok: bool, t0: float, err: str = "") -> None:
        print(json.dumps({"stage": stage, "ok": ok,
                          "s": round(time.monotonic() - t0, 2),
                          **({"error": err[:300]} if err else {})}),
              flush=True)

    def run_stage(stage: str, fn, timeout_s: int) -> bool:
        t0 = time.monotonic()
        faulthandler.dump_traceback_later(timeout_s, exit=True)
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — an ERROR is a diagnosis too
            faulthandler.cancel_dump_traceback_later()
            marker(stage, False, t0, f"{type(e).__name__}: {e}")
            return False
        faulthandler.cancel_dump_traceback_later()
        marker(stage, True, t0)
        return True

    ns: dict = {}

    def s_import():
        import jax
        ns["jax"] = jax
        # The axon sitecustomize's register() wins over the env var (it runs
        # at interpreter start and re-pins the platform); re-assert the env's
        # choice so cpu_control is a true harness control rather than a
        # second axon dial (observed: cpu_control wedged at backend_init
        # with the axon 'experimental platform' warning).
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError:
                pass

    def s_backend():
        ns["backend"] = ns["jax"].default_backend()

    def s_devices():
        ns["devices"] = ns["jax"].devices()

    def s_compile():
        jax = ns["jax"]
        import jax.numpy as jnp
        ns["fn"] = jax.jit(lambda x: (x + 1).sum())
        ns["x"] = jnp.zeros((8, 8))
        ns["v"] = int(ns["fn"](ns["x"]))

    def s_execute():
        v = int(ns["fn"](ns["x"]))
        if v != 64:
            raise ValueError(f"wrong result {v}")

    for stage, fn, to in [("import_jax", s_import, _STAGE_TIMEOUT_S),
                          ("backend_init", s_backend, _STAGE_TIMEOUT_S),
                          ("devices", s_devices, _STAGE_TIMEOUT_S),
                          ("tiny_compile", s_compile, _COMPILE_TIMEOUT_S),
                          ("tiny_execute", s_execute, _STAGE_TIMEOUT_S)]:
        if not run_stage(stage, fn, to):
            return 1
    print(json.dumps({"stage": "all", "ok": True,
                      "backend": ns.get("backend"),
                      "n_devices": len(ns.get("devices", []))}), flush=True)
    return 0


def _tcp_connect_report(ports: list[int], timeout_s: float = 3.0) -> dict:
    """Can we complete a TCP handshake with each candidate relay port?
    Distinguishes 'relay process gone' (connect refused) from 'relay up
    but the pool grant never arrives' (connect ok, PJRT init still
    hangs) — the difference decides whether restarting the relay could
    help at all. Tries IPv4 then IPv6 loopback (the listener may be
    bound to either family)."""
    import socket
    out = {}
    for port in ports:
        last = ""
        for host in ("127.0.0.1", "::1"):
            try:
                with socket.create_connection((host, port),
                                              timeout=timeout_s):
                    last = "connect_ok"
                    break
            except OSError as e:
                last = f"{type(e).__name__}: {e}"[:120]
        out[port] = last
    return out


def _relay_port_hints() -> list[int]:
    """Ports the axon relay is CONFIGURED to use, from PALLAS_AXON_* env:
    explicit single ports (PALLAS_AXON_RELAY_PORT / PALLAS_AXON_PORT),
    host:port entries in PALLAS_AXON_POOL_IPS, and an inclusive
    PALLAS_AXON_PORT_RANGE ("8470-8479"). Empty when nothing is
    configured — the caller then falls back to the bounded scan."""
    ports: set[int] = set()
    for var in ("PALLAS_AXON_RELAY_PORT", "PALLAS_AXON_PORT"):
        val = os.environ.get(var, "")
        for part in val.split(","):
            part = part.strip()
            if part.isdigit():
                ports.add(int(part))
    for entry in os.environ.get("PALLAS_AXON_POOL_IPS", "").split(","):
        _, sep, port = entry.strip().rpartition(":")
        if sep and port.isdigit():
            ports.add(int(port))
    rng = os.environ.get("PALLAS_AXON_PORT_RANGE", "")
    if "-" in rng:
        lo, _, hi = rng.partition("-")
        if lo.strip().isdigit() and hi.strip().isdigit():
            lo_i, hi_i = int(lo), int(hi)
            # inclusive (a single-port "8470-8470" range is a valid hint);
            # bounded: a typo'd range must not enumerate the port space
            if 0 <= hi_i - lo_i < 1024:
                ports.update(range(lo_i, hi_i + 1))
    return sorted(p for p in ports if 0 < p < 65536)


def _listening_ports() -> list[int]:
    """Local listening TCP ports from /proc/net/tcp{,6} (no psutil). The
    axon relay lives on localhost — if nothing is listening, the PJRT dial
    has nothing to reach and 'wedged' really means 'relay gone'."""
    ports: set[int] = set()
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path, encoding="ascii") as f:
                next(f)
                for line in f:
                    parts = line.split()
                    if len(parts) > 3 and parts[3] == "0A":  # LISTEN
                        ports.add(int(parts[1].rsplit(":", 1)[1], 16))
        except (OSError, ValueError, IndexError):
            continue
    return sorted(ports)


def run_variant(name: str, overrides: dict, deletes: list[str],
                budget_s: int, expect_backend: str = "") -> dict:
    env = dict(os.environ)
    env.update(overrides)
    for k in deletes:
        env.pop(k, None)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            capture_output=True, text=True, timeout=budget_s, env=env,
            cwd=_HERE)
        out, err, rc = proc.stdout or "", proc.stderr or "", proc.returncode
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode(errors="replace") if isinstance(
            e.stdout, bytes) else (e.stdout or "")
        err = e.stderr.decode(errors="replace") if isinstance(
            e.stderr, bytes) else (e.stderr or "")
        rc = -9
    stages = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                stages.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    ok_names = [s["stage"] for s in stages if s.get("ok")]
    all_ok = any(s.get("stage") == "all" for s in stages)
    got_backend = next((s.get("backend") for s in stages
                        if s.get("stage") == "all"), None)
    if all_ok and expect_backend and got_backend != expect_backend:
        # a pass on the WRONG backend is a false positive, not a diagnosis
        all_ok = False
        stages.append({"stage": "backend_check", "ok": False,
                       "error": f"expected backend {expect_backend!r}, "
                                f"got {got_backend!r}"})
    # the wedge is the first stage with no ok-marker (hang -> faulthandler
    # exit, or error -> marker with ok=false)
    order = ["import_jax", "backend_init", "devices", "tiny_compile",
             "tiny_execute"]
    wedge = None if all_ok else next(
        (s for s in order if s not in ok_names), None)
    errors = {s["stage"]: s["error"] for s in stages
              if not s.get("ok") and s.get("error")}
    # faulthandler writes "Timeout (0:02:00)!\nThread 0x...\n  File ..." to
    # stderr; keep the current-thread stack (the tail) for the record
    hang_stack = ""
    if "Timeout" in err:
        hang_stack = err[err.rindex("Timeout"):][:2000]
    return {"variant": name, "rc": rc, "ok": all_ok, "wedged_stage": wedge,
            "stage_errors": errors,
            "stages": stages,
            "hang_stack": hang_stack,
            "stderr_tail": "" if hang_stack else err[-1200:],
            "wall_s": round(time.monotonic() - t0, 1)}


def main() -> int:
    if "--child" in sys.argv:
        return _child()
    budget = 2 * _STAGE_TIMEOUT_S + _COMPILE_TIMEOUT_S + 3 * _STAGE_TIMEOUT_S
    report = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
              "env": {k: os.environ.get(k, "") for k in
                      ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS",
                       "PALLAS_AXON_REMOTE_COMPILE", "AXON_LOOPBACK_RELAY",
                       "PALLAS_AXON_TPU_GEN")},
              "listening_ports": _listening_ports(),
              "variants": []}
    # connect-probe only relay-plausible candidates: a connect consumes a
    # pending accept, so poking every listener on the box (ssh forwards,
    # one-shot accept loops — including, ironically, a fragile relay's
    # sibling services) is harm, not diagnosis. When PALLAS_AXON_* env
    # names the relay's ports, probe exactly those — INCLUDING ones with
    # no listener (connecting to a dead port is harmless and an instant
    # "connection refused on 8470" is the relay-down-vs-wedged evidence
    # this report exists for); only with no hint at all fall back to the
    # bounded first-8 listener scan.
    hints = _relay_port_hints()
    candidates = hints if hints else report["listening_ports"][:8]
    report["relay_port_hints"] = hints
    report["tcp_connect"] = _tcp_connect_report(candidates)
    for name, overrides, deletes, expect in _VARIANTS:
        rec = run_variant(name, overrides, deletes, budget, expect)
        report["variants"].append(rec)
        print(f"[diag] {name}: ok={rec['ok']} wedged={rec['wedged_stage']} "
              f"errors={list(rec['stage_errors'])} wall={rec['wall_s']}s",
              file=sys.stderr, flush=True)
        # default wedging at import/backend means every axon variant will
        # too; still run them (cheap signal: does no_remote_compile differ?)
    os.makedirs(_RESULTS_DIR, exist_ok=True)
    path = os.path.join(_RESULTS_DIR, "probe_diag.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    print(json.dumps({"metric": "probe_diag",
                      "variants": {v["variant"]:
                                   (v["wedged_stage"] or
                                    ("ok" if v["ok"] else "error"))
                                   for v in report["variants"]},
                      "path": path}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
