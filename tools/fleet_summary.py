"""Render fleet JSONL (router spans and/or registry snapshots) into
per-replica load + routing-decision tables.

Input lines may be either:
- **spans** from the router's ``--trace-export`` JSONL (``fleet.route``,
  ``fleet.scale``, ``fleet.evict``; other span names are ignored), or
- **registry snapshots** — the ``/debug/fleet`` payload (an object with a
  ``"replicas"`` list), e.g. appended periodically by
  ``curl router:8090/debug/fleet >> fleet.jsonl``.

Both may be mixed in one file. Output:
- a per-replica routing table: requests routed, affinity vs least-loaded
  vs two-hop vs failover share, error count, p50/p95 router-side latency;
- the latest load snapshot per replica — grouped per disaggregated POOL
  (unified / prefill / decode) — with state, slots, queue, KV tokens,
  TTFT/ITL p95 and free KV pages, when snapshots are present;
- the two-hop request timeline: route -> prefill -> handoff -> decode,
  joined per trace_id from the fleet.handoff span and the two engines'
  serving.kv_prefill / serving.kv_adopt spans riding the same trace
  (streamed hops add a chunks count + realized overlap fraction; each
  hop shows the transfer path it took, device or wire);
- a per-path / per-domain handoff rollup (device-native vs wire KV
  movement, hop latency percentiles per placement domain);
- the KV-fabric view (ISSUE 16): directory-lookup outcomes per routed
  replica (fleet.directory_lookup spans — pulled / local / miss / gone /
  no_owner / failed), a per-rung pull rollup (serving.kv_pull spans:
  device / shm / wire pages+bytes+latency), and the latest directory
  snapshot (entries + holders) when /debug/fleet lines carry one;
- per-stream CHUNK timelines for streamed handoffs: each frame's
  compute (serving.kv_chunk), push (serving.kv_push) and decode-side
  adopt (serving.kv_adopt_chunk) spans joined per seq;
- the scale/evict event timeline (scale events carry their pool's role).

Usage:
  python tools/fleet_summary.py fleet.jsonl
  python tools/fleet_summary.py spans.jsonl --top 5
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from collections import defaultdict


KNOWN_SCHEMA_VERSIONS = {1}


def load(path: str) -> tuple[list[dict], list[dict]]:
    """(spans, registry snapshots) from a mixed JSONL file."""
    spans, snapshots = [], []
    warned: set = set()
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                print(f"warning: {path}:{lineno}: bad JSON, skipped",
                      file=sys.stderr)
                continue
            if not isinstance(obj, dict):
                continue
            ver = obj.get("schema_version")
            if ver is not None and ver not in KNOWN_SCHEMA_VERSIONS \
                    and ver not in warned:
                # newer producer than this reader: render best-effort
                warned.add(ver)
                print(f"warning: {path}:{lineno}: unknown schema_version "
                      f"{ver!r}; rendering best-effort", file=sys.stderr)
            if "replicas" in obj and isinstance(obj["replicas"], list):
                snapshots.append(obj)
            elif "name" in obj and "trace_id" in obj:
                spans.append(obj)
    return spans, snapshots


def percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return float("nan")
    rank = max(1, min(len(sorted_vals),
                      math.ceil(p / 100.0 * len(sorted_vals))))
    return sorted_vals[rank - 1]


def _fmt_ms(v: float) -> str:
    return "-" if math.isnan(v) else f"{v * 1000:.1f}ms"


def routing_table(spans: list[dict]) -> list[str]:
    routes = [s for s in spans if s.get("name") == "fleet.route"]
    if not routes:
        return ["(no fleet.route spans)"]
    per: dict[str, dict] = defaultdict(
        lambda: {"n": 0, "affinity": 0, "least_loaded": 0, "two_hop": 0,
                 "failover": 0, "errors": 0, "streams": 0, "durs": []})
    for s in routes:
        a = s.get("attrs", {})
        rid = a.get("replica_id") or "(unrouted)"
        row = per[rid]
        row["n"] += 1
        reason = a.get("reason", "")
        if reason in ("affinity", "least_loaded", "two_hop"):
            row[reason] += 1
        if int(a.get("attempts", 1) or 1) > 1:
            row["failover"] += 1
        if int(a.get("status", 200) or 200) >= 400:
            row["errors"] += 1
        if a.get("streamed"):
            row["streams"] += 1
        row["durs"].append(float(s.get("duration_s", 0.0)))
    out = ["== routing decisions (fleet.route spans) ==",
           f"{'replica':<20} {'reqs':>6} {'affin':>6} {'least':>6} "
           f"{'2hop':>6} {'failov':>6} {'stream':>6} {'errors':>6} "
           f"{'p50':>9} {'p95':>9}"]
    for rid in sorted(per, key=lambda r: -per[r]["n"]):
        row = per[rid]
        durs = sorted(row["durs"])
        out.append(f"{rid:<20} {row['n']:>6} {row['affinity']:>6} "
                   f"{row['least_loaded']:>6} {row['two_hop']:>6} "
                   f"{row['failover']:>6} "
                   f"{row['streams']:>6} {row['errors']:>6} "
                   f"{_fmt_ms(percentile(durs, 50)):>9} "
                   f"{_fmt_ms(percentile(durs, 95)):>9}")
    return out


def load_table(snapshots: list[dict]) -> list[str]:
    if not snapshots:
        return []
    latest: dict[str, dict] = {}
    for snap in snapshots:  # later lines win: the file is appended in order
        for rep in snap.get("replicas", []):
            if isinstance(rep, dict) and rep.get("replica_id"):
                latest[rep["replica_id"]] = rep
    # group by disaggregated pool: each pool scales on different signals,
    # so its replicas are only comparable to each other (prefill: queue/
    # TTFT; decode: ITL + free KV pages; unified: all of them)
    pools: dict[str, list[str]] = defaultdict(list)
    for rid, rep in latest.items():
        pools[rep.get("role") or "unified"].append(rid)
    out = ["", "== latest replica load (registry snapshots) =="]
    for role in ("unified", "prefill", "decode",
                 *sorted(set(pools) - {"unified", "prefill", "decode"})):
        if role not in pools:
            continue
        out += [f"-- pool: {role} ({len(pools[role])} replica(s)) --",
                f"{'replica':<20} {'state':<9} {'gen':<5} {'npool':<8} "
                f"{'slots':>11} {'queue':>6} "
                f"{'kv_tokens':>10} {'ttft_p95':>9} {'itl_p95':>8} "
                f"{'kv_free':>9} {'prefix%':>8} {'spec%':>7} {'hb_age':>7}"]
        for rid in sorted(pools[role]):
            rep = latest[rid]
            st = rep.get("stats", {})
            slots = f"{st.get('active_slots', 0)}/{st.get('max_slots', 0)}"
            # prefix-cache hit rate: per-replica proof the router's
            # prefix-affinity concentrates reusable prompts (ISSUE 8)
            hit = st.get("prefix_hit_rate")
            hit_s = "-" if hit is None else f"{100.0 * float(hit):.1f}%"
            # speculative acceptance rate (ISSUE 14): accepted/proposed
            # drafts — "-" when the replica never proposed (speculate_k=0)
            spec = st.get("spec_acceptance_rate")
            spec_s = "-" if spec is None else f"{100.0 * float(spec):.1f}%"
            total = st.get("kv_pages_total", 0)
            free_s = f"{st.get('kv_pages_free', 0)}/{total}" if total \
                else "-"
            # node-pool identity (ISSUE 19): which generation/pool the
            # scheduler placed this replica onto — "-" for legacy fleets
            gen = rep.get("generation") or "-"
            npool = rep.get("pool") or "-"
            out.append(f"{rid:<20} {rep.get('state', '?'):<9} "
                       f"{gen:<5} {npool:<8} {slots:>11} "
                       f"{st.get('queue_depth', 0):>6} "
                       f"{st.get('kv_cache_tokens', 0):>10} "
                       f"{st.get('ttft_p95_s', 0.0):>8.3f}s "
                       f"{st.get('itl_p95_s', 0.0):>7.3f}s "
                       f"{free_s:>9} "
                       f"{hit_s:>8} "
                       f"{spec_s:>7} "
                       f"{rep.get('heartbeat_age_s', 0.0):>6.1f}s")
    return out


def scheduler_table(snapshots: list[dict]) -> list[str]:
    """Node-pool scheduler view (ISSUE 19): the latest snapshot's
    ``scheduler`` payload — per-pool chip accounting, live placements
    with their goodput-loss preemption estimates, and the
    effective-throughput matrix (measured cells marked ``*``)."""
    sched = None
    for snap in snapshots:  # later lines win
        if isinstance(snap.get("scheduler"), dict):
            sched = snap["scheduler"]
    if not sched:
        return []
    out = ["", f"== node pools (scheduler snapshot, "
               f"policy={sched.get('policy', '?')}) ==",
           f"{'pool':<10} {'gen':<5} {'chips':>6} {'reserved':>9} "
           f"{'free':>6} {'$/chip-hr':>10}"]
    for p in sched.get("pools", []):
        out.append(f"{p.get('pool', '?'):<10} {p.get('generation', '?'):<5} "
                   f"{p.get('total_chips', 0):>6} "
                   f"{p.get('reserved_chips', 0):>9} "
                   f"{p.get('free_chips', 0):>6} "
                   f"{p.get('cost_per_chip_hr', 0.0):>10.2f}")
    placements = sched.get("placements", [])
    if placements:
        out += ["", f"{'placement':<24} {'kind':<9} {'pool':<10} "
                    f"{'chips':>6} {'BE':>3} {'goodput_loss':>13}"]
        for pl in placements:
            out.append(f"{pl.get('tag', '?'):<24} {pl.get('kind', '?'):<9} "
                       f"{pl.get('pool', '?'):<10} {pl.get('chips', 0):>6} "
                       f"{'y' if pl.get('best_effort') else '-':>3} "
                       f"{pl.get('goodput_loss', 0.0):>13.1f}")
    matrix = sched.get("matrix", {})
    if matrix:
        gens = sorted({g for row in matrix.values() for g in row})
        out += ["", "-- effective throughput (kind x generation, "
                    "* = measured) --",
                f"{'kind':<10} " + " ".join(f"{g:>12}" for g in gens)]
        for kind in sorted(matrix):
            cells = []
            for g in gens:
                cell = matrix[kind].get(g, {})
                mark = "*" if cell.get("measured") else " "
                cells.append(f"{cell.get('eff', 0.0):>11.1f}{mark}")
            out.append(f"{kind:<10} " + " ".join(cells))
    return out


def two_hop_table(spans: list[dict], top: int) -> list[str]:
    """Per-trace two-hop timeline: route -> prefill -> handoff -> decode.
    The fleet.handoff span names both replicas; the engines' halves
    (serving.kv_prefill / serving.kv_adopt / serving.request) ride the
    same trace_id via the forwarded traceparent, so one trace joins the
    router and BOTH engines."""
    handoffs = [s for s in spans if s.get("name") == "fleet.handoff"]
    if not handoffs:
        return []
    by_trace: dict[str, dict[str, dict]] = defaultdict(dict)
    for s in spans:
        if s.get("name") in ("fleet.route", "serving.kv_prefill",
                             "serving.kv_adopt", "serving.request"):
            by_trace[s.get("trace_id", "")][s["name"]] = s
    handoffs.sort(key=lambda s: s.get("start", 0.0))
    out = ["", f"== two-hop requests (fleet.handoff spans, last {top}) =="]
    for s in handoffs[-top:]:
        a = s.get("attrs", {})
        tid = s.get("trace_id", "")
        sibs = by_trace.get(tid, {})

        def dur(name):
            sp = sibs.get(name)
            return "-" if sp is None else _fmt_ms(
                float(sp.get("duration_s", 0.0)))

        ok = a.get("ok")
        if ok:
            tail = f"pages={a.get('pages', 0)} bytes={a.get('bytes', 0)}"
            if a.get("streamed"):
                # streamed hop (ISSUE 10): chunk count + realized
                # compute/transfer overlap fraction
                ov = a.get("overlap_ratio")
                tail += (f" chunks={a.get('chunks', 0)}"
                         f" overlap={'-' if ov is None else f'{ov:.0%}'}")
        else:
            tail = (f"FAILED ({a.get('error') or '?'}) -> fell back to "
                    f"{sibs.get('fleet.route', {}).get('attrs', {}).get('replica_id', '?')}")
        out.append(
            f"  trace={tid[:16]} route[{dur('fleet.route')}] -> "
            f"prefill {a.get('prefill_replica', '?')}"
            f"[{dur('serving.kv_prefill')}] -> "
            # the transfer path the hop took (ISSUE 11): device =
            # arena-to-arena, wire = the HTTP codec
            f"handoff[{_fmt_ms(float(s.get('duration_s', 0.0)))}"
            f" path={a.get('path') or 'wire'}] -> "
            f"decode {a.get('decode_replica', '?')}"
            f"[{dur('serving.kv_adopt')}] {tail}")
    return out


def handoff_rollup(spans: list[dict]) -> list[str]:
    """Per-path / per-domain handoff rollup (ISSUE 11): how much KV moved
    device-native vs over the wire, per placement domain — a domain whose
    hops keep landing on `wire` is a misdeclared co-location claim (the
    downgrade counter's per-fleet view)."""
    handoffs = [s for s in spans if s.get("name") == "fleet.handoff"]
    if not handoffs:
        return []
    per: dict[tuple, dict] = defaultdict(
        lambda: {"n": 0, "ok": 0, "pages": 0, "bytes": 0, "durs": []})
    for s in handoffs:
        a = s.get("attrs", {})
        key = (str(a.get("path") or "wire"), str(a.get("domain") or "-"))
        row = per[key]
        row["n"] += 1
        if a.get("ok"):
            row["ok"] += 1
            row["pages"] += int(a.get("pages") or 0)
            row["bytes"] += int(a.get("bytes") or 0)
        row["durs"].append(float(s.get("duration_s", 0.0)))
    out = ["", "== handoff paths (fleet.handoff spans) ==",
           f"{'path':<8} {'domain':<24} {'hops':>6} {'ok':>5} "
           f"{'pages':>8} {'bytes':>12} {'p50':>9} {'p95':>9}"]
    for key in sorted(per):
        path, domain = key
        row = per[key]
        durs = sorted(row["durs"])
        out.append(f"{path:<8} {domain:<24} {row['n']:>6} {row['ok']:>5} "
                   f"{row['pages']:>8} {row['bytes']:>12} "
                   f"{_fmt_ms(percentile(durs, 50)):>9} "
                   f"{_fmt_ms(percentile(durs, 95)):>9}")
    return out


def directory_table(spans: list[dict],
                    snapshots: list[dict]) -> list[str]:
    """KV-fabric directory view (ISSUE 16): lookup outcomes per routed
    replica (how often the fleet directory turned a would-be re-prefill
    into a pull — or answered local / miss / gone) and the latest
    directory contents when a /debug/fleet snapshot carries them."""
    lookups = [s for s in spans
               if s.get("name") == "fleet.directory_lookup"]
    out: list[str] = []
    if lookups:
        outcomes = ("pulled", "local", "miss", "no_owner", "gone",
                    "failed")
        per: dict[str, dict] = defaultdict(
            lambda: {o: 0 for o in outcomes} | {"n": 0, "durs": []})
        for s in lookups:
            a = s.get("attrs", {})
            row = per[str(a.get("replica_id") or "(unrouted)")]
            row["n"] += 1
            oc = str(a.get("outcome") or "")
            if oc in outcomes:
                row[oc] += 1
            row["durs"].append(float(s.get("duration_s", 0.0)))
        out += ["", "== directory lookups (fleet.directory_lookup "
                    "spans) ==",
                f"{'replica':<20} {'lookups':>8} {'pulled':>7} "
                f"{'local':>6} {'miss':>6} {'noown':>6} {'gone':>5} "
                f"{'failed':>7} {'p95':>9}"]
        for rid in sorted(per, key=lambda r: -per[r]["n"]):
            row = per[rid]
            durs = sorted(row["durs"])
            out.append(f"{rid:<20} {row['n']:>8} {row['pulled']:>7} "
                       f"{row['local']:>6} {row['miss']:>6} "
                       f"{row['no_owner']:>6} {row['gone']:>5} "
                       f"{row['failed']:>7} "
                       f"{_fmt_ms(percentile(durs, 95)):>9}")
    latest = None
    for snap in snapshots:  # later lines win, like load_table
        if isinstance(snap.get("directory"), dict):
            latest = snap["directory"]
    if latest is not None:
        entries = latest.get("entries") or {}
        out += ["", f"== prefix directory snapshot "
                    f"({latest.get('size', len(entries))} entries, "
                    f"cap {latest.get('max_entries', '?')}) =="]
        for key in sorted(entries):
            e = entries[key] or {}
            adapter = e.get("adapter") or "-"
            out.append(f"  {key[:16]} pages={e.get('pages', 0)} "
                       f"model={e.get('model', '?')} adapter={adapter} "
                       f"holders={','.join(e.get('holders') or []) or '-'}")
    return out


def pull_rollup(spans: list[dict]) -> list[str]:
    """Per-rung pull rollup (ISSUE 16): how much KV the fabric moved via
    directory pulls on each rung (device / shm / wire) — the pull-side
    sibling of handoff_rollup. Puller-side serving.kv_pull spans only
    (the owner's export span would double-count the hop)."""
    pulls = [s for s in spans if s.get("name") == "serving.kv_pull"
             and (s.get("attrs") or {}).get("side") == "puller"]
    if not pulls:
        return []
    per: dict[str, dict] = defaultdict(
        lambda: {"n": 0, "ok": 0, "gone": 0, "pages": 0, "bytes": 0,
                 "durs": []})
    for s in pulls:
        a = s.get("attrs", {})
        key = str(a.get("path") or ("gone" if a.get("gone") else "failed"))
        row = per[key]
        row["n"] += 1
        if a.get("ok"):
            row["ok"] += 1
            row["pages"] += int(a.get("pages") or 0)
            row["bytes"] += int(a.get("bytes") or 0)
        if a.get("gone"):
            row["gone"] += 1
        row["durs"].append(float(s.get("duration_s", 0.0)))
    out = ["", "== KV pulls per rung (serving.kv_pull spans) ==",
           f"{'rung':<8} {'pulls':>6} {'ok':>5} {'gone':>5} "
           f"{'pages':>8} {'bytes':>12} {'p50':>9} {'p95':>9}"]
    for key in sorted(per):
        row = per[key]
        durs = sorted(row["durs"])
        out.append(f"{key:<8} {row['n']:>6} {row['ok']:>5} "
                   f"{row['gone']:>5} {row['pages']:>8} "
                   f"{row['bytes']:>12} "
                   f"{_fmt_ms(percentile(durs, 50)):>9} "
                   f"{_fmt_ms(percentile(durs, 95)):>9}")
    return out


def chunk_timeline(spans: list[dict], top: int) -> list[str]:
    """Per-stream chunk timeline for STREAMED handoffs (ISSUE 10): the
    prefill side's serving.kv_chunk (compute) / serving.kv_push
    (serialize + POST) spans and the decode side's serving.kv_adopt_chunk
    spans share the hop's trace_id; rows join per seq so the overlap —
    push k riding under compute k+1 — is visible span by span."""
    names = ("serving.kv_chunk", "serving.kv_push",
             "serving.kv_adopt_chunk")
    by_trace: dict[str, dict[int, dict]] = defaultdict(
        lambda: defaultdict(dict))
    order: dict[str, float] = {}
    for s in spans:
        if s.get("name") not in names:
            continue
        seq = (s.get("attrs") or {}).get("seq")
        if seq is None:
            continue
        tid = s.get("trace_id", "")
        by_trace[tid][int(seq)][s["name"]] = s
        order.setdefault(tid, s.get("start", 0.0))
    if not by_trace:
        return []
    out = ["", f"== streamed-handoff chunk timelines (last {top}) =="]
    for tid in sorted(order, key=order.get)[-top:]:
        rows = by_trace[tid]
        total_pages = sum(
            (r.get("serving.kv_chunk", {}).get("attrs") or {})
            .get("pages", 0) for r in rows.values())
        out.append(f"  trace={tid[:16]} ({len(rows)} frames, "
                   f"{total_pages} pages)")
        for seq in sorted(rows):
            row = rows[seq]

            def dur(name):
                sp = row.get(name)
                return "-" if sp is None else _fmt_ms(
                    float(sp.get("duration_s", 0.0)))

            a = (row.get("serving.kv_chunk", {}).get("attrs") or {})
            final = " FINAL" if a.get("final") or (
                row.get("serving.kv_adopt_chunk", {})
                .get("attrs") or {}).get("final") else ""
            out.append(
                f"    seq={seq:<3} compute[{dur('serving.kv_chunk')}] "
                f"push[{dur('serving.kv_push')}] "
                f"adopt[{dur('serving.kv_adopt_chunk')}] "
                f"pages={a.get('pages', 0)}{final}")
    return out


def event_timeline(spans: list[dict], top: int) -> list[str]:
    events = [s for s in spans
              if s.get("name") in ("fleet.scale", "fleet.evict")]
    if not events:
        return []
    events.sort(key=lambda s: s.get("start", 0.0))
    out = ["", f"== scale/evict timeline (last {top}) =="]
    for s in events[-top:]:
        a = s.get("attrs", {})
        if s["name"] == "fleet.scale":
            # pool loops stamp their role; the whole-fleet loop renders as
            # before ("unified" doubles as its span attr default)
            role = a.get("role")
            tag = f"[{role}]" if role and role != "unified" else ""
            out.append(f"  t={s.get('start', 0.0):.1f} scale{tag} "
                       f"{a.get('direction')} "
                       f"{a.get('from')} -> {a.get('to')} "
                       f"[{a.get('target', '')}] — {a.get('reason', '')}")
        else:
            out.append(f"  t={s.get('start', 0.0):.1f} evict "
                       f"{a.get('replica_id')} — {a.get('reason', '')}")
    return out


def render(spans: list[dict], snapshots: list[dict], top: int = 20) -> str:
    lines = routing_table(spans)
    lines += load_table(snapshots)
    lines += scheduler_table(snapshots)
    lines += two_hop_table(spans, top)
    lines += handoff_rollup(spans)
    lines += directory_table(spans, snapshots)
    lines += pull_rollup(spans)
    lines += chunk_timeline(spans, top)
    lines += event_timeline(spans, top)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="per-replica load + routing-decision tables from "
                    "fleet JSONL")
    p.add_argument("path", help="JSONL file: router span export and/or "
                                "appended /debug/fleet snapshots")
    p.add_argument("--top", type=int, default=20,
                   help="scale/evict timeline length")
    args = p.parse_args(argv)
    spans, snapshots = load(args.path)
    if not spans and not snapshots:
        print(f"{args.path}: no fleet spans or registry snapshots found",
              file=sys.stderr)
        return 1
    print(render(spans, snapshots, args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
