"""Summarize a JAX/XLA profiler trace (xplane.pb) with no TF dependency.

The staged ``headline_profile`` bench step captures an XLA trace of the
timed steps so an MFU shortfall gets a profile, not a guess (r3 VERDICT
item 2). This image's tensorboard_plugin_profile cannot convert traces
(its pywrap symbol set mismatches the installed TF), so this tool parses
the protobuf WIRE FORMAT of tsl's XSpace directly — ~100 lines of varint
walking against the public schema (tsl/profiler/protobuf/xplane.proto):

  XSpace.planes=1 / XPlane{name=2, lines=3, event_metadata=4(map)}
  XLine{name=2, events=4} / XEvent{metadata_id=1, duration_ps=3}
  XEventMetadata{id=1, name=2, display_name=4}

Per plane it aggregates event durations by op name and prints the top-N
table (total ms, count, share of plane busy time) — the bottleneck view
round 5 reads next to the chip's MFU number.

Usage: python tools/xplane_summary.py <trace_dir_or_xplane.pb> [--top N]
"""

from __future__ import annotations

import glob
import os
import sys
from collections import defaultdict


def _varint(buf: memoryview, i: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: memoryview):
    """Yield (field_number, wire_type, value) over one message's bytes.
    value: int for varint/fixed, memoryview for length-delimited."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:                      # varint
            v, i = _varint(buf, i)
        elif wt == 1:                    # fixed64
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 2:                    # length-delimited
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:                    # fixed32
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt} at {i}")
        yield field, wt, v


def _parse_event(buf) -> tuple[int, int]:
    mid = dur = 0
    for f, _, v in _fields(buf):
        if f == 1:
            mid = v
        elif f == 3:
            dur = v
    return mid, dur


def _parse_line(buf) -> tuple[str, list]:
    name, events = "", []
    for f, wt, v in _fields(buf):
        if f == 2 and wt == 2:
            name = bytes(v).decode(errors="replace")
        elif f == 4 and wt == 2:
            events.append(_parse_event(v))
    return name, events


def _parse_meta_entry(buf) -> tuple[int, str]:
    """map<int64, XEventMetadata> entry -> (id, best name)."""
    key, name = 0, ""
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            key = v
        elif f == 2 and wt == 2:
            disp = nm = ""
            for f2, wt2, v2 in _fields(v):
                if f2 == 2 and wt2 == 2:
                    nm = bytes(v2).decode(errors="replace")
                elif f2 == 4 and wt2 == 2:
                    disp = bytes(v2).decode(errors="replace")
            name = disp or nm
    return key, name


def newest_xplane(trace_dir: str, since: float = 0.0):
    """Newest *.xplane.pb under ``trace_dir`` modified after ``since``
    (mtime epoch seconds), or None — the ONE definition of "this run's
    capture" shared by the CLI and bench.py (a stale pb from a previous
    round must never be attributed to the current run)."""
    pbs = [(os.path.getmtime(f), f) for f in glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)]
    pbs = [(m, f) for m, f in pbs if m >= since]
    return max(pbs)[1] if pbs else None


def summarize(path: str, top: int = 20) -> list[dict]:
    """Returns one record per plane: {plane, busy_ms, top: [(name, ms,
    count, share)]}. Pure parse — no TF, no protobuf package."""
    buf = memoryview(open(path, "rb").read())
    out = []
    for f, wt, plane_buf in _fields(buf):
        if f != 1 or wt != 2:
            continue
        plane_name, meta, agg = "", {}, defaultdict(lambda: [0, 0])
        for pf, pwt, pv in _fields(plane_buf):
            if pf == 2 and pwt == 2:
                plane_name = bytes(pv).decode(errors="replace")
            elif pf == 4 and pwt == 2:
                k, nm = _parse_meta_entry(pv)
                meta[k] = nm
            elif pf == 3 and pwt == 2:
                _, events = _parse_line(pv)
                for mid, dur in events:
                    agg[mid][0] += dur
                    agg[mid][1] += 1
        if not agg:
            continue
        busy_ps = sum(d for d, _ in agg.values())
        rows = sorted(((meta.get(mid, f"metadata#{mid}"), d, c)
                       for mid, (d, c) in agg.items()),
                      key=lambda r: -r[1])[:top]
        out.append({
            "plane": plane_name,
            "busy_ms": busy_ps / 1e9,
            "top": [(nm, d / 1e9, c, d / busy_ps) for nm, d, c in rows],
        })
    return out


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    top = 20
    if "--top" in argv:
        i = argv.index("--top")
        try:
            top = int(argv[i + 1])
        except (IndexError, ValueError):
            print("--top needs an integer", file=sys.stderr)
            return 2
        argv = argv[:i] + argv[i + 2:]
    if not argv:
        print(__doc__)
        return 2
    path = argv[0]
    if not os.path.exists(path):
        print(f"no such path: {path}", file=sys.stderr)
        return 1
    if os.path.isdir(path):
        pb = newest_xplane(path)
        if pb is None:
            print(f"no *.xplane.pb under {path}", file=sys.stderr)
            return 1
        path = pb
    print(f"# {path}")
    for plane in summarize(path, top):
        print(f"\n== plane: {plane['plane']}  "
              f"(busy {plane['busy_ms']:.2f} ms aggregated)")
        print(f"{'total_ms':>10}  {'count':>6}  {'share':>6}  op")
        for nm, ms, c, share in plane["top"]:
            print(f"{ms:10.3f}  {c:6d}  {share:5.1%}  {nm[:90]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
