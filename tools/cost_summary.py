"""Render the fleet cost headline: $/1M tokens, chip utilization, idle burn.

Input lines may be any mix of (ISSUE 20):
- **fleet cost rollups** — the router's ``GET /debug/costs`` payload (an
  object with a ``"groups"`` list and a ``"tenants"`` dict), e.g. appended
  periodically by ``curl router:8090/debug/costs >> costs.jsonl``,
- **replica cost snapshots** — a single replica's ``GET /debug/costs``
  (the CostMeter ledger: ``"totals"`` + ``"price_per_chip_hr"``),
- **training status** — the kubelet's ``GET /debug/train`` payload (a
  ``"pods"`` dict with per-pod chip-seconds/dollars), so training and
  serving spend render side by side from one file.

Later lines win (snapshots are cumulative); unknown ``schema_version``
values warn to stderr and render best-effort instead of crashing.

Usage:
  python tools/cost_summary.py costs.jsonl
  python tools/cost_summary.py costs.jsonl --top 10
"""

from __future__ import annotations

import argparse
import json
import sys

# /debug/costs + /debug/train schema versions this reader understands
KNOWN_SCHEMA_VERSIONS = {1}

_PHASES = ("queue", "prefill", "decode")


def _check_schema(obj: dict, path: str, lineno: int,
                  warned: set) -> None:
    ver = obj.get("schema_version")
    if ver is not None and ver not in KNOWN_SCHEMA_VERSIONS and \
            ver not in warned:
        warned.add(ver)
        print(f"warning: {path}:{lineno}: schema_version {ver!r} is newer "
              f"than this tool understands ({sorted(KNOWN_SCHEMA_VERSIONS)})"
              f"; rendering best-effort", file=sys.stderr)


def load(path: str) -> tuple[list[dict], list[dict], list[dict]]:
    """(fleet rollups, replica snapshots, training statuses) from a
    mixed JSONL file."""
    fleet, replicas, training = [], [], []
    warned: set = set()
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                print(f"warning: {path}:{lineno}: bad JSON, skipped",
                      file=sys.stderr)
                continue
            if not isinstance(obj, dict):
                continue
            _check_schema(obj, path, lineno, warned)
            if isinstance(obj.get("groups"), list):
                fleet.append(obj)
            elif isinstance(obj.get("totals"), dict) \
                    and "price_per_chip_hr" in obj:
                replicas.append(obj)
            elif isinstance(obj.get("pods"), dict) \
                    and "stall_timeout_s" in obj:
                training.append(obj)
    return fleet, replicas, training


def _fmt_dollars(v) -> str:
    return "-" if v is None else f"${v:,.4f}"


def _fmt_rate(v, suffix: str = "") -> str:
    return "-" if v is None else f"{v:,.2f}{suffix}"


def _replica_to_group(snap: dict) -> dict:
    """Shape a lone replica snapshot like one fleet rollup group so a
    file of replica-only appends still renders the headline table."""
    t = snap.get("totals") or {}
    paid = float(snap.get("paid_chip_seconds", 0.0) or 0.0)
    spent = sum(float((t.get("chip_seconds") or {}).get(p, 0.0) or 0.0)
                for p in _PHASES)
    tokens = int(t.get("tokens", 0) or 0)
    cost = float(t.get("cost_dollars", 0.0) or 0.0)
    return {"model": snap.get("model", ""), "pool": snap.get("pool", ""),
            "generation": snap.get("generation", ""), "replicas": 1,
            "requests": t.get("requests", 0), "tokens": tokens,
            "chip_seconds": t.get("chip_seconds") or {},
            "cost_dollars": cost,
            "paid_chip_seconds": paid,
            "idle_chip_seconds": snap.get("idle_chip_seconds", 0.0),
            "utilization": (spent / paid) if paid > 0 else None,
            "tokens_per_sec_per_chip": (tokens / paid) if paid > 0
            else None,
            "dollars_per_mtok": (cost / tokens * 1e6) if tokens else None}


def headline_table(groups: list[dict]) -> list[str]:
    if not groups:
        return []
    out = ["== cost headline (per model/pool) ==",
           f"{'model':<18} {'pool':<8} {'gen':<5} {'reps':>4} "
           f"{'requests':>9} {'tokens':>10} {'$/1Mtok':>10} "
           f"{'tok/s/chip':>10} {'util':>6} {'idle chip-s':>12} "
           f"{'spend':>11}"]
    for g in groups:
        util = g.get("utilization")
        out.append(
            f"{str(g.get('model', ''))[:18]:<18} "
            f"{str(g.get('pool', ''))[:8]:<8} "
            f"{str(g.get('generation', ''))[:5]:<5} "
            f"{g.get('replicas', 0):>4} "
            f"{g.get('requests', 0):>9} "
            f"{g.get('tokens', 0):>10} "
            f"{_fmt_dollars(g.get('dollars_per_mtok')):>10} "
            f"{_fmt_rate(g.get('tokens_per_sec_per_chip')):>10} "
            f"{'-' if util is None else f'{util * 100:.1f}%':>6} "
            f"{g.get('idle_chip_seconds', 0.0):>12,.1f} "
            f"{_fmt_dollars(g.get('cost_dollars')):>11}")
    return out


def tenant_table(tenants: dict, top: int) -> list[str]:
    if not tenants:
        return []
    ranked = sorted(tenants.items(),
                    key=lambda kv: -float(kv[1].get("cost_dollars", 0.0)
                                          or 0.0))[:top]
    out = ["", f"== spend by tenant (top {len(ranked)}; '-' = untagged, "
               f"'~other' = overflow) ==",
           f"{'tenant':<20} {'requests':>9} {'tokens':>10} "
           f"{'$/1Mtok':>10} {'spend':>11}"]
    for tenant, b in ranked:
        out.append(f"{str(tenant)[:20]:<20} {b.get('requests', 0):>9} "
                   f"{b.get('tokens', 0):>10} "
                   f"{_fmt_dollars(b.get('dollars_per_mtok')):>10} "
                   f"{_fmt_dollars(b.get('cost_dollars')):>11}")
    return out


def replica_table(replicas: dict) -> list[str]:
    if not replicas:
        return []
    out = ["", "== per-replica ledgers (live) ==",
           f"{'replica':<22} {'gen':<5} {'chips':>5} {'requests':>9} "
           f"{'tokens':>10} {'idle chip-s':>12} {'spend':>11}"]
    for rid in sorted(replicas):
        snap = replicas[rid] or {}
        t = snap.get("totals") or {}
        out.append(f"{str(rid)[:22]:<22} "
                   f"{str(snap.get('generation', ''))[:5]:<5} "
                   f"{snap.get('chips', 0):>5} "
                   f"{t.get('requests', 0):>9} {t.get('tokens', 0):>10} "
                   f"{snap.get('idle_chip_seconds', 0.0):>12,.1f} "
                   f"{_fmt_dollars(t.get('cost_dollars')):>11}")
    return out


def training_table(training: list[dict]) -> list[str]:
    if not training:
        return []
    pods = {}
    for status in training:  # later lines win per pod
        pods.update(status.get("pods") or {})
    priced = {k: p for k, p in pods.items()
              if isinstance(p, dict) and "chip_seconds" in p}
    if not priced:
        return []
    out = ["", "== training spend (/debug/train join) ==",
           f"{'pod':<28} {'gen':<5} {'chips':>5} {'step':>8} "
           f"{'chip-s':>12} {'spend':>11}"]
    total = 0.0
    for key in sorted(priced):
        p = priced[key]
        total += float(p.get("cost_dollars", 0.0) or 0.0)
        out.append(f"{str(key)[:28]:<28} "
                   f"{str(p.get('generation', ''))[:5]:<5} "
                   f"{p.get('chips', 0):>5} {p.get('last_step', 0):>8} "
                   f"{p.get('chip_seconds', 0.0):>12,.1f} "
                   f"{_fmt_dollars(p.get('cost_dollars')):>11}")
    out.append(f"{'total':<28} {'':<5} {'':>5} {'':>8} {'':>12} "
               f"{_fmt_dollars(total):>11}")
    return out


def render(fleet: list[dict], replicas: list[dict],
           training: list[dict], top: int = 10) -> str:
    groups: list[dict] = []
    tenants: dict = {}
    live_replicas: dict = {}
    if fleet:
        latest = fleet[-1]  # cumulative: later lines win
        groups = [g for g in latest.get("groups", []) if isinstance(g, dict)]
        tenants = latest.get("tenants") or {}
        live_replicas = latest.get("replicas") or {}
        skews = latest.get("schema_skews") or {}
        if skews:
            print(f"warning: replicas sent unmerged schema versions: "
                  f"{skews}", file=sys.stderr)
    elif replicas:
        # no fleet rollup in the file: the newest snapshot per
        # (model, pool) stands in for a group
        newest: dict[tuple, dict] = {}
        for snap in replicas:
            newest[(snap.get("model"), snap.get("pool"))] = snap
        groups = [_replica_to_group(s) for s in newest.values()]
        tenants = {}
        for snap in newest.values():
            for tenant, b in (snap.get("tenants") or {}).items():
                tenants.setdefault(tenant, b)
    lines = headline_table(groups)
    lines += tenant_table(tenants, top)
    lines += replica_table(live_replicas)
    lines += training_table(training)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Fleet cost headline ($/1M tokens, utilization, idle "
                    "burn) from mixed JSONL (/debug/costs and /debug/train "
                    "appends)")
    p.add_argument("path", help="JSONL file")
    p.add_argument("--top", type=int, default=10,
                   help="tenant rows to show (by spend)")
    args = p.parse_args(argv)
    fleet, replicas, training = load(args.path)
    if not fleet and not replicas and not training:
        print(f"{args.path}: no cost rollups, replica ledgers, or training "
              f"statuses found", file=sys.stderr)
        return 1
    print(render(fleet, replicas, training, args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
