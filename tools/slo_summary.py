"""Render SLO burn-rate timelines and per-step phase waterfalls (ISSUE 17).

Input lines may be any mix of:
- **spans** from a ``--trace-export`` JSONL (``fleet.slo_burn`` crossings,
  ``fleet.scale`` events for corroboration, ``serving.recompile`` from the
  engine's compile watchdog; other names are ignored),
- **SLO snapshots** — the router's ``GET /debug/slo`` payload (an object
  with a ``"signals"`` dict and a bounded ``"history"`` ring), e.g.
  appended periodically by ``curl router:8090/debug/slo >> slo.jsonl``,
- **step dumps** — the serving server's ``GET /debug/steps`` payload (an
  object with a ``"steps"`` record list, a ``"rollup"``, and the
  watchdog's ``"recompiles"`` table).

Output:
- the latest per-signal SLO status (objective, burning flag, short/long
  burn multiples, crossing count, window sample depths);
- a burn-rate timeline per signal rendered from the snapshot history —
  one character column per time bucket, height-coded by the short-window
  burn relative to the threshold (``#`` = at/over threshold);
- the crossing/scale timeline: every ``fleet.slo_burn`` onset interleaved
  with the autoscaler's ``fleet.scale`` events, so burn -> scale-up
  causality reads off one list;
- the per-step phase waterfall: the rollup's phase medians, then the last
  N step records as schedule/kernel/sample/commit bars (see "Reading a
  step waterfall" in the README);
- the recompile table: per-fn compile counts vs budget from the step
  dumps, plus each ``serving.recompile`` span's aval diff.

Usage:
  python tools/slo_summary.py slo.jsonl
  python tools/slo_summary.py spans.jsonl --steps 12 --width 72
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict

PHASES = ("schedule", "kernel", "sample", "commit")
# burn magnitude -> glyph, in fractions of the threshold; '#' means the
# short window alone is at/over the scale-up bar
_BURN_GLYPHS = ((1.0, "#"), (0.75, "="), (0.5, "-"), (0.25, "."), (0.0, " "))


KNOWN_SCHEMA_VERSIONS = {1}


def load(path: str) -> tuple[list[dict], list[dict], list[dict]]:
    """(spans, slo snapshots, step dumps) from a mixed JSONL file."""
    spans, slo_snaps, step_dumps = [], [], []
    warned: set = set()
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                print(f"warning: {path}:{lineno}: bad JSON, skipped",
                      file=sys.stderr)
                continue
            if not isinstance(obj, dict):
                continue
            ver = obj.get("schema_version")
            if ver is not None and ver not in KNOWN_SCHEMA_VERSIONS \
                    and ver not in warned:
                # newer producer than this reader: render best-effort
                warned.add(ver)
                print(f"warning: {path}:{lineno}: unknown schema_version "
                      f"{ver!r}; rendering best-effort", file=sys.stderr)
            if "name" in obj and "trace_id" in obj:
                spans.append(obj)
            elif isinstance(obj.get("signals"), dict):
                slo_snaps.append(obj)
            elif isinstance(obj.get("steps"), list) \
                    or isinstance(obj.get("recompiles"), dict):
                step_dumps.append(obj)
    return spans, slo_snaps, step_dumps


def _burn_glyph(burn: float, threshold: float) -> str:
    frac = burn / threshold if threshold > 0 else 0.0
    for floor, glyph in _BURN_GLYPHS:
        if frac >= floor and (floor > 0 or frac > 0):
            return glyph
    return " "


def status_table(slo_snaps: list[dict]) -> list[str]:
    if not slo_snaps:
        return []
    snap = slo_snaps[-1]  # later lines win: the file is appended in order
    w = snap.get("windows", {})
    out = [f"== SLO status (latest /debug/slo; threshold "
           f"{snap.get('burn_threshold', '?')}x of budget_frac="
           f"{snap.get('budget_frac', '?')}, windows "
           f"{w.get('short_s', '?')}s/{w.get('long_s', '?')}s) ==",
           f"{'signal':<12} {'objective':>10} {'burning':>8} "
           f"{'short':>8} {'long':>8} {'cross':>6} {'n_short':>8} "
           f"{'n_long':>7}"]
    for sig in sorted(snap["signals"]):
        s = snap["signals"][sig]
        out.append(f"{sig:<12} {s.get('objective', 0.0):>10.3f} "
                   f"{'BURNING' if s.get('burning') else 'ok':>8} "
                   f"{s.get('short_burn', 0.0):>7.2f}x "
                   f"{s.get('long_burn', 0.0):>7.2f}x "
                   f"{s.get('crossings', 0):>6} "
                   f"{s.get('samples_short', 0):>8} "
                   f"{s.get('samples_long', 0):>7}")
    return out


def burn_timeline(slo_snaps: list[dict], width: int) -> list[str]:
    """One char column per time bucket, short-window burn vs threshold.
    History entries from EVERY snapshot line merge (deduped on t), so a
    file of periodic /debug/slo appends renders one continuous timeline
    even though each snapshot only carries the bounded ring."""
    if not slo_snaps:
        return []
    threshold = float(slo_snaps[-1].get("burn_threshold", 2.0) or 2.0)
    merged: dict[float, dict] = {}
    for snap in slo_snaps:
        for entry in snap.get("history", []):
            t = entry.get("t")
            if t is not None and isinstance(entry.get("burn"), dict):
                merged[float(t)] = entry["burn"]
    if len(merged) < 2:
        return []
    times = sorted(merged)
    t0, t1 = times[0], times[-1]
    span = max(t1 - t0, 1e-9)
    # bucket by time, keep the max burn per bucket (a burst must not
    # average away just because the file over-samples quiet periods)
    buckets: dict[str, list[float]] = {}
    signals = sorted({sig for b in merged.values() for sig in b})
    for sig in signals:
        cols = [0.0] * width
        for t in times:
            burn = float(merged[t].get(sig, 0.0) or 0.0)
            i = min(width - 1, int((t - t0) / span * width))
            cols[i] = max(cols[i], burn)
        buckets[sig] = cols
    out = ["", f"== burn-rate timeline (short window, {len(merged)} "
               f"ingests over {span:.0f}s; '#' >= {threshold:.1f}x "
               f"threshold) =="]
    for sig in signals:
        line = "".join(_burn_glyph(b, threshold) for b in buckets[sig])
        peak = max(buckets[sig])
        out.append(f"{sig:<12} |{line}| peak {peak:.2f}x")
    out.append(f"{'':<12}  t={t0:.0f}{'':>{max(0, width - 18)}}t={t1:.0f}")
    return out


def crossing_timeline(spans: list[dict], top: int) -> list[str]:
    """fleet.slo_burn onsets interleaved with fleet.scale events: the
    burn -> scale-up causality chain, one line per event."""
    events = [s for s in spans
              if s.get("name") in ("fleet.slo_burn", "fleet.scale")]
    if not events:
        return []
    events.sort(key=lambda s: s.get("start", 0.0))
    out = ["", f"== SLO crossings + scale events (last {top}) =="]
    for s in events[-top:]:
        a = s.get("attrs", {})
        if s["name"] == "fleet.slo_burn":
            out.append(f"  t={s.get('start', 0.0):.1f} BURN "
                       f"{a.get('signal')} short={a.get('short_burn')}x "
                       f"long={a.get('long_burn')}x "
                       f"(threshold {a.get('threshold')}x, objective "
                       f"{a.get('objective')})")
        else:
            role = a.get("role")
            tag = f"[{role}]" if role and role != "unified" else ""
            out.append(f"  t={s.get('start', 0.0):.1f} scale{tag} "
                       f"{a.get('direction')} {a.get('from')} -> "
                       f"{a.get('to')} — {a.get('reason', '')}")
    return out


def _bar(frac: float, width: int) -> str:
    return "#" * max(0, round(frac * width))


def step_waterfall(step_dumps: list[dict], n: int,
                   width: int) -> list[str]:
    """Phase medians from the rollup, then the last n step records as
    stacked phase bars scaled to the slowest shown step."""
    if not step_dumps:
        return []
    dump = step_dumps[-1]  # later lines win
    out: list[str] = []
    roll = dump.get("rollup")
    if isinstance(roll, dict) and roll.get("steps"):
        med = "  ".join(f"{p} {roll.get(f'{p}_ms_p50', 0.0):.2f}ms"
                        for p in PHASES)
        out += ["", f"== step rollup ({roll['steps']} steps, "
                    f"{roll.get('tokens_total', 0)} tokens, "
                    f"{roll.get('spec_steps', 0)} spec-verify, "
                    f"ring {roll.get('bytes', 0)}/"
                    f"{roll.get('max_bytes', 0)}B, "
                    f"dropped {roll.get('dropped', 0)}) ==",
                f"  wall p50 {roll.get('wall_ms_p50', 0.0):.2f}ms — {med}"]
    steps = [r for r in dump.get("steps", []) if "wall_s" in r]
    if not steps:
        return out
    steps = steps[-n:]
    max_wall = max(r["wall_s"] for r in steps) or 1e-9
    bar_w = max(16, width - 34)
    out += ["", f"== step waterfall (last {len(steps)} steps; bars "
                f"scaled to {max_wall * 1e3:.2f}ms; "
                f"s=schedule k=kernel a=sample c=commit) =="]
    for r in steps:
        ph = r.get("phases", {})
        wall = r["wall_s"]
        cells = []
        for p, ch in zip(PHASES, "skac"):
            frac = ph.get(f"{p}_s", 0.0) / max_wall
            cells.append(ch * max(1 if ph.get(f"{p}_s", 0.0) > 0 else 0,
                                  round(frac * bar_w)))
        b = r.get("batch", {})
        tag = b.get("mode", "?")
        if b.get("spec_k"):
            tag += f" k={b['spec_k']}"
        if b.get("interleaved"):
            tag += " interleave"
        out.append(f"  seq={r.get('seq', '?'):<5} "
                   f"{wall * 1e3:>7.2f}ms |{''.join(cells):<{bar_w}}| "
                   f"n={b.get('active', 0)} {tag}")
    return out


def recompile_table(spans: list[dict],
                    step_dumps: list[dict]) -> list[str]:
    """Per-fn compile counts (watchdog snapshot riding /debug/steps) and
    each serving.recompile span's aval diff — the flap's smoking gun."""
    table = {}
    for dump in step_dumps:  # later lines win per fn
        rec = dump.get("recompiles")
        if isinstance(rec, dict):
            table.update(rec)
    recompiles = [s for s in spans if s.get("name") == "serving.recompile"]
    out: list[str] = []
    if table:
        out += ["", "== hot-path compiles (watchdog) ==",
                f"{'fn':<24} {'compiles':>9} {'recompiles':>11} "
                f"{'budget':>7} {'warned':>7}"]
        for fn in sorted(table):
            t = table[fn] or {}
            budget = t.get("budget")
            out.append(f"{fn:<24} {t.get('compiles', 0):>9} "
                       f"{t.get('recompiles', 0):>11} "
                       f"{'-' if budget is None else budget:>7} "
                       f"{'YES' if t.get('warned') else '-':>7}")
    if recompiles:
        by_fn: dict[str, list[dict]] = defaultdict(list)
        for s in recompiles:
            by_fn[str((s.get("attrs") or {}).get("fn") or "?")].append(s)
        out += ["", "== recompile spans (serving.recompile) =="]
        for fn in sorted(by_fn):
            last = by_fn[fn][-1].get("attrs", {})
            diff = last.get("aval_diff") or []
            if isinstance(diff, str):
                diff = [diff]
            out.append(f"  {fn}: {len(by_fn[fn])} recompile(s); last diff:")
            for line in diff[:8]:
                out.append(f"    {line}")
    return out


def render(spans: list[dict], slo_snaps: list[dict],
           step_dumps: list[dict], steps: int = 12, top: int = 20,
           width: int = 64) -> str:
    lines = status_table(slo_snaps)
    lines += burn_timeline(slo_snaps, width)
    lines += crossing_timeline(spans, top)
    lines += step_waterfall(step_dumps, steps, width)
    lines += recompile_table(spans, step_dumps)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="SLO burn-rate timelines + per-step phase waterfalls "
                    "from mixed JSONL (span export, /debug/slo and "
                    "/debug/steps appends)")
    p.add_argument("path", help="JSONL file")
    p.add_argument("--steps", type=int, default=12,
                   help="step-waterfall rows")
    p.add_argument("--top", type=int, default=20,
                   help="crossing/scale timeline length")
    p.add_argument("--width", type=int, default=64,
                   help="timeline/bar column width")
    args = p.parse_args(argv)
    spans, slo_snaps, step_dumps = load(args.path)
    if not spans and not slo_snaps and not step_dumps:
        print(f"{args.path}: no SLO snapshots, step dumps, or spans found",
              file=sys.stderr)
        return 1
    print(render(spans, slo_snaps, step_dumps, args.steps, args.top,
                 args.width))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
