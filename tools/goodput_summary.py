"""Render training.* spans into a goodput waterfall + per-host step table.

Input is the same JSONL span export the rest of the repo writes
(train_main ``--trace-export`` / TPU_TRACE_EXPORT_PATH, one JSON span per
line). The training telemetry layer (workloads/telemetry.py) emits:

  training.run        one per run()/attempt segment; attrs carry the full
                      goodput-ledger snapshot (buckets, goodput, mfu,
                      tokens_per_sec, attempt) + the watchdog's per-host
                      table on worker-0
  training.step       per optimizer step (step/host/tokens/loss attrs)
  training.checkpoint / training.restore   blocking save/restore intervals
  training.straggler  a host newly flagged stalled/slow (host/kind/lag)

This tool answers "where did the time go across restarts": a per-attempt
bucket waterfall (productive / compile / checkpoint / restart_lost /
stalled / idle), the restore/straggler timeline, and the per-host step-time
table from the newest training.run snapshot.

Usage:
  python tools/goodput_summary.py spans.jsonl
  python tools/goodput_summary.py spans.jsonl --steps   # + step-time rollup
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from trace_summary import load_spans, percentile  # noqa: E402

_BAR_WIDTH = 40
_BUCKET_ORDER = ("productive", "compile", "checkpoint_save",
                 "checkpoint_restore", "restart_lost", "resize", "stalled",
                 "idle")


def _fmt_s(v: float) -> str:
    return f"{v:10.3f}s"


def render_run_waterfall(runs: list[dict]) -> str:
    """Per-attempt goodput bars: one block per training.run span, buckets
    scaled against that attempt's wall clock."""
    out = ["goodput waterfall (one block per run segment):"]
    for i, span in enumerate(runs):
        attrs = span.get("attrs") or {}
        buckets = attrs.get("buckets") or {}
        wall = float(attrs.get("wall_s") or sum(buckets.values()) or 0.0)
        out.append(
            f"  run[{i}] attempt={attrs.get('attempt', 0)} "
            f"steps->{attrs.get('step', '?')} wall={wall:.3f}s "
            f"goodput={attrs.get('goodput', 0.0):.3f} "
            f"mfu={attrs.get('mfu', 0.0):.4f} "
            f"tokens/s={attrs.get('tokens_per_sec', 0.0):.1f}")
        for bucket in _BUCKET_ORDER:
            v = float(buckets.get(bucket, 0.0))
            if v <= 0:
                continue
            frac = v / wall if wall > 0 else 0.0
            bar = "#" * max(1, int(frac * _BAR_WIDTH))
            out.append(f"    {bucket:<20} |{bar:<{_BAR_WIDTH}}| "
                       f"{_fmt_s(v)} ({frac * 100:5.1f}%)")
    return "\n".join(out)


def render_host_table(runs: list[dict]) -> str:
    """Per-host step-time table from the NEWEST run snapshot's watchdog
    view (worker-0 aggregates peers' heartbeats)."""
    hosts = None
    for span in reversed(runs):
        hosts = (span.get("attrs") or {}).get("hosts")
        if hosts:
            break
    if not hosts:
        return "per-host table: (single-host run or no watchdog snapshot)"
    out = ["per-host step times (newest snapshot):",
           f"  {'host':>4}  {'step':>8}  {'mean_step_s':>12}  "
           f"{'age_s':>8}  flag"]
    for host in sorted(hosts, key=lambda h: int(h)):
        row = hosts[host]
        out.append(f"  {host:>4}  {row.get('step', -1):>8}  "
                   f"{row.get('mean_step_s', 0.0):>12.4f}  "
                   f"{row.get('age_s', 0.0):>8.1f}  "
                   f"{row.get('flagged', '') or '-'}")
    return "\n".join(out)


def render_events(spans: list[dict]) -> str:
    """Restore + straggler timeline, oldest first."""
    rows = []
    for s in spans:
        attrs = s.get("attrs") or {}
        if s["name"] == "training.restore":
            rows.append((s.get("start", 0.0),
                         f"restore   step={attrs.get('step', '?')} "
                         f"took={s.get('duration_s', 0.0):.3f}s"))
        elif s["name"] == "training.checkpoint":
            rows.append((s.get("start", 0.0),
                         f"checkpoint step={attrs.get('step', '?')} "
                         f"took={s.get('duration_s', 0.0):.3f}s"))
        elif s["name"] == "training.straggler":
            rows.append((s.get("start", 0.0),
                         f"straggler host={attrs.get('host', '?')} "
                         f"kind={attrs.get('kind', '?')} "
                         f"last_step={attrs.get('last_step', '?')} "
                         f"lag_s={attrs.get('lag_s', '?')}"))
        elif s["name"] in ("training.resize", "pod.gang_resize"):
            # kubelet-side spans carry no training step — print '?' rather
            # than falling back to a number that isn't one (the resize
            # count would read as "shrunk at step 2")
            width = attrs.get("new_width", attrs.get("width", "?"))
            rows.append((s.get("start", 0.0),
                         f"resize    kind={attrs.get('kind', '?')} "
                         f"dp_width->{width} "
                         f"step={attrs.get('step', '?')} "
                         f"took={s.get('duration_s', 0.0):.3f}s"))
    if not rows:
        return "events: (no checkpoint/restore/straggler/resize spans)"
    rows.sort(key=lambda r: r[0])
    t0 = rows[0][0]
    return "\n".join(["events:"] + [f"  +{t - t0:9.3f}s  {msg}"
                                    for t, msg in rows])


def render_resize_timeline(spans: list[dict]) -> str:
    """Elastic shrink/grow timeline (ISSUE 6): one row per resize with the
    DP width each segment ran at — from workload-side ``training.resize``
    spans and/or kubelet-side ``pod.gang_resize`` spans (the soak exports
    both; either alone renders)."""
    events = []
    for s in spans:
        if s["name"] not in ("training.resize", "pod.gang_resize"):
            continue
        attrs = s.get("attrs") or {}
        width = attrs.get("new_width", attrs.get("width"))
        old = attrs.get("old_width", attrs.get("full_width"))
        events.append((s.get("start", 0.0), attrs.get("kind", "?"),
                       old, width, attrs.get("lost_workers")))
    if not events:
        return ""
    events.sort(key=lambda e: e[0])
    t0 = events[0][0]
    initial = events[0][2]
    out = ["resize timeline (DP width per segment):",
           f"  +{0.0:9.3f}s  start            dp_width={initial}"]
    for t, kind, _old, width, lost in events:
        note = f"  lost_workers={lost}" if kind == "shrink" and lost else ""
        out.append(f"  +{t - t0:9.3f}s  {kind:<6} -> dp_width={width}{note}")
    return "\n".join(out)


def render_steps(spans: list[dict]) -> str:
    by_host: dict[int, list[float]] = {}
    for s in spans:
        if s["name"] != "training.step":
            continue
        host = int((s.get("attrs") or {}).get("host", 0))
        by_host.setdefault(host, []).append(s.get("duration_s", 0.0))
    if not by_host:
        return "step rollup: (no training.step spans)"
    out = ["step-time rollup (from training.step spans):"]
    for host in sorted(by_host):
        vals = sorted(by_host[host])
        out.append(f"  host {host}: n={len(vals)} "
                   f"p50={percentile(vals, 50):.4f}s "
                   f"p95={percentile(vals, 95):.4f}s "
                   f"p99={percentile(vals, 99):.4f}s")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="goodput waterfall + per-host step table from a JSONL "
                    "span export (train_main --trace-export)")
    p.add_argument("path", help="JSONL span file")
    p.add_argument("--steps", action="store_true",
                   help="also roll up per-host training.step durations")
    args = p.parse_args(argv)
    spans = load_spans(args.path)
    training = [s for s in spans if s["name"].startswith("training.")
                or s["name"] == "pod.gang_resize"]
    if not training:
        print(f"no training.* spans in {args.path}", file=sys.stderr)
        return 1
    runs = sorted((s for s in training if s["name"] == "training.run"),
                  key=lambda s: s.get("start", 0.0))
    total_lost = 0.0
    total_wall = 0.0
    for s in runs:
        attrs = s.get("attrs") or {}
        buckets = attrs.get("buckets") or {}
        total_wall += float(attrs.get("wall_s") or 0.0)
        total_lost += sum(float(v) for b, v in buckets.items()
                          if b != "productive")
    if runs:
        print(f"runs: {len(runs)}  total_wall={total_wall:.3f}s  "
              f"lost={total_lost:.3f}s  "
              f"overall_goodput="
              f"{(1 - total_lost / total_wall) if total_wall else 0:.3f}")
        print()
        print(render_run_waterfall(runs))
        print()
        print(render_host_table(runs))
        print()
    resize = render_resize_timeline(training)
    if resize:
        print(resize)
        print()
    print(render_events(training))
    if args.steps:
        print()
        print(render_steps(training))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
