"""Render a JSONL span export into per-request waterfalls + SLO rollups.

The serving engine (`--trace-export`) and the kubelet (`--trace-export` /
TPU_TRACE_EXPORT_PATH) append one JSON span per line:

  {"trace_id": ..., "span_id": ..., "parent_id": ..., "name": ...,
   "start": <wall seconds>, "duration_s": ..., "attrs": {...}}

This tool groups spans by trace, prints each trace as an indented waterfall
(offset + bar over the trace's own timeline), and rolls up the SLO currency
across all `serving.request` spans: p50/p95/p99 of TTFT (the request span's
``ttft_s`` attr) and of per-request mean inter-token latency (the
``serving.decode`` span's duration over its tokens-1 gaps).

Usage:
  python tools/trace_summary.py spans.jsonl                 # rollups + slowest traces
  python tools/trace_summary.py spans.jsonl --trace <id>    # one trace's waterfall
  python tools/trace_summary.py spans.jsonl --top 10        # how many traces to draw
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from collections import defaultdict

_BAR_WIDTH = 40


KNOWN_SCHEMA_VERSIONS = {1}


def load_spans(path: str) -> list[dict]:
    spans = []
    warned: set = set()
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                s = json.loads(line)
            except json.JSONDecodeError:
                print(f"warning: {path}:{lineno}: bad JSON, skipped",
                      file=sys.stderr)
                continue
            if isinstance(s, dict):
                ver = s.get("schema_version")
                if ver is not None and ver not in KNOWN_SCHEMA_VERSIONS \
                        and ver not in warned:
                    # newer producer than this reader: render best-effort
                    warned.add(ver)
                    print(f"warning: {path}:{lineno}: unknown "
                          f"schema_version {ver!r}; rendering best-effort",
                          file=sys.stderr)
            if isinstance(s, dict) and "trace_id" in s and "name" in s:
                spans.append(s)
    return spans


def percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over a pre-sorted list."""
    if not sorted_vals:
        return float("nan")
    rank = max(1, min(len(sorted_vals),
                      math.ceil(p / 100.0 * len(sorted_vals))))
    return sorted_vals[rank - 1]


def _tree_order(spans: list[dict]) -> list[tuple[int, dict]]:
    """(depth, span) rows: children under their parent, siblings by start.
    Spans whose parent is absent from the trace (e.g. the inbound caller's
    span, or a root exported after its children rotated out of the file)
    render as roots."""
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list[dict]] = defaultdict(list)
    roots = []
    for s in spans:
        parent = s.get("parent_id") or ""
        if parent and parent in by_id and parent != s["span_id"]:
            children[parent].append(s)
        else:
            roots.append(s)
    rows: list[tuple[int, dict]] = []
    seen: set[str] = set()

    def walk(span: dict, depth: int):
        if span["span_id"] in seen:  # defensive: malformed cyclic parents
            return
        seen.add(span["span_id"])
        rows.append((depth, span))
        for c in sorted(children.get(span["span_id"], []),
                        key=lambda s: s.get("start", 0.0)):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda s: s.get("start", 0.0)):
        walk(r, 0)
    return rows


def render_trace(trace_id: str, spans: list[dict]) -> str:
    t0 = min(s.get("start", 0.0) for s in spans)
    t1 = max(s.get("start", 0.0) + s.get("duration_s", 0.0) for s in spans)
    total = max(t1 - t0, 1e-9)
    out = [f"trace {trace_id}  ({total * 1000:.1f} ms, {len(spans)} spans)"]
    for depth, s in _tree_order(spans):
        start = s.get("start", 0.0) - t0
        dur = s.get("duration_s", 0.0)
        lo = int(start / total * _BAR_WIDTH)
        hi = max(lo + 1, int((start + dur) / total * _BAR_WIDTH))
        bar = " " * lo + "#" * (hi - lo)
        bar = bar[:_BAR_WIDTH].ljust(_BAR_WIDTH)
        label = "  " * depth + s["name"]
        attrs = s.get("attrs") or {}
        extra = " ".join(f"{k}={attrs[k]}"
                         for k in ("rid", "pod", "tokens", "step", "host",
                                   "seq")
                         if attrs.get(k) is not None)
        out.append(f"  {label:<32} |{bar}| {start * 1000:8.1f} ms "
                   f"+{dur * 1000:8.1f} ms  {extra}".rstrip())
    return "\n".join(out)


def rollups(spans: list[dict]) -> str:
    ttfts, itls, latencies = [], [], []
    steps, stragglers, runs = [], 0, []
    chunk_computes, chunk_pushes = [], []
    handoff_paths: dict[str, list[float]] = defaultdict(list)
    dir_outcomes: dict[str, int] = defaultdict(int)
    pull_paths: dict[str, list[float]] = defaultdict(list)
    for s in spans:
        attrs = s.get("attrs") or {}
        if s["name"] == "serving.request":
            if isinstance(attrs.get("ttft_s"), (int, float)):
                ttfts.append(float(attrs["ttft_s"]))
            if isinstance(attrs.get("latency_s"), (int, float)):
                latencies.append(float(attrs["latency_s"]))
        elif s["name"] == "serving.decode":
            tokens = attrs.get("tokens")
            if isinstance(tokens, int) and tokens > 1:
                itls.append(s.get("duration_s", 0.0) / (tokens - 1))
        # streamed chunked handoff (ISSUE 10): per-frame compute/push
        elif s["name"] == "serving.kv_chunk":
            chunk_computes.append(s.get("duration_s", 0.0))
        elif s["name"] == "serving.kv_push":
            chunk_pushes.append(s.get("duration_s", 0.0))
        # transfer-path families (ISSUE 11): fleet.handoff{path=device|wire}
        elif s["name"] == "fleet.handoff":
            handoff_paths[str(attrs.get("path") or "wire")].append(
                s.get("duration_s", 0.0))
        # KV fabric (ISSUE 16): directory lookups + per-rung pulls
        elif s["name"] == "fleet.directory_lookup":
            dir_outcomes[str(attrs.get("outcome") or "?")] += 1
        elif s["name"] == "serving.kv_pull" \
                and attrs.get("side") == "puller":
            rung = str(attrs.get("path")
                       or ("gone" if attrs.get("gone") else "failed"))
            pull_paths[rung].append(s.get("duration_s", 0.0))
        # training span families (ISSUE 5: one tool renders both layers;
        # tools/goodput_summary.py draws the full goodput waterfall)
        elif s["name"] == "training.step":
            steps.append(s.get("duration_s", 0.0))
        elif s["name"] == "training.straggler":
            stragglers += 1
        elif s["name"] == "training.run":
            runs.append(s)
    lines = [f"requests: {len(latencies)}"]
    for label, vals in (("ttft_s", ttfts), ("itl_s (per-request mean)", itls),
                        ("latency_s", latencies)):
        if not vals:
            lines.append(f"  {label:<28} (no samples)")
            continue
        vals = sorted(vals)
        lines.append(
            f"  {label:<28} p50={percentile(vals, 50):.4f}  "
            f"p95={percentile(vals, 95):.4f}  p99={percentile(vals, 99):.4f}  "
            f"n={len(vals)}")
    if chunk_computes or chunk_pushes:
        cc, cp = sorted(chunk_computes), sorted(chunk_pushes)
        lines.append(
            f"handoff chunks: {len(cc)} computed / {len(cp)} pushed  "
            f"compute p50={percentile(cc, 50):.4f}s  "
            f"push p50={percentile(cp, 50):.4f}s  "
            f"(per-stream timelines: tools/fleet_summary.py)")
    if handoff_paths:
        parts = []
        for path in sorted(handoff_paths):
            durs = sorted(handoff_paths[path])
            parts.append(f"{path}={len(durs)} "
                         f"(p50={percentile(durs, 50):.4f}s)")
        lines.append("fleet handoffs by path: " + "  ".join(parts)
                     + "  (per-domain rollup: tools/fleet_summary.py)")
    if dir_outcomes:
        lines.append("directory lookups: " + "  ".join(
            f"{oc}={dir_outcomes[oc]}" for oc in sorted(dir_outcomes)))
    if pull_paths:
        parts = []
        for rung in sorted(pull_paths):
            durs = sorted(pull_paths[rung])
            parts.append(f"{rung}={len(durs)} "
                         f"(p50={percentile(durs, 50):.4f}s)")
        lines.append("KV pulls by rung: " + "  ".join(parts)
                     + "  (per-rung rollup: tools/fleet_summary.py)")
    if steps or runs:
        lines.append(f"training steps: {len(steps)}"
                     + (f"  straggler events: {stragglers}" if stragglers
                        else ""))
        if steps:
            vals = sorted(steps)
            lines.append(
                f"  {'step_time_s':<28} p50={percentile(vals, 50):.4f}  "
                f"p95={percentile(vals, 95):.4f}  "
                f"p99={percentile(vals, 99):.4f}  n={len(vals)}")
        for r in runs:
            attrs = r.get("attrs") or {}
            lines.append(
                f"  run attempt={attrs.get('attempt', 0)}: "
                f"goodput={attrs.get('goodput', 0.0):.3f}  "
                f"mfu={attrs.get('mfu', 0.0):.4f}  "
                f"tokens/s={attrs.get('tokens_per_sec', 0.0):.1f}  "
                f"wall={attrs.get('wall_s', 0.0):.3f}s  "
                f"(waterfall: tools/goodput_summary.py)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="waterfall + TTFT/ITL rollups from a JSONL span export")
    p.add_argument("path", help="JSONL span file (--trace-export output)")
    p.add_argument("--trace", default="",
                   help="render only this trace_id's waterfall")
    p.add_argument("--top", type=int, default=5,
                   help="without --trace: draw the N slowest traces")
    args = p.parse_args(argv)
    spans = load_spans(args.path)
    if not spans:
        print(f"no spans in {args.path}", file=sys.stderr)
        return 1
    by_trace: dict[str, list[dict]] = defaultdict(list)
    for s in spans:
        by_trace[s["trace_id"]].append(s)
    if args.trace:
        if args.trace not in by_trace:
            print(f"trace {args.trace} not found "
                  f"({len(by_trace)} traces in file)", file=sys.stderr)
            return 1
        print(render_trace(args.trace, by_trace[args.trace]))
        return 0
    print(rollups(spans))
    print()

    def trace_span(tid: str) -> float:
        ss = by_trace[tid]
        return (max(s.get("start", 0.0) + s.get("duration_s", 0.0) for s in ss)
                - min(s.get("start", 0.0) for s in ss))

    slowest = sorted(by_trace, key=trace_span, reverse=True)[:args.top]
    for tid in slowest:
        print(render_trace(tid, by_trace[tid]))
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
