"""Offline v5e compile evidence (r3 VERDICT item 6).

With the TPU tunnel flapping across whole build sessions, this produces
machine-generated evidence that the perf-critical programs COMPILE for real
v5e hardware and what XLA's own cost model says about them — no chip needed:
JAX AOT compilation against a device-less `TopologyDescription`
(`jax.experimental.topologies`) runs the full XLA:TPU pipeline (including
Mosaic for Pallas kernels) and exposes `cost_analysis()` (flops / bytes
accessed) and `memory_analysis()` (argument/temp HBM) per compiled program.

Not a substitute for measurement: the cost model's `optimal_seconds` is
unreliable from a CPU client, so we derive roofline bounds ourselves from
public v5e specs (197 bf16 TFLOP/s, 819 GB/s HBM) and label them as bounds.

Programs covered (the round's headline benches):
  - 260M train step, remat dots vs none, batch 8/12 (the --mfu-sweep grid)
  - 530M train step (sweep point)
  - llama3-8b int8 decode + prefill steps (the --serve 8B geometry)
  - flash-attention fwd+bwd Pallas kernel at S=2048 (training geometry)
  - ring flash attention over a seq=4 mesh on a v5e:2x2 topology

  - Gemma-2 mixed-cache and Mistral ring-cache int8 decode (the exotic
    cache index math, previously interpret/CPU-verified only)

Writes one JSON record per program to bench_results/aot_v5e.json and prints
a summary line each. RESOURCE_EXHAUSTED records are memory-boundary
answers, not failures; only non-OOM compile failures exit nonzero.

Usage: python tools/aot_check.py
       python tools/aot_check.py --only train|serving|alt|flash|flash32k|\
ring|sharded|sharded_serving|ep_serving|mla
       (--only merges its subset over the existing evidence file)
"""

from __future__ import annotations

import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _HERE)

# v5e public spec-sheet numbers (same source as bench.py's _PEAK_TFLOPS)
_V5E_BF16_FLOPS = 197e12
_V5E_HBM_BYTES_S = 819e9
_V5E_HBM_BYTES = 16 * 1024**3


def _force_cpu():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass


def _topo(name: str, **kw):
    from jax.experimental import topologies
    return topologies.get_topology_desc(topology_name=name, platform="tpu",
                                        **kw)


def _sds_tree(tree, sharding):
    """ShapeDtypeStructs under ``sharding`` — EXCEPT leaves that already
    carry one (the sharded-serving cell pre-assigns per-leaf mesh
    shardings; the single-device cells pass bare shapes)."""
    import jax
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype,
            sharding=getattr(x, "sharding", None) or sharding),
        tree)


def _analyze(compiled, *, tokens_per_step=None, model_flops_per_tok=None):
    """Cost + memory analysis -> derived v5e roofline bounds."""
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):   # some jax versions wrap the dict
        ca = ca[0] if ca else {}
    ma = compiled.memory_analysis()
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    t_compute = flops / _V5E_BF16_FLOPS
    t_hbm = byts / _V5E_HBM_BYTES_S
    bound = "compute" if t_compute >= t_hbm else "hbm"
    rec = {
        "xla_flops": flops,
        "xla_bytes_accessed": byts,
        "arithmetic_intensity": round(flops / byts, 2) if byts else None,
        "roofline_s_compute": round(t_compute, 6),
        "roofline_s_hbm": round(t_hbm, 6),
        "roofline_bound": bound,
        "hbm_argument_bytes": ma.argument_size_in_bytes,
        "hbm_temp_bytes": ma.temp_size_in_bytes,
        "hbm_alias_bytes": ma.alias_size_in_bytes,
        "hbm_peak_est_bytes": (ma.argument_size_in_bytes
                               + ma.temp_size_in_bytes
                               + ma.output_size_in_bytes
                               - ma.alias_size_in_bytes),
        "fits_16gb": (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                      + ma.output_size_in_bytes - ma.alias_size_in_bytes)
        < _V5E_HBM_BYTES,
    }
    if tokens_per_step:
        t_bound = max(t_compute, t_hbm)
        rec["tokens_per_step"] = tokens_per_step
        rec["roofline_tok_s_bound"] = round(tokens_per_step / t_bound, 1)
        if model_flops_per_tok:
            # MFU ceiling IF the program ran exactly at the XLA cost-model
            # roofline (real kernels won't; this bounds the sweep, it does
            # not predict it)
            rec["roofline_mfu_bound"] = round(
                model_flops_per_tok * rec["roofline_tok_s_bound"]
                / _V5E_BF16_FLOPS, 3)
    return rec


def _train_step_program(cfg, batch: int, dev, fused_ce_chunks: int = 0):
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    from k8s_runpod_kubelet_tpu.models import LlamaModel, init_params
    from k8s_runpod_kubelet_tpu.workloads.train import (TrainConfig,
                                                        make_optimizer,
                                                        make_train_step)
    tc = TrainConfig(batch_size=batch, seq_len=2048, steps=1)
    model = LlamaModel(cfg)
    opt = make_optimizer(tc)
    params_abs = jax.eval_shape(lambda k: init_params(cfg, k),
                                jax.random.PRNGKey(0))
    opt_abs = jax.eval_shape(opt.init, params_abs)
    s = SingleDeviceSharding(dev)
    step = make_train_step(model, opt, fused_ce_chunks=fused_ce_chunks)
    batch_abs = jax.ShapeDtypeStruct((batch, tc.seq_len + 1), jnp.int32,
                                     sharding=s)
    return step.lower(_sds_tree(params_abs, s), _sds_tree(opt_abs, s),
                      batch_abs)


def check_train(results, dev):
    import dataclasses
    # the SAME configs the sweep runs — defined once in __graft_entry__ so
    # this prevalidation can never drift from the grid it validates
    from __graft_entry__ import _bench_config, _bench_config_530m
    wider_530m = _bench_config_530m

    base = _bench_config(tiny=False)
    # First AOT pass falsified the staged sweep grid: remat "none" OOMs at
    # B=8 (24GB) and 530m "dots" OOMs at B=8 (18.9GB) — XLA's buffer
    # assignment for the v5e target, so they would OOM on the chip too.
    # This grid probes what DOES fit: "full" remat (recompute everything,
    # lowest activation memory) buys batch, "dots" at the edge.
    grid = [
        ("train_260m_dots_b8", base, 8),
        ("train_260m_none_b8",
         dataclasses.replace(base, remat_policy="none"), 8),
        ("train_260m_none_b12",
         dataclasses.replace(base, remat_policy="none"), 12),
        ("train_260m_dots_b12", base, 12),
        ("train_260m_full_b16",
         dataclasses.replace(base, remat_policy="full"), 16),
        ("train_260m_full_b32",
         dataclasses.replace(base, remat_policy="full"), 32),
        ("train_530m_dots_b8", wider_530m(), 8),
        ("train_530m_none_b8",
         dataclasses.replace(wider_530m(), remat_policy="none"), 8),
        ("train_530m_full_b8",
         dataclasses.replace(wider_530m(), remat_policy="full"), 8),
        ("train_530m_full_b16",
         dataclasses.replace(wider_530m(), remat_policy="full"), 16),
    ]
    grid = [(name, cfg, b, 0) for name, cfg, b in grid]
    # Fused-CE cells (ops/fused_ce.py): the (B, S, V) logits tensor never
    # materializes — ~1GB bf16 + ~2.1GB f32 at the 260m geometry — so the
    # same remat policy should fit meaningfully more batch. 8 chunks =
    # 4096-wide vocab slices (MXU-friendly N x 1024 x 4096 matmuls).
    grid += [
        ("train_260m_fce8_dots_b8", base, 8, 8),
        ("train_260m_fce8_dots_b12", base, 12, 8),
        ("train_260m_fce8_dots_b16", base, 16, 8),
        ("train_260m_fce8_full_b24",
         dataclasses.replace(base, remat_policy="full"), 24, 8),
        ("train_260m_fce8_full_b32",
         dataclasses.replace(base, remat_policy="full"), 32, 8),
        ("train_530m_fce8_full_b16",
         dataclasses.replace(wider_530m(), remat_policy="full"), 16, 8),
        # b16 refused at 16.18G — probe the b12 point between known-fit
        # 530m_full_b8 and that refusal
        ("train_530m_fce8_full_b12",
         dataclasses.replace(wider_530m(), remat_policy="full"), 12, 8),
    ]
    # The 128k-vocab pair: the geometry fused CE exists for. Same body as
    # the 260m bench but Llama-3's real vocabulary — the naive loss's
    # logits are 4.2 GB bf16 at B=8; expectation is naive refuses / fused
    # fits, which is the memory-enabler claim stated as a compile boundary.
    from __graft_entry__ import _bench_config_v128k
    v128k = _bench_config_v128k()
    grid += [
        ("train_v128k_naive_b8", v128k, 8, 0),
        ("train_v128k_fce16_b8", v128k, 8, 16),
        ("train_v128k_fce16_b12", v128k, 12, 16),
    ]
    for name, cfg, b, chunks in grid:
        results[name] = _run(name, lambda cfg=cfg, b=b, chunks=chunks:
                             _analyze(
            _train_step_program(cfg, b, dev, fused_ce_chunks=chunks)
            .compile(),
            tokens_per_step=b * 2048,
            model_flops_per_tok=6.0 * cfg.param_count))


def _quantized_params_abs(cfg, bits: int = 8):
    """Abstract int8/int4 param tree for a model config. quantize_params is
    host-side numpy (not traceable), so run it over a zeros host tree
    (copy-on-write pages, same trick as bench _serve_params) and keep only
    the SHAPES."""
    import jax
    import numpy as np
    from k8s_runpod_kubelet_tpu.models import init_params
    from k8s_runpod_kubelet_tpu.models.quant import quantize_params

    params_abs = jax.eval_shape(lambda k: init_params(cfg, k),
                                jax.random.PRNGKey(0))
    host = jax.tree_util.tree_map(
        lambda sd: np.zeros(sd.shape, sd.dtype), params_abs)
    q_real = quantize_params(cfg, host, bits=bits)
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), q_real)


def _lower_decode(model, q_abs, cache_abs, n_slots, s, note, k=1):
    """ONE lower/compile recipe for every int8 decode cell (8B econ A/B,
    slot sweep, exotic-cache models, speculative verify) — changes here
    retune all of them. ``k`` > 1 lowers verify_step with (slots, k)
    candidate tokens (decode_step IS verify at K=1, same kernel);
    tokens_per_step then assumes full acceptance (upper bound)."""
    import jax
    import jax.numpy as jnp

    if k == 1:
        def step(params, token, cache, active):
            return model.decode_step(params, token, cache, active)
        tok_sds = jax.ShapeDtypeStruct((n_slots,), jnp.int32, sharding=s)
    else:
        def step(params, toks, cache, active):
            return model.verify_step(params, toks, cache, active)
        tok_sds = jax.ShapeDtypeStruct((n_slots, k), jnp.int32, sharding=s)

    lowered = jax.jit(step, donate_argnums=(2,)).lower(
        _sds_tree(q_abs, s), tok_sds, _sds_tree(cache_abs, s),
        jax.ShapeDtypeStruct((n_slots,), bool, sharding=s))
    rec = _analyze(lowered.compile(), tokens_per_step=n_slots * k)
    rec["note"] = note
    return rec


_SERVING_8B_KEYS = ("decode_8b_int8_kv8", "decode_8b_int8_kvbf16",
                    "decode_8b_int8_kv8_slots16",
                    "decode_8b_int8_kv8_slots32",
                    "decode_8b_int8_kv8_slots48", "prefill_8b_int8",
                    "verify_8b_int8_kv8_k4",
                    "econ_kv_int8_traffic_ratio",
                    "decode_8b_int4_kv8_slots16",
                    "decode_8b_int4_kv8_slots32",
                    "decode_8b_int4_kv8_slots64",
                    "decode_8b_int4pk_kv8_slots16",
                    "decode_8b_int4pk_kv8_slots32",
                    "decode_8b_int4pk_kv8_slots64")


def check_serving_8b(results, dev):
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    from k8s_runpod_kubelet_tpu.models import LlamaModel, llama3_8b

    cfg = llama3_8b()
    model = LlamaModel(cfg)
    slots, cache_len, prefill_len = 8, 2048, 512  # run_serve_bench 8B geometry
    s = SingleDeviceSharding(dev)
    try:
        q_abs = _quantized_params_abs(cfg)
    except Exception as e:  # noqa: BLE001 — record EVERY serving key
        # (econ ratio included) as failed: a partial failure record would
        # let the --only merge carry stale entries under a fresh timestamp
        err = {"compile_ok": False, "compile_wall_s": 0.0,
               "error": f"setup: {type(e).__name__}: {e}"[:500]}
        for key in _SERVING_8B_KEYS:
            results[key] = dict(err)
        print(f"[aot] serving_8b setup FAILED: {err['error'][:120]}",
              flush=True)
        return

    def prog_decode_variant(n_slots, kv_int8, note):
        # decode is weight-amortization-bound — every step reads the whole
        # int8 weight tree once regardless of batch, so tok/s scales with
        # slots until KV traffic or HBM capacity pushes back; int8 KV
        # buys the headroom
        cache_n = jax.eval_shape(
            lambda: model.init_cache(n_slots, cache_len, quantize=kv_int8))
        return _lower_decode(model, q_abs, cache_n, n_slots, s, note)

    def prog_prefill():
        prefill_cache_abs = jax.eval_shape(
            lambda: model.init_cache(1, cache_len, quantize=True))
        lowered = jax.jit(model.prefill).lower(
            _sds_tree(q_abs, s),
            jax.ShapeDtypeStruct((1, prefill_len), jnp.int32, sharding=s),
            _sds_tree(prefill_cache_abs, s))
        return _analyze(lowered.compile(), tokens_per_step=prefill_len)

    def prog_verify_k4():
        # speculative decoding's roofline case FOR the --econ speculate
        # cell: one verify pass commits up to K=4 tokens while reading the
        # weight tree ONCE — on a weight-amortization-bound decode that is
        # the whole win, and this program's roofline vs decode_8b_int8_kv8
        # bounds it (realized gain scales with the acceptance rate)
        cache_n = jax.eval_shape(
            lambda: model.init_cache(slots, cache_len, quantize=True))
        return _lower_decode(
            model, q_abs, cache_n, slots, s,
            f"speculative verify, K=4, {slots} slots, int8 weights + int8 "
            f"KV; tokens_per_step assumes 100% acceptance (upper bound)",
            k=4)

    results["decode_8b_int8_kv8"] = _run(
        "decode_8b_int8_kv8", lambda: prog_decode_variant(
            slots, True, f"int8 weights + int8 KV, {slots} slots, "
                         f"cache_len {cache_len}"))
    results["decode_8b_int8_kvbf16"] = _run(
        "decode_8b_int8_kvbf16", lambda: prog_decode_variant(
            slots, False,
            "int8 weights + BF16 KV (the --econ kv_int8-off cell)"))
    for n_slots in (16, 32, 48):
        results[f"decode_8b_int8_kv8_slots{n_slots}"] = _run(
            f"decode_8b_int8_kv8_slots{n_slots}",
            lambda n=n_slots: prog_decode_variant(
                n, True, f"{n} slots, int8 weights + int8 KV"))
    results["prefill_8b_int8"] = _run("prefill_8b_int8", prog_prefill)
    results["verify_8b_int8_kv8_k4"] = _run("verify_8b_int8_kv8_k4",
                                            prog_verify_k4)

    # int4 weights (models/quant.py bits=4): weight bytes drop 2x vs int8
    # (8GB -> ~4.3GB incl. group scales on 8B). Decode at low concurrency is
    # weight-amortization-bound, so the roofline should rise and the freed
    # HBM should admit more slots — the boundary answers recorded here.
    q4_abs = _quantized_params_abs(cfg, bits=4)  # hoisted: shared by 6 cells

    def prog_decode_int4(n_slots, pallas_kernel):
        import os
        cache_n = jax.eval_shape(
            lambda: model.init_cache(n_slots, cache_len, quantize=True))
        key = "TPU_KUBELET_FORCE_PALLAS"
        prev = os.environ.get(key)
        # AOT runs on a CPU host, so backend autodetection would pick the
        # XLA fallback; force the Mosaic kernel path for the *pk cells
        # (only the literal "1" means force — absent = autodetect)
        if pallas_kernel:
            os.environ[key] = "1"
        else:
            os.environ.pop(key, None)
        try:
            note = (f"{n_slots} slots, int4 weights + int8 KV"
                    + (", Pallas unpack kernel" if pallas_kernel
                       else ", XLA fallback path"))
            return _lower_decode(model, q4_abs, cache_n, n_slots, s, note)
        finally:
            if prev is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prev
    for n_slots in (16, 32, 64):
        results[f"decode_8b_int4_kv8_slots{n_slots}"] = _run(
            f"decode_8b_int4_kv8_slots{n_slots}",
            lambda n=n_slots: prog_decode_int4(n, False))
    # Pallas kernel path (ops/int4_matmul.py): the XLA cost model cannot
    # see inside Mosaic custom calls, so its byte counts understate these
    # cells — the claims here are (a) the kernel Mosaic-compiles for v5e at
    # the 8B geometry and (b) the HBM boundary (which slot counts fit);
    # throughput comes from the chip (bench --serve --int4).
    for n_slots in (16, 32, 64):
        results[f"decode_8b_int4pk_kv8_slots{n_slots}"] = _run(
            f"decode_8b_int4pk_kv8_slots{n_slots}",
            lambda n=n_slots: prog_decode_int4(n, True))
    a = results.get("decode_8b_int8_kv8", {})
    b = results.get("decode_8b_int8_kvbf16", {})
    if a.get("compile_ok") and b.get("compile_ok"):
        results["econ_kv_int8_traffic_ratio"] = {
            "compile_ok": True, "compile_wall_s": 0.0,
            "bytes_int8_kv": a["xla_bytes_accessed"],
            "bytes_bf16_kv": b["xla_bytes_accessed"],
            "ratio": round(a["xla_bytes_accessed"]
                           / b["xla_bytes_accessed"], 3),
            "roofline_tok_s_int8": a.get("roofline_tok_s_bound"),
            "roofline_tok_s_bf16": b.get("roofline_tok_s_bound"),
        }
        print(f"[aot] econ: int8-KV decode moves "
              f"{results['econ_kv_int8_traffic_ratio']['ratio']:.0%} of the "
              f"bf16-KV bytes", flush=True)
    else:
        # the ratio's INPUT cells failed: the econ record must fail WITH
        # them, or a --only merge would carry the stale ratio forward
        results["econ_kv_int8_traffic_ratio"] = {
            "compile_ok": False, "compile_wall_s": 0.0,
            "error": "input decode cells did not both compile"}


def check_serving_alt(results, dev):
    """The EXOTIC cache paths compiled for the real target: Gemma-2's
    mixed (local-ring/global-full) cache and Mistral's uniform ring cache,
    both with int8 weights + int8 KV — these decode programs have the most
    bespoke index math in the serving stack, exactly where an
    interpret-mode-only check could hide a v5e lowering failure."""
    import jax
    from jax.sharding import SingleDeviceSharding

    s = SingleDeviceSharding(dev)

    def decode_prog(model_name, make_cache, slots, note):
        # EVERYTHING (model import + config construction included) inside
        # the prog so a models/ API drift is recorded by _run, not fatal
        # to the tool
        from k8s_runpod_kubelet_tpu import models as M
        cfg = getattr(M, model_name)()
        model = M.LlamaModel(cfg)
        q_abs = _quantized_params_abs(cfg)
        cache_abs = jax.eval_shape(lambda: make_cache(model, cfg))
        return _lower_decode(model, q_abs, cache_abs, slots, s, note)

    # gemma2: 2 slots / 6k context — gemma2-9b is HBM-tight on one v5e
    # (9.2GB int8 weights + a 1.9GB bf16 embedding); 4 slots at 8k OOM'd
    # at 19.6G (recorded in git history); this is the fitting point
    results["decode_gemma2_9b_mixed_int8kv"] = _run(
        "decode_gemma2_9b_mixed_int8kv",
        lambda: decode_prog(
            "gemma2_9b",
            lambda m, c: m.init_mixed_cache(
                2, 6144, (c.sliding_window or 4096) + 512, quantize=True),
            2, "mixed cache: local sublayers ring at window+slack, global "
               "full 6k; 2 slots, int8 weights + int8 KV"))
    # MLA at the 8B weight class on ONE chip (the serve_mla_8b staged
    # step's geometry — models.mla_8b, the SAME definition bench.py
    # serves): int8 weights + int8 LATENT cache — memory-fit
    # compile-proven so the watcher step can't OOM-surprise
    results["decode_mla8b_int8_kv8"] = _run(
        "decode_mla8b_int8_kv8",
        lambda: decode_prog(
            "mla_8b",
            lambda m, c: m.init_cache(8, 2048, quantize=True),
            8, "MLA absorbed decode, 8B weight class, int8 weights + "
               "int8 latent cache, 8 slots"))
    results["decode_mistral_7b_ring_int8kv"] = _run(
        "decode_mistral_7b_ring_int8kv",
        lambda: decode_prog(
            "mistral_7b",
            lambda m, c: m.init_ring_cache(
                8, (c.sliding_window or 4096) + 512, quantize=True),
            8, "uniform ring cache (abs_pos ownership map), 8 slots, int8 "
               "weights + int8 KV"))


def check_flash_attention(results, dev):
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding
    from k8s_runpod_kubelet_tpu.ops.attention import flash_attention

    s = SingleDeviceSharding(dev)
    b, hq, hkv, d, sl = 8, 16, 8, 64, 2048  # the TRAINING geometry

    def fwd_bwd(q, k, v):
        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True, use_pallas=True))
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    def prog():
        args = [jax.ShapeDtypeStruct((b, h, sl, d), jnp.bfloat16, sharding=s)
                for h in (hq, hkv, hkv)]
        lowered = jax.jit(fwd_bwd).lower(*args)
        rec = _analyze(lowered.compile())
        rec["note"] = "Pallas kernels compiled by Mosaic for v5e (AOT)"
        return rec

    results["flash_attn_s2048_fwd_bwd"] = _run("flash_attn_s2048_fwd_bwd",
                                               prog)


def check_ring_flash(results):
    import importlib

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    ra = importlib.import_module("k8s_runpod_kubelet_tpu.ops.ring_attention")

    def setup():
        # built per-prog so a topology/jaxlib failure is RECORDED by _run
        # (compile_ok=false) instead of aborting the whole evidence tool
        topo = _topo("v5e:2x2")
        devs = np.array(topo.devices).reshape(1, 4)
        mesh = Mesh(devs, ("data", "seq"))
        b, hq, hkv, d, sl = 1, 8, 4, 128, 4096  # S_local=1024, blockable
        spec = NamedSharding(mesh, P(None, None, "seq", None))
        args = [jax.ShapeDtypeStruct((b, h, sl, d), jnp.bfloat16,
                                     sharding=spec)
                for h in (hq, hkv, hkv)]
        return mesh, args

    def prog_fwd():
        mesh, args = setup()

        def f(q, k, v):
            return ra.ring_attention(q, k, v, mesh, causal=True,
                                     use_flash=True)

        rec = _analyze(jax.jit(f).lower(*args).compile())
        rec["note"] = ("ring flash fwd over seq=4 mesh on v5e:2x2 — Pallas "
                       "chunk kernels + ppermute collectives AOT-compiled")
        return rec

    def prog_bwd():
        # the custom VJP: backward ring re-feeding the kernels the global
        # (o, lse) with rotating dk/dv accumulators — the hardest program
        # in ops/, compile-checked for the real target
        mesh, args = setup()

        def loss(q, k, v):
            o = ra.ring_attention(q, k, v, mesh, causal=True, use_flash=True)
            return jnp.sum(o.astype(jnp.float32))

        rec = _analyze(
            jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(*args).compile())
        rec["note"] = "ring flash custom-VJP backward, same mesh/geometry"
        return rec

    results["ring_flash_sp4_fwd"] = _run("ring_flash_sp4_fwd", prog_fwd)
    results["ring_flash_sp4_bwd"] = _run("ring_flash_sp4_bwd", prog_bwd)


def check_flash_32k(results, dev):
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding
    from k8s_runpod_kubelet_tpu.ops.attention import flash_attention

    s = SingleDeviceSharding(dev)
    b, hq, hkv, d, sl = 1, 32, 8, 128, 32768  # r2's unverified 32k point

    def prog():
        def f(q, k, v):
            def loss(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, use_pallas=True)
                    .astype(jnp.float32))
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        args = [jax.ShapeDtypeStruct((b, h, sl, d), jnp.bfloat16, sharding=s)
                for h in (hq, hkv, hkv)]
        rec = _analyze(jax.jit(f).lower(*args).compile())
        rec["note"] = ("S=32768 fwd+bwd (llama3-8b heads) — the r2 point "
                       "the tunnel died under; streamed K/V must fit VMEM "
                       "and the whole program must fit HBM")
        return rec

    results["flash_attn_s32k_fwd_bwd"] = _run("flash_attn_s32k_fwd_bwd",
                                              prog)


def check_sharded_train(results):
    """The driver dryrun validates multi-chip sharding on VIRTUAL CPU
    devices; this compiles the same fsdp x tp x seq train step for the
    REAL v5e target over a 2x4 topology — SPMD partitioner, collectives,
    and per-chip memory all machine-checked for the hardware."""
    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def prog():
        import jax.numpy as jnp
        from __graft_entry__ import _bench_config
        from k8s_runpod_kubelet_tpu.models import (LlamaModel, init_params,
                                                   param_logical_axes)
        from k8s_runpod_kubelet_tpu.parallel import (MeshConfig, make_mesh,
                                                     param_shardings)
        from k8s_runpod_kubelet_tpu.workloads.train import (TrainConfig,
                                                            make_optimizer,
                                                            make_train_step)
        topo = _topo("v5e:2x4")
        mesh = make_mesh(MeshConfig(data=-1, fsdp=2, seq=2, tensor=2),
                         list(topo.devices))
        cfg = _bench_config(tiny=False)
        b = 8
        tc = TrainConfig(batch_size=b, seq_len=2048, steps=1)
        model = LlamaModel(cfg, mesh)
        opt = make_optimizer(tc)
        params_abs = jax.eval_shape(lambda k: init_params(cfg, k),
                                    jax.random.PRNGKey(0))
        shardings = param_shardings(mesh, param_logical_axes(cfg))
        params_sds = jax.tree_util.tree_map(
            lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            params_abs, shardings)
        opt_abs = jax.eval_shape(opt.init, params_abs)
        # optax's adam moments mirror the params tree (Trainer relies on
        # exactly this: "optax state mirrors the already-sharded params");
        # map each moment leaf to its param leaf's sharding by shape+dtype
        # (stacked-layer leaves are unique per (shape, dtype)), scalars
        # (count etc.) replicate
        by_shape = {}
        for p, sh in zip(jax.tree_util.tree_leaves(params_abs),
                         jax.tree_util.tree_leaves(shardings)):
            by_shape[(p.shape, str(p.dtype))] = sh
        repl = NamedSharding(mesh, P())

        def opt_shard(x):
            sh = by_shape.get((x.shape, str(x.dtype)), repl)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

        opt_sds = jax.tree_util.tree_map(opt_shard, opt_abs)
        step = make_train_step(model, opt)
        batch_sds = jax.ShapeDtypeStruct(
            (b, tc.seq_len + 1), jnp.int32,
            sharding=NamedSharding(mesh, P(("data", "fsdp"), None)))
        rec = _analyze(step.lower(params_sds, opt_sds, batch_sds).compile(),
                       tokens_per_step=b * tc.seq_len)
        rec["note"] = ("260M train step, fsdp=2 x sp=2 x tp=2 over v5e:2x4 "
                       "— the dryrun mesh compiled for the REAL target")
        return rec

    results["train_260m_sharded_2x4"] = _run("train_260m_sharded_2x4", prog)


def _quantized_abs_shapes(cfg, bits: int = 8):
    """ShapeDtypeStruct tree of an int8/int4-quantized param tree, computed
    from shapes alone — the numpy path (_quantized_params_abs) would
    materialize per-leaf f32 temporaries (a stacked llama3-70b w_gate is
    ~75GB), which only SHAPES of are ever wanted here."""
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.models import init_params
    from k8s_runpod_kubelet_tpu.models.quant import (_EXPERT_WEIGHTS,
                                                     _LAYER_WEIGHTS,
                                                     INT4_GROUP)

    params_abs = jax.eval_shape(lambda k: init_params(cfg, k),
                                jax.random.PRNGKey(0))
    quantized = set(_LAYER_WEIGHTS) | set(_EXPERT_WEIGHTS)

    def q(sd):
        if bits == 4:   # packed: (in/2, out) u8 + (g, 1, out) f32 scales
            kin, out = sd.shape[-2], sd.shape[-1]
            gs = INT4_GROUP if kin % INT4_GROUP == 0 else kin
            return {"q4": jax.ShapeDtypeStruct(
                        sd.shape[:-2] + (kin // 2, out), jnp.uint8),
                    "scale": jax.ShapeDtypeStruct(
                        sd.shape[:-2] + (kin // gs, 1, out), jnp.float32)}
        return {"q8": jax.ShapeDtypeStruct(sd.shape, jnp.int8),
                "scale": jax.ShapeDtypeStruct(
                    sd.shape[:-2] + (1, sd.shape[-1]), jnp.float32)}

    def passthrough(name, sd):
        if name in ("w_uk", "w_uv"):
            # quantize_params stores the MLA up-projections in the COMPUTE
            # dtype (quant.py) — the evidence cell must compile the same
            # program production serves, not an f32 variant
            return jax.ShapeDtypeStruct(sd.shape, cfg.dtype)
        return sd

    out = {"tok_embed": jax.ShapeDtypeStruct(params_abs["tok_embed"].shape,
                                             cfg.dtype),
           "final_norm": params_abs["final_norm"]}
    for stack in ("layers", "prefix_layers"):
        if stack in params_abs:
            out[stack] = {name: (q(sd) if name in quantized
                                 else passthrough(name, sd))
                          for name, sd in params_abs[stack].items()}
    if "lm_head" in params_abs:
        out["lm_head"] = q(params_abs["lm_head"])
    return out


def check_sharded_serving(results):
    """70B-class int8 decode over a v5e:2x4 mesh (tensor=8): the
    quantized_logical_axes shardings compiled for the REAL target — the
    big-model production config (a 70B does not fit ONE chip at any
    precision; int8 + 8-way tensor parallel is how it serves)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def prog(make_cfg, what, bits=8):
        from k8s_runpod_kubelet_tpu.models import LlamaModel
        from k8s_runpod_kubelet_tpu.models.quant import quantized_logical_axes
        from k8s_runpod_kubelet_tpu.parallel import (MeshConfig, make_mesh,
                                                     param_shardings)
        topo = _topo("v5e:2x4")
        mesh = make_mesh(MeshConfig(data=1, tensor=8), list(topo.devices))
        cfg = make_cfg()
        model = LlamaModel(cfg, mesh)
        slots, cache_len = 8, 2048
        q_abs = _quantized_abs_shapes(cfg, bits=bits)
        shardings = param_shardings(mesh,
                                    quantized_logical_axes(cfg, bits=bits))
        q_sds = jax.tree_util.tree_map(
            lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            q_abs, shardings)
        cache_abs = jax.eval_shape(
            lambda: model.init_cache(slots, cache_len, quantize=True))
        repl = NamedSharding(mesh, P())
        # the engine's OWN layout contract (one definition, serving.py)
        from k8s_runpod_kubelet_tpu.workloads.serving import kv_cache_pspec
        cache_sds = {
            name: jax.ShapeDtypeStruct(
                sd.shape, sd.dtype,
                sharding=NamedSharding(mesh, kv_cache_pspec(name, sd.ndim)))
            for name, sd in cache_abs.items()}
        # same _lower_decode recipe as every single-device decode cell —
        # pre-sharded trees pass through, repl covers token/active
        return _lower_decode(
            model, q_sds, cache_sds, slots, repl,
            f"{what} int{bits} decode, tensor=8 over v5e:2x4, "
            f"{slots} slots int8 KV — sharded quantized serving "
            "compiled for the real target")

    def _cell(maker_name, what, bits=8):
        # model import INSIDE the cell thunk: _run records an import
        # failure as that cell's compile_ok=false instead of aborting
        # the whole evidence run
        import k8s_runpod_kubelet_tpu.models as models
        return prog(getattr(models, maker_name), what, bits=bits)

    results["decode_70b_int8_tp8_2x4"] = _run(
        "decode_70b_int8_tp8_2x4",
        lambda: _cell("llama3_70b", "llama3-70b"))
    # MoE: expert weights quantize too (~96% of mixtral's params); this
    # cell compile-proves the {q8, scale} expert einsums under GSPMD
    results["decode_mixtral_int8_tp8_2x4"] = _run(
        "decode_mixtral_int8_tp8_2x4",
        lambda: _cell("mixtral_8x7b", "mixtral-8x7b"))
    # MLA (VERDICT r4 item 3): deepseek-v2-lite absorbed decode from the
    # int8 LATENT cache under GSPMD — params shard by heads/mlp over
    # tensor, the latent c/kr sections REPLICATE (kv_cache_pspec: no heads
    # axis; every shard's heads read all latents). 16B int8 does not fit
    # one v5e; tensor=8 is its serving shape.
    results["decode_dsv2lite_mla_int8_tp8_2x4"] = _run(
        "decode_dsv2lite_mla_int8_tp8_2x4",
        lambda: _cell("deepseek_v2_lite",
                      "deepseek-v2-lite MLA absorbed decode, int8 latent "
                      "cache (576B/tok bf16 -> int8+scales)"))
    # int4 x tensor parallel (VERDICT r4 item 6): packed weights shard
    # their OUT axis (quantized_logical_axes bits=4); the Pallas unpack
    # kernel partitions via int4_matmul_sharded's shard_map —
    # 70B at ~4.4GB int4 weights per chip is the quarter-traffic rung of
    # the slice-serving ladder
    results["decode_70b_int4_tp8_2x4"] = _run(
        "decode_70b_int4_tp8_2x4",
        lambda: _cell("llama3_70b", "llama3-70b", bits=4))


def _tree_bytes_per_chip(sds_tree) -> int:
    """Per-chip bytes of a ShapeDtypeStruct tree whose leaves carry
    NamedShardings: sum of each leaf's SHARD size. The memory-evidence
    number AOT cost analysis cannot give (it reports whole-program HBM,
    not which tree pays it)."""
    import math

    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(sds_tree):
        shard = leaf.sharding.shard_shape(leaf.shape)
        total += math.prod(shard) * leaf.dtype.itemsize
    return total


def _expert_bytes_per_chip(sds_tree) -> int:
    """Per-chip bytes of just the EXPERT leaves (we_gate/we_up/we_down,
    any quantized form) — the tree EP exists to divide."""
    total = 0
    for stack in ("layers", "prefix_layers"):
        for name, leaf in sds_tree.get(stack, {}).items():
            if name.startswith("we_"):
                total += _tree_bytes_per_chip(leaf)
    return total


def check_ep_serving(results):
    """Expert-parallel MoE decode over v5e:2x4 as EP4 x TP2: expert
    weights shard their EXPERT axis (4-way) on top of tensor parallelism
    (2-way), the expert FFN runs under moe._expert_ffn_sharded's
    shard_map, and — the int4 cell — the per-expert Pallas unpack kernel
    Mosaic-compiles inside it. Each record carries per-chip weight bytes
    (computed from the shard shapes, not asserted) against a
    tensor-only-at-the-same-TP-degree baseline: EP must divide the
    expert bytes by the EP factor that tensor parallelism alone (TP2 +
    replication over the remaining chips) cannot touch."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def prog(bits, ep=4, tp=2):
        import os

        from k8s_runpod_kubelet_tpu.models import LlamaModel, mixtral_8x7b
        from k8s_runpod_kubelet_tpu.models.quant import quantized_logical_axes
        from k8s_runpod_kubelet_tpu.parallel import (MeshConfig, make_mesh,
                                                     param_shardings)
        from k8s_runpod_kubelet_tpu.workloads.serving import kv_cache_pspec
        topo = _topo("v5e:2x4")
        mesh = make_mesh(MeshConfig(data=1, expert=ep, tensor=tp),
                         list(topo.devices))
        cfg = mixtral_8x7b()
        model = LlamaModel(cfg, mesh)
        slots, cache_len = 8, 2048
        q_abs = _quantized_abs_shapes(cfg, bits=bits)
        shardings = param_shardings(mesh,
                                    quantized_logical_axes(cfg, bits=bits))
        q_sds = jax.tree_util.tree_map(
            lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            q_abs, shardings)
        # tensor-only baseline at the SAME TP degree: the other chips
        # replicate — what the engine sharded like before the expert axis
        # existed
        base_mesh = make_mesh(MeshConfig(data=8 // tp, tensor=tp),
                              list(topo.devices))
        base_shardings = param_shardings(
            base_mesh, quantized_logical_axes(cfg, bits=bits))
        base_sds = jax.tree_util.tree_map(
            lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            q_abs, base_shardings)
        cache_abs = jax.eval_shape(
            lambda: model.init_cache(slots, cache_len, quantize=True))
        repl = NamedSharding(mesh, P())
        cache_sds = {
            name: jax.ShapeDtypeStruct(
                sd.shape, sd.dtype,
                sharding=NamedSharding(mesh, kv_cache_pspec(name, sd.ndim)))
            for name, sd in cache_abs.items()}
        key = "TPU_KUBELET_FORCE_PALLAS"
        prev = os.environ.get(key)
        if bits == 4:
            # AOT runs on a CPU host: force the Mosaic unpack kernel so
            # the cell compiles the program production serves, not the
            # XLA fallback (same discipline as the *pk dense cells)
            os.environ[key] = "1"
        try:
            rec = _lower_decode(
                model, q_sds, cache_sds, slots, repl,
                f"mixtral-8x7b int{bits} decode, expert={ep} x tensor={tp} "
                f"over v5e:2x4, {slots} slots int8 KV — expert-parallel MoE "
                "serving compiled for the real target"
                + (" (per-expert Pallas int4 unpack under shard_map)"
                   if bits == 4 else ""))
        finally:
            if prev is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prev
        ep_chip = _expert_bytes_per_chip(q_sds)
        tp_chip = _expert_bytes_per_chip(base_sds)
        rec["weight_bytes_per_chip"] = _tree_bytes_per_chip(q_sds)
        rec["weight_bytes_per_chip_tp_only"] = _tree_bytes_per_chip(base_sds)
        rec["expert_bytes_per_chip"] = ep_chip
        rec["expert_bytes_per_chip_tp_only"] = tp_chip
        rec["expert_reduction_vs_tp_only"] = round(tp_chip / ep_chip, 2)
        return rec

    results["decode_mixtral_int8_ep4_tp2"] = _run(
        "decode_mixtral_int8_ep4_tp2", lambda: prog(8))
    results["decode_mixtral_int4_ep4_tp2"] = _run(
        "decode_mixtral_int4_ep4_tp2", lambda: prog(4))
    # int4's best shape is EP-heavy: packed experts replicate over tensor
    # (their contraction cannot shard), so at EP4xTP2 the 2x packing win
    # and the 2x tensor replication cancel — per-chip expert bytes equal
    # int8's. EP8xTP1 keeps the full packing win: this cell records the
    # int4-MoE memory headline (per-chip expert bytes ~half the EP4xTP2
    # cells')
    results["decode_mixtral_int4_ep8"] = _run(
        "decode_mixtral_int4_ep8", lambda: prog(4, ep=8, tp=1))


def check_mla(results, dev):
    """MLA (ops/mla.py) absorbed decode at DeepSeek-V2-Lite-class geometry
    vs a standard-cache attention decode of the SAME head count — the
    latent-cache bandwidth claim as XLA-measured bytes, plus the Mosaic/
    XLA compile proof for v5e."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding
    from k8s_runpod_kubelet_tpu.ops.mla import (init_mla_cache,
                                                init_mla_params,
                                                mla_decode_step)
    from k8s_runpod_kubelet_tpu.ops.rope import rope_frequencies

    s = SingleDeviceSharding(dev)
    b, e, h, dh, dr, r, cache_len = 8, 2048, 16, 128, 64, 512, 2048

    def prog_mla():
        params = jax.eval_shape(
            lambda k: init_mla_params(k, embed_dim=e, n_heads=h, head_dim=dh,
                                      latent_dim=r, rope_dim=dr,
                                      dtype=jnp.bfloat16),
            jax.random.PRNGKey(0))
        cache = jax.eval_shape(
            lambda: init_mla_cache(b, cache_len, latent_dim=r, rope_dim=dr,
                                   dtype=jnp.bfloat16))
        cos, sin = rope_frequencies(dr, max_seq_len=cache_len)

        def step(h1, params, cache):
            return mla_decode_step(h1, params, cache, cos, sin)
        lowered = jax.jit(step, donate_argnums=(2,)).lower(
            jax.ShapeDtypeStruct((b, 1, e), jnp.bfloat16, sharding=s),
            _sds_tree(params, s), _sds_tree(cache, s))
        rec = _analyze(lowered.compile(), tokens_per_step=b)
        rec["note"] = (f"MLA absorbed decode, {h} heads x {dh}, latent "
                       f"{r}+{dr}, cache {cache_len}: latent KV = "
                       f"{(r + dr) / (2 * h * dh):.0%} of standard KV bytes")
        return rec

    def prog_std():
        # LIKE-FOR-LIKE standard block: the same h (B,1,E) input through
        # full QKVO projections + a per-head KV cache — a bare attention
        # core without weights would understate the baseline's reads and
        # overstate MLA's advantage (first AOT pass made that mistake)
        from k8s_runpod_kubelet_tpu.ops.rope import rope_frequencies
        cos, sin = rope_frequencies(dh, max_seq_len=cache_len)
        wq_sds = jax.ShapeDtypeStruct((e, h * dh), jnp.bfloat16, sharding=s)
        wo_sds = jax.ShapeDtypeStruct((h * dh, e), jnp.bfloat16, sharding=s)
        kv_sds = jax.ShapeDtypeStruct((b, cache_len, h, dh), jnp.bfloat16,
                                      sharding=s)
        idx_sds = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=s)

        def step(h1, wq, wk, wv, wo, kc, vc, idx):
            from k8s_runpod_kubelet_tpu.ops.rope import apply_rope
            q = (h1 @ wq).reshape(b, 1, h, dh)
            k1 = (h1 @ wk).reshape(b, 1, h, dh)
            v1 = (h1 @ wv).reshape(b, 1, h, dh)
            pos = idx[:, None]
            q = apply_rope(q, cos, sin, pos)
            k1 = apply_rope(k1, cos, sin, pos)
            rows = jnp.arange(b)
            kc = kc.at[rows, idx].set(k1[:, 0])
            vc = vc.at[rows, idx].set(v1[:, 0])
            scores = jnp.einsum("bohd,blhd->bhol", q, kc) * dh ** -0.5
            live = (jnp.arange(cache_len)[None]
                    <= idx[:, None])[:, None, None, :]
            scores = jnp.where(live, scores.astype(jnp.float32), -jnp.inf)
            p = jax.nn.softmax(scores, axis=-1).astype(h1.dtype)
            o = jnp.einsum("bhol,blhd->bohd", p, vc).reshape(b, 1, h * dh)
            return o @ wo, kc, vc
        lowered = jax.jit(step, donate_argnums=(5, 6)).lower(
            jax.ShapeDtypeStruct((b, 1, e), jnp.bfloat16, sharding=s),
            wq_sds, wq_sds, wq_sds, wo_sds, kv_sds, kv_sds, idx_sds)
        rec = _analyze(lowered.compile(), tokens_per_step=b)
        rec["note"] = ("standard-cache QKVO attention block, same heads/"
                      "geometry/input — the like-for-like MLA baseline")
        return rec

    results["mla_decode_8x2048"] = _run("mla_decode_8x2048", prog_mla)
    results["std_attn_decode_8x2048"] = _run("std_attn_decode_8x2048",
                                             prog_std)


def _run(name, fn):
    t0 = time.time()
    try:
        rec = fn()
        rec["compile_ok"] = True
    except Exception as e:  # noqa: BLE001 — record, keep going
        rec = {"compile_ok": False,
               "error": f"{type(e).__name__}: {e}"[:500]}
    rec["compile_wall_s"] = round(time.time() - t0, 1)
    print(f"[aot] {name}: "
          + (f"ok bound={rec.get('roofline_bound')} "
             f"fits16gb={rec.get('fits_16gb')} "
             f"tok/s<= {rec.get('roofline_tok_s_bound')}"
             if rec["compile_ok"] else f"FAILED {rec['error'][:120]}"),
          flush=True)
    return rec


def main() -> int:
    _force_cpu()
    import jax  # noqa: F401 — initialize before topologies

    results: dict[str, dict] = {}
    topo1 = _topo("v5e:1x1", chips_per_host_bounds=(1, 1, 1))
    dev = topo1.devices[0]
    checks = [
        ("train", lambda: check_train(results, dev)),
        ("serving", lambda: check_serving_8b(results, dev)),
        ("alt", lambda: check_serving_alt(results, dev)),
        ("flash", lambda: check_flash_attention(results, dev)),
        ("flash32k", lambda: check_flash_32k(results, dev)),
        ("ring", lambda: check_ring_flash(results)),
        ("sharded", lambda: check_sharded_train(results)),
        ("sharded_serving", lambda: check_sharded_serving(results)),
        ("ep_serving", lambda: check_ep_serving(results)),
        ("mla", lambda: check_mla(results, dev)),
    ]
    names = [n for n, _ in checks]
    only = ""
    if "--only" in sys.argv:
        i = sys.argv.index("--only") + 1
        only = sys.argv[i] if i < len(sys.argv) else ""
        if only not in names:  # a typo must not rewrite the evidence file
            print(f"usage: aot_check.py [--only {'|'.join(names)}]",
                  file=sys.stderr)
            return 2
    for name, fn in checks:
        if only and only != name:
            continue
        fn()

    os.makedirs(os.path.join(_HERE, "bench_results"), exist_ok=True)
    path = os.path.join(_HERE, "bench_results", "aot_v5e.json")
    programs = {}
    if only:  # partial run (--only): merge over the existing evidence file
        try:
            with open(path, encoding="utf-8") as f:
                programs = json.load(f).get("programs", {})
        except (OSError, json.JSONDecodeError):
            pass
    programs.update(results)
    out = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "jax": jax.__version__,
        "target": "v5e (device-less TopologyDescription AOT)",
        "v5e_specs": {"bf16_flops": _V5E_BF16_FLOPS,
                      "hbm_bytes_s": _V5E_HBM_BYTES_S,
                      "hbm_bytes": _V5E_HBM_BYTES},
        "programs": programs,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(f"[aot] wrote {path}")
    ok = sum(1 for r in results.values() if r.get("compile_ok"))
    # RESOURCE_EXHAUSTED records are memory-boundary ANSWERS (several
    # grid points OOM by design), so they must not fail the run — but a
    # NON-OOM compile failure (e.g. a Mosaic lowering regression) must
    # still gate scripts chaining on the exit code
    real_failures = [k for k, r in results.items()
                     if not r.get("compile_ok")
                     and "RESOURCE_EXHAUSTED" not in r.get("error", "")]
    print(f"[aot] {ok}/{len(results)} programs compiled for v5e "
          f"(OOM records are memory-boundary answers; "
          f"real failures: {real_failures or 'none'})")
    return 1 if (real_failures or not results) else 0


if __name__ == "__main__":
    sys.exit(main())
