{{/* Helper shape parity: helm/runpod-kubelet/templates/_helpers.tpl */}}
{{- define "tpu-virtual-kubelet.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" }}
{{- end }}

{{- define "tpu-virtual-kubelet.fullname" -}}
{{- if .Values.fullnameOverride }}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" }}
{{- else }}
{{- printf "%s-%s" .Release.Name (include "tpu-virtual-kubelet.name" .) | trunc 63 | trimSuffix "-" }}
{{- end }}
{{- end }}

{{- define "tpu-virtual-kubelet.labels" -}}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version }}
app.kubernetes.io/name: {{ include "tpu-virtual-kubelet.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{- define "tpu-virtual-kubelet.selectorLabels" -}}
app.kubernetes.io/name: {{ include "tpu-virtual-kubelet.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end }}

{{- define "tpu-virtual-kubelet.serviceAccountName" -}}
{{- if .Values.serviceAccount.create }}
{{- default (include "tpu-virtual-kubelet.fullname" .) .Values.serviceAccount.name }}
{{- else }}
{{- default "default" .Values.serviceAccount.name }}
{{- end }}
{{- end }}
