"""Checker framework: findings, justification-carrying allowlists, reports.

Every checker follows the contract ``tests/test_exception_hygiene.py``
pioneered:

- ``collect(index)`` yields RAW findings — every violation the heuristic
  sees, before any suppression;
- the checker's ``allowlist`` maps a finding key to a WRITTEN justification
  (adding an entry is a conscious, reviewed act, never an accident);
- ``run(index)`` splits raw findings into live findings (not allowlisted)
  and suppressed ones, and reports STALE allowlist entries — an entry that
  no longer suppresses anything is dead weight, and a typo'd entry would
  silently protect nothing, so staleness fails as loudly as a finding.

Report format is ``file:line (in func): message`` for humans and GitHub
``::error`` annotations for CI (``--format=github``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Hashable, Iterable, Optional

from .index import PACKAGE_NAME, PackageIndex


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    file: str          # package-relative path ("" for package-wide findings)
    line: int
    func: str          # enclosing function, "<module>", or a logical scope
    message: str
    key: Hashable      # allowlist key; conventionally (file, func) or a name

    def text(self) -> str:
        loc = f"{self.file}:{self.line}" if self.file else "<package>"
        return f"{loc} (in {self.func}): {self.message}"

    def github(self) -> str:
        if self.file:
            path = f"{PACKAGE_NAME}/{self.file}"
        elif "/" in self.func:
            path = self.func  # package-wide finding located by resource path
        else:
            path = "README.md"
        # GitHub annotation message is a single line; commas in file are fine
        msg = self.message.replace("\n", " ")
        return (f"::error file={path},line={max(self.line, 1)},"
                f"title=graftlint/{self.checker}::{msg}")


@dataclasses.dataclass
class CheckResult:
    checker: str
    findings: list[Finding]            # live, not allowlisted
    suppressed: list[Finding]          # allowlisted, with justification
    stale_allowlist: list[Hashable]    # entries that suppressed nothing

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_allowlist


class Checker:
    """Base class: subclasses set ``name``/``description``/``allowlist`` and
    implement ``collect``. The allowlist may be overridden per instance so
    snippet tests can exercise the allowlisted path."""

    name: str = ""
    description: str = ""
    allowlist: dict = {}

    def __init__(self, allowlist: Optional[dict] = None):
        if allowlist is not None:
            self.allowlist = dict(allowlist)

    def collect(self, index: PackageIndex) -> Iterable[Finding]:
        raise NotImplementedError

    def run(self, index: PackageIndex) -> CheckResult:
        raw = list(self.collect(index))
        live = [f for f in raw if f.key not in self.allowlist]
        suppressed = [f for f in raw if f.key in self.allowlist]
        seen = {f.key for f in raw}
        stale = sorted((k for k in self.allowlist if k not in seen), key=repr)
        return CheckResult(self.name, live, suppressed, stale)


@dataclasses.dataclass
class SuiteResult:
    results: list[CheckResult]
    files_parsed: int
    elapsed_s: float

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    @property
    def findings(self) -> list[Finding]:
        return [f for r in self.results for f in r.findings]

    def render(self, fmt: str = "text") -> str:
        out: list[str] = []
        for r in self.results:
            for f in sorted(r.findings, key=lambda f: (f.file, f.line)):
                out.append(f.github() if fmt == "github"
                           else f"[{r.checker}] {f.text()}")
            for key in r.stale_allowlist:
                msg = (f"stale allowlist entry {key!r}: it no longer "
                       f"suppresses any finding — remove it (or fix the typo; "
                       f"a typo'd entry protects nothing)")
                out.append(f"::error title=graftlint/{r.checker}::{msg}"
                           if fmt == "github" else f"[{r.checker}] {msg}")
        n_sup = sum(len(r.suppressed) for r in self.results)
        n_live = len(self.findings)
        n_stale = sum(len(r.stale_allowlist) for r in self.results)
        out.append(f"graftlint: {len(self.results)} checkers over "
                   f"{self.files_parsed} files in {self.elapsed_s:.2f}s — "
                   f"{n_live} finding(s), {n_sup} allowlisted, "
                   f"{n_stale} stale allowlist entr(ies)")
        return "\n".join(out)


def run_checkers(index: PackageIndex,
                 checkers: Iterable[Checker]) -> SuiteResult:
    started = time.monotonic()
    results = [c.run(index) for c in checkers]
    return SuiteResult(results, len(index), time.monotonic() - started)
