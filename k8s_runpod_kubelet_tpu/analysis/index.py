"""Single-parse package index shared by every checker and lint test.

Before this existed, each lint test (exception hygiene, metrics lint)
re-walked and re-parsed the whole package independently; every new checker
would have added another full parse. This module parses each package file
exactly ONCE per process (``get_package_index`` is cached) and hands
checkers an indexed view: per-file ASTs, source text, enclosing
function/class lookup by line, and the non-Python resources the
cross-layer checkers need (README, helm values + templates).

A ``PackageIndex`` can also be built from in-memory snippets
(``PackageIndex(files={...}, resources={...})``) so each checker is unit
-testable against small synthetic positive/negative cases without touching
the real tree.
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import pathlib
from typing import Iterable, Optional

PACKAGE_NAME = "k8s_runpod_kubelet_tpu"


@dataclasses.dataclass
class _Scope:
    """One function or class body: name + inclusive line span."""
    kind: str  # "func" | "class"
    name: str
    start: int
    end: int
    node: ast.AST


@dataclasses.dataclass
class FileInfo:
    rel: str          # posix path relative to the package root, e.g. "fleet/router.py"
    source: str
    tree: ast.Module
    _scopes: Optional[list[_Scope]] = None

    @property
    def scopes(self) -> list[_Scope]:
        if self._scopes is None:
            out = []
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append(_Scope("func", node.name, node.lineno,
                                      getattr(node, "end_lineno", node.lineno),
                                      node))
                elif isinstance(node, ast.ClassDef):
                    out.append(_Scope("class", node.name, node.lineno,
                                      getattr(node, "end_lineno", node.lineno),
                                      node))
            self._scopes = out
        return self._scopes

    def _innermost(self, kind: str, lineno: int) -> Optional[_Scope]:
        best: Optional[_Scope] = None
        for s in self.scopes:
            if s.kind == kind and s.start <= lineno <= s.end:
                if best is None or s.end - s.start < best.end - best.start:
                    best = s
        return best

    def enclosing_function(self, lineno: int) -> str:
        """Name of the innermost def containing the line (or <module>)."""
        s = self._innermost("func", lineno)
        return s.name if s else "<module>"

    def enclosing_function_node(self, lineno: int) -> Optional[ast.AST]:
        s = self._innermost("func", lineno)
        return s.node if s else None

    def enclosing_class(self, lineno: int) -> Optional[str]:
        s = self._innermost("class", lineno)
        return s.name if s else None


class PackageIndex:
    """All package files parsed once, plus cross-layer text resources.

    ``files`` maps package-relative posix paths to source text; ``resources``
    maps repo-relative names (``README.md``, ``helm/values.yaml``,
    ``helm/templates/deployment.yaml``) to raw text. Checkers that need a
    missing resource must report that loudly, never skip silently.
    """

    def __init__(self, files: dict[str, str],
                 resources: Optional[dict[str, str]] = None):
        self._files: dict[str, FileInfo] = {}
        for rel, source in sorted(files.items()):
            self._files[rel] = FileInfo(
                rel=rel, source=source,
                tree=ast.parse(source, filename=rel))
        self._resources = dict(resources or {})

    @classmethod
    def from_package(cls, pkg_root: pathlib.Path,
                     repo_root: Optional[pathlib.Path] = None) -> "PackageIndex":
        pkg_root = pathlib.Path(pkg_root)
        files = {p.relative_to(pkg_root).as_posix(): p.read_text(encoding="utf-8")
                 for p in sorted(pkg_root.rglob("*.py"))}
        resources: dict[str, str] = {}
        if repo_root is None:
            repo_root = pkg_root.parent
        for name in ("README.md",):
            p = repo_root / name
            if p.is_file():
                resources[name] = p.read_text(encoding="utf-8")
        helm = repo_root / "helm"
        if helm.is_dir():
            for p in sorted(helm.rglob("*")):
                if p.suffix in (".yaml", ".yml", ".tpl", ".txt") and p.is_file():
                    resources["helm/" + p.relative_to(helm).as_posix()] = \
                        p.read_text(encoding="utf-8")
        return cls(files, resources)

    # -- files -----------------------------------------------------------------

    def files(self) -> Iterable[FileInfo]:
        return self._files.values()

    def file(self, rel: str) -> Optional[FileInfo]:
        return self._files.get(rel)

    def __contains__(self, rel: str) -> bool:
        return rel in self._files

    def __len__(self) -> int:
        return len(self._files)

    # -- resources -------------------------------------------------------------

    def resource(self, name: str) -> Optional[str]:
        return self._resources.get(name)

    def resource_names(self, prefix: str = "") -> list[str]:
        return sorted(n for n in self._resources if n.startswith(prefix))


@functools.lru_cache(maxsize=4)
def _cached_index(pkg_root: str, repo_root: Optional[str]) -> PackageIndex:
    return PackageIndex.from_package(
        pathlib.Path(pkg_root),
        pathlib.Path(repo_root) if repo_root else None)


def get_package_index(pkg_root: Optional[pathlib.Path] = None,
                      repo_root: Optional[pathlib.Path] = None) -> PackageIndex:
    """The process-wide shared index: one AST parse per file per process,
    whether five lint tests or the CLI ask for it."""
    if pkg_root is None:
        pkg_root = pathlib.Path(__file__).resolve().parent.parent
    return _cached_index(str(pkg_root), str(repo_root) if repo_root else None)
