"""CLI: ``python -m k8s_runpod_kubelet_tpu.analysis`` / ``graftlint``.

Exit status is the CI contract: 0 = clean, 1 = findings or stale allowlist
entries, 2 = bad invocation. ``--format=github`` renders findings as
``::error`` workflow annotations; the default text format is
``file:line (in func): message`` like the repo's other lints.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from .checkers import ALL_CHECKERS
from .core import run_checkers
from .index import get_package_index


def main(argv=None) -> int:
    by_name = {c.name: c for c in ALL_CHECKERS}
    p = argparse.ArgumentParser(
        "graftlint",
        description="project-specific static analysis (see README "
                    "'Static analysis' for the checker catalogue)")
    p.add_argument("--format", choices=["text", "github"], default="text",
                   help="github = ::error workflow annotations for CI")
    p.add_argument("--checker", action="append", choices=sorted(by_name),
                   help="run only these checkers (repeatable); default all")
    p.add_argument("--package", default=None,
                   help="package root to analyze (default: the installed "
                        "k8s_runpod_kubelet_tpu package)")
    p.add_argument("--repo-root", default=None,
                   help="repo root holding README.md and helm/ (default: "
                        "the package root's parent)")
    p.add_argument("--list", action="store_true",
                   help="list checkers and exit")
    args = p.parse_args(argv)

    if args.list:
        for c in ALL_CHECKERS:
            print(f"{c.name}: {c.description}")
        return 0

    pkg_root = pathlib.Path(args.package).resolve() if args.package else None
    repo_root = pathlib.Path(args.repo_root).resolve() \
        if args.repo_root else None
    index = get_package_index(pkg_root, repo_root)
    names = args.checker or [c.name for c in ALL_CHECKERS]
    suite = run_checkers(index, [by_name[n]() for n in names])
    print(suite.render(args.format))
    return 0 if suite.ok else 1


if __name__ == "__main__":
    sys.exit(main())
