"""graftlint: project-specific static analysis for the TPU virtual kubelet.

Mechanizes the bug classes five PRs of review-hardening kept re-finding by
hand: raw wall-clock calls that break injected-clock soak determinism,
state mutated outside its admission lock, config knobs that never reach
the gang env, telemetry emitted under uncatalogued names, and
fire-and-forget threads.

Run it three ways, all off ONE shared parse of the package:

- ``python -m k8s_runpod_kubelet_tpu.analysis`` (CLI; ``--format=github``
  for CI annotations; exits nonzero on findings or stale allowlists);
- ``graftlint`` (console script, same thing);
- tier-1 pytest (``tests/test_static_analysis.py`` plus the migrated
  exception-hygiene/metrics lints share the cached index).
"""

from .core import Checker, CheckResult, Finding, SuiteResult, run_checkers
from .index import (PACKAGE_NAME, FileInfo, PackageIndex,
                    get_package_index)
from .checkers import ALL_CHECKERS

__all__ = ["ALL_CHECKERS", "Checker", "CheckResult", "FileInfo", "Finding",
           "PACKAGE_NAME", "PackageIndex", "SuiteResult",
           "get_package_index", "run_checkers"]
