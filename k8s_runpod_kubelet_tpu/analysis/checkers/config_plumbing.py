"""Config-plumbing checker: no dead knobs, no unwired channels.

PR 5's review found helm ``stragglerFactor``/``stallTimeoutSeconds`` keys
that reached nothing, and the reference design this repo reproduces shipped
``--max-gpu-price`` parsed-but-never-used (SURVEY §5.6). The knob classes
keep multiplying (config -> env -> flag -> helm is four layers that must
agree), so this checker makes the whole chain structural. For every field
of ``Config``:

- **read**: the field must be consumed somewhere outside ``config.py``
  (attribute-name match across the package) — a field nothing reads is the
  ``PendingJobThreshold`` dead-knob class;
- **env**: an ``_ENV_MAP`` entry must map to it (``TPU_*`` convention);
- **flag**: a ``cmd/main.py`` or ``fleet/router_main.py`` ``add_argument``
  must have it as dest;
- **validated**: numeric fields must be range-checked in ``validate()``
  (an unvalidated interval accepts ``-30`` and spins a hot loop);
- **helm**: one of the field's env names or flag spellings must appear in a
  helm template (values.yaml alone is not wiring — that was the PR 5 bug).

And in the other direction:

- every ``_ENV_MAP`` value and every ``cmd/main.py`` dest must be a real
  field (typo guard);
- every scalar leaf in helm ``values.yaml`` must be referenced by some
  template (``.Values.<path>``, prefix-matching for ``toYaml`` blocks);
- every ``TPU_*``/``KUBELET_*`` env name a template renders must exist in
  ``_ENV_MAP`` (template-vs-code drift guard).

Fields where a channel is intentionally absent carry an allowlist entry
keyed ``(dimension, field)`` with the reason — secrets never ride argv,
identity comes from the downward API, etc.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from ..core import Checker, Finding
from ..index import PackageIndex

_FLAG_FILES = ("cmd/main.py", "fleet/router_main.py",
               "workloads/serve_main.py")
# must END on an alnum: "TPU_FLEET_*" in a template comment is prose, not
# an env name
_ENV_NAME_RE = re.compile(r"\b(?:TPU|KUBELET)_[A-Z0-9_]*[A-Z0-9]\b")


def _numeric_default(node: Optional[ast.expr]) -> bool:
    """True when the field default is an int/float (incl. simple arithmetic
    like ``15 * 60``) — the fields validate() must range-check."""
    if node is None:
        return False
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.BinOp):
        return _numeric_default(node.left) and _numeric_default(node.right)
    if isinstance(node, ast.UnaryOp):
        return _numeric_default(node.operand)
    return False


def _config_fields(tree: ast.Module) -> dict[str, bool]:
    """Field name -> is_numeric for the ``Config`` dataclass."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            out = {}
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    out[stmt.target.id] = _numeric_default(stmt.value)
            return out
    return {}


def _env_map(tree: ast.Module) -> dict[str, str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "_ENV_MAP"
                        for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            return {k.value: v.value
                    for k, v in zip(node.value.keys, node.value.values)
                    if isinstance(k, ast.Constant)
                    and isinstance(v, ast.Constant)}
    return {}


def _validated_fields(tree: ast.Module) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "validate":
            out = {n.attr for n in ast.walk(node)
                   if isinstance(n, ast.Attribute)
                   and isinstance(n.value, ast.Name)
                   and n.value.id == "self"}
            # the `for f in ("a_s", "b_s"): getattr(self, f)` batch idiom:
            # string literals inside validate() count as referenced fields
            out |= {n.value for n in ast.walk(node)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)}
            return out
    return set()


def _flags_by_file(index: PackageIndex) -> dict[str, dict[str, list[str]]]:
    """file -> (argparse dest -> option strings), for the flag-owning
    mains — read off the SHARED index, never a second parse."""
    out: dict[str, dict[str, list[str]]] = {}
    for rel in _FLAG_FILES:
        fi = index.file(rel)
        if fi is None:
            continue
        per_file = out.setdefault(rel, {})
        for node in ast.walk(fi.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"):
                continue
            opts = [a.value for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str) and a.value.startswith("--")]
            if not opts:
                continue
            dest = next((kw.value.value for kw in node.keywords
                         if kw.arg == "dest"
                         and isinstance(kw.value, ast.Constant)), None)
            if dest is None:
                dest = opts[0].lstrip("-").replace("-", "_")
            per_file.setdefault(dest, []).extend(opts)
    return out


def _merge_flags(by_file: dict[str, dict[str, list[str]]]) -> dict[str, list[str]]:
    merged: dict[str, list[str]] = {}
    for per_file in by_file.values():
        for dest, opts in per_file.items():
            merged.setdefault(dest, []).extend(opts)
    return merged


def _values_leaves(values_text: str) -> list[str]:
    """Dotted paths of every leaf in values.yaml (maps recursed; a scalar,
    list, or empty map is a leaf)."""
    import yaml
    data = yaml.safe_load(values_text) or {}
    leaves: list[str] = []

    def rec(prefix: str, node):
        if isinstance(node, dict) and node:
            for k, v in node.items():
                rec(f"{prefix}.{k}" if prefix else str(k), v)
        else:
            leaves.append(prefix)

    rec("", data)
    return leaves


class ConfigPlumbingChecker(Checker):
    name = "config-plumbing"
    description = ("every Config field wired through env/flag/validate/helm; "
                   "no dead values.yaml knobs or template drift")

    # (dimension, name) -> why the missing channel is intentional.
    allowlist = {
        # -- secrets: must not ride argv (visible in `ps`/pod spec) ----------
        ("flag", "tpu_api_token"):
            "secret: env/Secret-mount only, never argv (visible in ps)",
        ("flag", "api_auth_token"):
            "secret: env/Secret-mount only, never argv (visible in ps)",
        # -- identity/paths resolved by the runtime environment --------------
        ("env", "internal_ip"):
            "pod IP comes from the runtime (downward API / default "
            "127.0.0.1 for dev); the flag exists for bare-process runs",
        ("helm", "internal_ip"):
            "in-cluster the pod IP is discovered, not configured",
        ("env", "operating_system"):
            "reference-parity --os flag only; never varies in a chart deploy",
        ("helm", "operating_system"):
            "chart deploys are always Linux; --os is a dev/testing flag",
        ("env", "kubeconfig"):
            "standard KUBECONFIG discovery happens in RealKubeClient."
            "from_env; a second env var would shadow the convention",
        ("helm", "kubeconfig"):
            "in-cluster service-account auth; kubeconfig is for dev runs",
        ("env", "tls_cert_file"):
            "paths are fixed by the tlsSecretName mount (templates pass the "
            "flags); an env override would desync cert and key",
        ("env", "tls_key_file"):
            "paths are fixed by the tlsSecretName mount (see tls_cert_file)",
        # -- control-loop timing parity knobs (kubelet.go defaults):
        #    provider-config file only, deliberately not operator-facing ----
        ("env", "notify_interval_s"): "file-only parity timing knob",
        ("flag", "notify_interval_s"): "file-only parity timing knob",
        ("helm", "notify_interval_s"): "file-only parity timing knob",
        ("env", "pending_retry_interval_s"): "file-only parity timing knob",
        ("flag", "pending_retry_interval_s"): "file-only parity timing knob",
        ("helm", "pending_retry_interval_s"): "file-only parity timing knob",
        ("env", "max_pending_s"): "file-only parity timing knob",
        ("flag", "max_pending_s"): "file-only parity timing knob",
        ("helm", "max_pending_s"): "file-only parity timing knob",
        ("env", "cleanup_interval_s"): "file-only parity timing knob",
        ("flag", "cleanup_interval_s"): "file-only parity timing knob",
        ("helm", "cleanup_interval_s"): "file-only parity timing knob",
        ("env", "node_status_interval_s"): "file-only parity timing knob",
        ("flag", "node_status_interval_s"): "file-only parity timing knob",
        ("helm", "node_status_interval_s"): "file-only parity timing knob",
        ("env", "stuck_reterminate_s"): "file-only parity timing knob "
            "(5/10/15-min stuck-terminating ladder, kubelet.go:1333)",
        ("flag", "stuck_reterminate_s"): "file-only parity timing knob",
        ("helm", "stuck_reterminate_s"): "file-only parity timing knob",
        ("env", "stuck_unreachable_force_s"): "file-only parity timing knob",
        ("flag", "stuck_unreachable_force_s"): "file-only parity timing knob",
        ("helm", "stuck_unreachable_force_s"): "file-only parity timing knob",
        ("env", "stuck_force_delete_s"): "file-only parity timing knob",
        ("flag", "stuck_force_delete_s"): "file-only parity timing knob",
        ("helm", "stuck_force_delete_s"): "file-only parity timing knob",
        # -- misc deliberate gaps --------------------------------------------
        ("flag", "sentry_url"):
            "reference parity: SENTRY_URL is env-only (main.go:111)",
        ("env", "exec_killable"):
            "workload-image property, set per provider-config file; the "
            "helm chart has no distroless-image toggle yet",
        ("flag", "exec_killable"): "see (env, exec_killable)",
        ("helm", "exec_killable"): "see (env, exec_killable)",
        ("env", "metrics_enabled"): "dev-only off-switch, file-only",
        ("flag", "metrics_enabled"): "dev-only off-switch, file-only",
        ("helm", "metrics_enabled"): "dev-only off-switch, file-only",
        ("env", "trace_ring_size"):
            "debug sizing knob, provider-config file only",
        ("flag", "trace_ring_size"): "see (env, trace_ring_size)",
        ("helm", "trace_ring_size"): "see (env, trace_ring_size)",
        ("helm", "serving_role"):
            "per-pool role is stamped on each serving pod by the pool "
            "autoscaler (TPU_SERVING_ROLE env + tpu.dev/fleet-role label), "
            "not by the chart — the chart only sizes the pools",
    }

    def collect(self, index: PackageIndex) -> Iterable[Finding]:
        cfg = index.file("config.py")
        if cfg is None:
            return
        fields = _config_fields(cfg.tree)
        if not fields:
            return
        env_map = _env_map(cfg.tree)
        env_by_field: dict[str, list[str]] = {}
        for env_key, field in env_map.items():
            env_by_field.setdefault(field, []).append(env_key)
        validated = _validated_fields(cfg.tree)
        flags_by_file = _flags_by_file(index)
        flags = _merge_flags(flags_by_file)

        field_def_lines = {
            stmt.target.id: stmt.lineno
            for node in ast.walk(cfg.tree)
            if isinstance(node, ast.ClassDef) and node.name == "Config"
            for stmt in node.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)}

        # one pass: every attribute name accessed outside config.py —
        # including getattr(cfg, "field", ...) string literals, the
        # defensive-read idiom some consumers use
        attrs_read: set[str] = set()
        for fi in index.files():
            if fi.rel == "config.py":
                continue
            for node in ast.walk(fi.tree):
                if isinstance(node, ast.Attribute):
                    attrs_read.add(node.attr)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "getattr" \
                        and len(node.args) >= 2 \
                        and isinstance(node.args[1], ast.Constant) \
                        and isinstance(node.args[1].value, str):
                    attrs_read.add(node.args[1].value)

        templates = {n: index.resource(n) for n in index.resource_names("helm/")
                     if "/templates/" in n and n.endswith((".yaml", ".tpl"))}
        template_text = "\n".join(templates.values())
        values_name = next((n for n in index.resource_names("helm/")
                            if n.endswith("values.yaml")), None)
        values_text = index.resource(values_name) if values_name else None

        def helm_wired(field: str) -> bool:
            spellings = list(env_by_field.get(field, []))
            spellings += flags.get(field, [])
            # boundary-matched: "--zone" must not count as wired via a
            # surviving "--zones" line (prefix spellings are exactly the
            # dead-knob class this check exists to catch)
            return any(re.search(re.escape(s) + r"(?![\w-])", template_text)
                       for s in spellings)

        for field, numeric in fields.items():
            line = field_def_lines.get(field, 1)
            if field not in attrs_read:
                yield Finding(
                    self.name, "config.py", line, "Config",
                    f"dead knob: Config.{field} is never read outside "
                    f"config.py — delete it or wire it to behavior",
                    key=("read", field))
            if field not in env_by_field:
                yield Finding(
                    self.name, "config.py", line, "Config",
                    f"Config.{field} has no _ENV_MAP env var (TPU_* "
                    f"convention) — containerized deploys can't set it",
                    key=("env", field))
            if field not in flags:
                yield Finding(
                    self.name, "config.py", line, "Config",
                    f"Config.{field} has no argparse flag in "
                    f"{' or '.join(_FLAG_FILES)}",
                    key=("flag", field))
            if numeric and field not in validated:
                yield Finding(
                    self.name, "config.py", line, "Config",
                    f"numeric Config.{field} is not range-checked in "
                    f"validate() — a negative/zero value would misbehave "
                    f"silently at runtime",
                    key=("validated", field))
            if template_text and not helm_wired(field):
                yield Finding(
                    self.name, "config.py", line, "Config",
                    f"Config.{field} is reachable by no helm template (none "
                    f"of its env/flag spellings appear) — the PR 5 "
                    f"dead-helm-knob class",
                    key=("helm", field))

        for env_key, field in env_map.items():
            if field not in fields:
                yield Finding(
                    self.name, "config.py", 1, "_ENV_MAP",
                    f"_ENV_MAP[{env_key!r}] -> {field!r} is not a Config "
                    f"field (typo? renamed field?)",
                    key=("env-unknown", env_key))

        if "cmd/main.py" in index:
            known_extra = {"provider_config"}
            for dest, opts in flags_by_file.get("cmd/main.py", {}).items():
                if dest not in fields and dest not in known_extra:
                    yield Finding(
                        self.name, "cmd/main.py", 1, "parse_flags",
                        f"flag {opts[0]} (dest={dest}) is not a Config field "
                        f"— parsed but can never be applied (the reference's "
                        f"--max-gpu-price bug class)",
                        key=("flag-unknown", dest))

        if values_text and template_text:
            for path in _values_leaves(values_text):
                parts = path.split(".")
                prefixes = [".".join(parts[:i + 1]) for i in range(len(parts))]
                # a PREFIX only counts when consumed whole (`toYaml
                # .Values.resources`): it must not be followed by a deeper
                # `.key` — else a sibling's wiring would mask a dead leaf
                if not any(re.search(r"\.Values\." + re.escape(p)
                                     + r"(?![.\w])", template_text)
                           for p in prefixes):
                    yield Finding(
                        self.name, "", 1, values_name,
                        f"values.yaml key {path!r} is referenced by no "
                        f"template — a knob operators can set that changes "
                        f"nothing (the PR 5 stragglerFactor bug class)",
                        key=("helm-dead", path))
            for env_name in sorted(set(_ENV_NAME_RE.findall(template_text))):
                if env_name not in env_map:
                    yield Finding(
                        self.name, "", 1, "helm/templates",
                        f"template renders env var {env_name} but _ENV_MAP "
                        f"has no such key — the container sets it, the "
                        f"kubelet ignores it",
                        key=("template-env-unknown", env_name))
        elif values_text is None and "cmd/main.py" in index:
            # real-package run without helm resources: that's a broken
            # invocation (the helm dimension silently passing would defeat
            # the checker), so say it loudly
            yield Finding(
                self.name, "", 1, "helm/values.yaml",
                "helm/values.yaml not indexed — run from the repo root (or "
                "pass --repo-root) so the helm dimensions actually run",
                key=("resource", "helm/values.yaml"))
