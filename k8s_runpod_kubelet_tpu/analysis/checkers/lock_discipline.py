"""Lock-discipline checker: guarded state never touched outside the lock.

PR 4 needed three review passes to close drain/transit-counter races in the
engine: an attribute carefully mutated under ``with self._lock:`` in one
method, then read or written bare in another. This checker mechanizes that
review pass with a deliberately simple lexical heuristic:

- a class's LOCKS are the ``self.X = threading.Lock()/RLock()/Condition()``
  assignments in ``__init__``;
- a class's GUARDED attributes are those *written* (assign / augmented
  assign) inside any ``with self.<lock>:`` block outside ``__init__`` —
  writes define the protected state; reads of unguarded helpers (metrics,
  config) do not;
- a finding is any read OR write of a guarded attribute, outside every
  ``with self.<lock>:`` span, in any method except:
  ``__init__`` (single-threaded construction), methods named ``*_locked``
  (the caller-holds-the-lock convention), and methods whose docstring
  declares ``caller holds <lock>``;
- self-synchronizing attributes (Event/Queue/Semaphore/deque/Thread
  assigned in ``__init__``) are exempt — their methods take their own
  internal locks.

Findings aggregate to one per (file, class, attribute) so the allowlist
stays reviewable; a justification covers the attribute's whole unlocked
access pattern (e.g. "single consumer-thread reads by design"), which is
exactly the sentence a reviewer would otherwise re-derive every PR.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..core import Checker, Finding
from ..index import PackageIndex

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_SYNC_CTORS = {"Event", "Semaphore", "BoundedSemaphore", "Barrier",
               "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
               "deque", "Thread"}


def _ctor_name(value: ast.expr) -> Optional[str]:
    """Name of the class being constructed: threading.Lock() -> 'Lock'."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _self_attr(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _caller_holds(func: ast.AST, locks: set[str]) -> bool:
    doc = ast.get_docstring(func) if isinstance(
        func, (ast.FunctionDef, ast.AsyncFunctionDef)) else None
    if not doc:
        return False
    low = doc.lower()
    return "holds" in low and any(lk.lower() in low for lk in locks)


class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    description = ("attributes written under `with self._lock:` must not be "
                   "read or written bare elsewhere in the class")

    # (file, "Class.attr") -> why the unlocked accesses are correct.
    allowlist = {
        ("workloads/serving/engine.py", "ServingEngine._adapters"):
            "None-vs-dict is fixed at construction (lora_rank gate), so the "
            "`is None` reads are stable; the leaf arrays inside are only "
            "REPLACED wholesale under _adapter_lock (register_adapter), and "
            "the engine/prefill threads read whichever consistent stack "
            "reference they observe for that step — per-step staleness is "
            "the documented multi-LoRA contract, a lock here would serialize "
            "decode against adapter registration",
        ("workloads/serving/engine.py", "ServingEngine._transit"):
            "debug_snapshot is the documented lock-free statusz surface "
            "(its docstring: single GIL-atomic reads, may straddle a step); "
            "the authoritative drain check (`drained`) reads _transit under "
            "_transit_lock",
        ("workloads/serving/engine.py", "ServingEngine._ring_recycled"):
            "engine-thread-only counter: the ring-window recycle in "
            "_grow_slot_table and the drain in _arena_step_stats both run "
            "on the engine thread (decode loop), so no concurrent access "
            "exists — the increment merely happens to sit inside the "
            "prefix-lock block that guards the ARENA mutation next to it",
        ("workloads/serving/engine.py", "ServingEngine._kv_store"):
            "the reference is rebound ONLY by the engine thread's crash "
            "recovery (under _prefix_lock, after every in-flight future "
            "was failed); all trie/arena OPERATIONS re-enter via "
            "_prefix_lock, so the worst a stale reference can do is "
            "operate on the pre-crash store whose buffers the crash "
            "already invalidated (the request then fails like any "
            "poisoned prefill) — it can never corrupt the rebuilt store",
    }

    def collect(self, index: PackageIndex) -> Iterable[Finding]:
        for fi in index.files():
            for cls in ast.walk(fi.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                yield from self._check_class(fi, cls)

    def _check_class(self, fi, cls: ast.ClassDef) -> Iterable[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        init = next((m for m in methods if m.name == "__init__"), None)
        locks: set[str] = set()
        sync_attrs: set[str] = set()
        if init is not None:
            for node in ast.walk(init):
                if not isinstance(node, ast.Assign):
                    continue
                ctor = _ctor_name(node.value)
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if ctor in _LOCK_CTORS:
                        locks.add(attr)
                    elif ctor in _SYNC_CTORS:
                        sync_attrs.add(attr)
        if not locks:
            return

        def locked_spans(method) -> list[tuple[int, int]]:
            spans = []
            for node in ast.walk(method):
                if isinstance(node, ast.With):
                    for item in node.items:
                        if _self_attr(item.context_expr) in locks:
                            spans.append((node.lineno,
                                          getattr(node, "end_lineno",
                                                  node.lineno)))
                            break
            return spans

        def under_lock(spans, lineno) -> bool:
            return any(a <= lineno <= b for a, b in spans)

        # pass 1: attributes WRITTEN under a lock anywhere outside __init__
        guarded: set[str] = set()
        for m in methods:
            if m.name == "__init__":
                continue
            spans = locked_spans(m)
            if not spans:
                continue
            for node in ast.walk(m):
                attr = None
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        a = _self_attr(tgt)
                        if a and under_lock(spans, tgt.lineno):
                            attr = a
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    a = _self_attr(node.target)
                    if a and under_lock(spans, node.target.lineno):
                        attr = a
                if attr and attr not in locks and attr not in sync_attrs:
                    guarded.add(attr)
        if not guarded:
            return

        # pass 2: bare accesses of guarded attrs
        bare: dict[str, list[tuple[str, int]]] = {}
        for m in methods:
            if m.name == "__init__" or m.name.endswith("_locked") \
                    or _caller_holds(m, locks):
                continue
            spans = locked_spans(m)
            for node in ast.walk(m):
                attr = _self_attr(node)
                if attr in guarded and not under_lock(spans, node.lineno):
                    bare.setdefault(attr, []).append((m.name, node.lineno))

        for attr, sites in sorted(bare.items()):
            methods_str = ", ".join(sorted({f"{mname}:{ln}"
                                            for mname, ln in sites}))
            first_line = min(ln for _, ln in sites)
            yield Finding(
                self.name, fi.rel, first_line, f"{cls.name}.{attr}",
                f"self.{attr} is written under a lock but accessed bare in "
                f"{methods_str} — take the lock, rename the helper "
                f"*_locked, or allowlist with the invariant that makes the "
                f"bare access safe",
                key=(fi.rel, f"{cls.name}.{attr}"))
