"""Thread-hygiene checker: no fire-and-forget non-daemon threads.

A ``threading.Thread`` that is neither daemonized nor joined outlives
shutdown: the kubelet's signal handler returns, ``main()`` exits, and the
interpreter hangs waiting on a worker nobody will stop — or worse, the
thread keeps mutating state during teardown (the chaos soaks' zombie
class). The discipline is mechanical:

- ``daemon=True`` at construction, or
- a discoverable join/close path: a ``.join(`` call somewhere in the same
  class (for ``self._thread``-style members, usually in ``stop()``/
  ``close()``) or — for module-level/local threads — in the same
  function or module.

Anything else is a finding, allowlisted by (file, enclosing function)
with the reason the thread's lifetime is actually bounded.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..core import Checker, Finding
from ..index import PackageIndex


def _is_thread_ctor(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return True
    if isinstance(f, ast.Name) and f.id == "Thread":
        return True
    return False


def _daemon_kwarg(node: ast.Call) -> Optional[bool]:
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return None


class ThreadHygieneChecker(Checker):
    name = "thread-hygiene"
    description = ("threading.Thread creations must be daemon=True or have "
                   "a join/close path in the same scope")

    # (file, enclosing function) -> why the thread's lifetime is bounded.
    allowlist: dict = {}

    def collect(self, index: PackageIndex) -> Iterable[Finding]:
        for fi in index.files():
            # class spans, so "a join exists in the same class" is cheap
            class_spans = [(s.start, s.end) for s in fi.scopes
                           if s.kind == "class"]
            join_lines = [n.lineno for n in ast.walk(fi.tree)
                          if isinstance(n, ast.Call)
                          and isinstance(n.func, ast.Attribute)
                          and n.func.attr == "join"
                          and not (n.args and isinstance(n.args[0],
                                                         ast.Constant)
                                   and isinstance(n.args[0].value, str))]

            def scope_has_join(lineno: int) -> bool:
                # innermost class containing the ctor; else whole module
                spans = [s for s in class_spans if s[0] <= lineno <= s[1]]
                if spans:
                    start, end = min(spans, key=lambda s: s[1] - s[0])
                else:
                    start, end = 1, len(fi.source.splitlines()) + 1
                return any(start <= j <= end for j in join_lines)

            for node in ast.walk(fi.tree):
                if not (isinstance(node, ast.Call)
                        and _is_thread_ctor(node)):
                    continue
                daemon = _daemon_kwarg(node)
                if daemon is True:
                    continue
                if scope_has_join(node.lineno):
                    continue
                func = fi.enclosing_function(node.lineno)
                yield Finding(
                    self.name, fi.rel, node.lineno, func,
                    "non-daemon Thread with no join in scope: it will "
                    "outlive shutdown (interpreter hang / teardown "
                    "mutation) — pass daemon=True or join it in "
                    "stop()/close()",
                    key=(fi.rel, func))
