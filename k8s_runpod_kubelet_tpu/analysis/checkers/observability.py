"""Observability-contract checker: names on the wire match the catalogue.

Generalizes ``tests/test_metrics_lint.py`` (metric names need describe())
and extends the same honesty contract to spans: dashboards and the
summary tools (``trace_summary``/``fleet_summary``/``goodput_summary``)
are written against the README Observability catalogue, so a span or
metric emitted under an uncatalogued name is invisible telemetry — it
exists in the ring but nobody queries it, which is how renames rot
observability one PR at a time.

Rules:

- every METRIC name passed to ``incr/set_gauge/observe/time_block/
  remove_gauge`` as a string literal must have a ``describe()`` somewhere
  in the package AND appear in README.md;
- every SPAN name passed to ``tracer.record(...)``/``tracer.span(...)``
  as a string literal must appear in README.md;
- metric/span call sites whose name is NOT a literal are findings too —
  a computed name escapes this lint, so each needs an allowlist entry
  explaining why (build variability into labels/attrs instead);
- a ``describe()`` for a name no call site emits is dead catalogue;
- a gauge set with a PER-ENTITY label (``pod``/``pod_name``/``replica``/
  ``replica_id`` in a literal labels dict) must have a
  ``remove_gauge(name)`` call somewhere in the package — the PR 5
  stalled-gauge-leak class: a labeled series for an entity that left
  (pod deleted, replica deregistered) pages someone forever unless the
  delete path drops it;
- **merged-counter discipline** (ISSUE 20): every counter the fleet
  heartbeat reads cumulative via ``get_counter(...)`` in
  ``fleet/registry.py`` must (a) appear in that module's
  ``GUARDED_HEARTBEAT_COUNTERS`` tuple — the registry-tier consumers'
  contract that a RestartGuard differences it — and (b) have a
  zero-seed ``incr(name, 0, ...)`` site somewhere in the package. A
  counter that first appears mid-flight, or whose merge side lacks a
  restart guard, fabricates fleet deltas on replica restart (the
  SLOTracker bug class this tuple exists to prevent).

Allowlist keys: ``("metric", name)`` / ``("span", name)`` for catalogue
gaps, ``("dynamic", file, func)`` for computed names,
``("undescribed", name)`` / ``("unemitted", name)`` for describe gaps,
``("leak", name)`` for per-entity gauges with no removal call,
``("merge-unguarded", name)`` / ``("merge-unseeded", name)`` /
``("merge-dead-guard", name)`` for merged-counter discipline gaps.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..core import Checker, Finding
from ..index import PackageIndex

# remove_gauge deliberately absent: dropping a phantom series is not
# emission, and the names it drops are linted at their set_gauge sites
_METRIC_METHODS = {"incr", "set_gauge", "observe", "time_block"}
_SPAN_METHODS = {"record", "span"}
# labels keys that mark a gauge series as per-entity: the entity can
# leave (pod deleted, replica deregistered), so the series needs a
# removal call or it outlives its referent
_ENTITY_LABEL_KEYS = {"pod", "pod_name", "replica", "replica_id"}


def _first_arg_literal(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _recv_text(func: ast.Attribute) -> str:
    """Receiver spelling: 'self.metrics', 'tracer', 'self.m'."""
    return ast.unparse(func.value)


def _is_metrics_recv(recv: str) -> bool:
    # mirrors test_metrics_lint's rule: the receiver must *end* in
    # "metrics" so registry-internal plumbing (_Timer's self.m.observe)
    # stays exempt from the dynamic-name rule
    return recv.endswith("metrics") or recv == "m"


def _is_tracer_recv(recv: str) -> bool:
    return recv.endswith(("tracer", "tr"))


def _labels_dict(node: ast.Call) -> Optional[ast.Dict]:
    """The labels argument of a gauge call, when it is a LITERAL dict
    (keyword ``labels=...`` or the third positional). A labels variable
    returns None — the leak rule only judges what it can see."""
    for kw in node.keywords:
        if kw.arg == "labels" and isinstance(kw.value, ast.Dict):
            return kw.value
    if len(node.args) >= 3 and isinstance(node.args[2], ast.Dict):
        return node.args[2]
    return None


def _entity_labeled(node: ast.Call) -> bool:
    d = _labels_dict(node)
    if d is None:
        return False
    return any(isinstance(k, ast.Constant) and k.value in _ENTITY_LABEL_KEYS
               for k in d.keys)


def _is_zero_seed(node: ast.Call) -> bool:
    """incr(name, 0, ...) — the scrape-from-zero discipline."""
    return (node.func.attr == "incr" and len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and node.args[1].value == 0)


def _guarded_tuple(tree) -> Optional[set]:
    """The GUARDED_HEARTBEAT_COUNTERS module constant as a set of
    names (None when the module doesn't define it)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "GUARDED_HEARTBEAT_COUNTERS"
                        for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return {e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return None


def _removal_names(tree) -> set:
    """Gauge names some remove_gauge call drops: literal first args,
    plus every string in a constant tuple/list a for-loop iterates when
    the loop body calls remove_gauge (training_watch's
    _clear_training_gauges idiom)."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "remove_gauge":
            name = _first_arg_literal(node)
            if name is not None:
                out.add(name)
        elif isinstance(node, ast.For) \
                and isinstance(node.iter, (ast.Tuple, ast.List)):
            if any(isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Attribute)
                   and n.func.attr == "remove_gauge"
                   for n in ast.walk(node)):
                out.update(e.value for e in node.iter.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, str))
    return out


class ObservabilityChecker(Checker):
    name = "observability"
    description = ("every emitted metric/span name is described and "
                   "catalogued in the README Observability section")

    allowlist = {
        ("dynamic", "workloads/telemetry.py", "__exit__"):
            "_CheckpointTimer.__exit__ picks between exactly two literals "
            "four lines above ('training.checkpoint' save / "
            "'training.restore' restore), both in the README catalogue; "
            "splitting the record() call per branch would duplicate the "
            "attrs/trace plumbing for no new information",
    }

    def collect(self, index: PackageIndex) -> Iterable[Finding]:
        readme = index.resource("README.md")
        used_metrics: dict[str, tuple[str, int, str]] = {}
        described: dict[str, tuple[str, int, str]] = {}
        used_spans: dict[str, tuple[str, int, str]] = {}
        entity_gauges: dict[str, tuple[str, int, str]] = {}
        removal_names: set = set()
        zero_seeded: set = set()
        merged_counters: dict[str, tuple[str, int, str]] = {}
        guarded: Optional[set] = None

        for fi in index.files():
            if fi.rel.startswith("analysis/"):
                continue  # the lint's own name tables are not telemetry
            removal_names |= _removal_names(fi.tree)
            if fi.rel == "fleet/registry.py":
                guarded = _guarded_tuple(fi.tree)
            # tracing.py's Span.__exit__ records self.name — registry
            # plumbing, like metrics' _Timer; the literal names live at
            # the tracer.span(...) call sites, which ARE collected
            is_tracing_internals = fi.rel == "tracing.py"
            for node in ast.walk(fi.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                attr = node.func.attr
                recv = _recv_text(node.func)
                site = (fi.rel, node.lineno,
                        fi.enclosing_function(node.lineno))
                if attr == "get_counter" and fi.rel == "fleet/registry.py":
                    # a cumulative read the heartbeat ships for
                    # registry-tier differencing — the merged-counter
                    # discipline's input set
                    name = _first_arg_literal(node)
                    if name is not None:
                        merged_counters.setdefault(name, site)
                if attr in _METRIC_METHODS:
                    name = _first_arg_literal(node)
                    if name is not None:
                        used_metrics.setdefault(name, site)
                        if _is_zero_seed(node):
                            zero_seeded.add(name)
                        if attr == "set_gauge" and _entity_labeled(node):
                            entity_gauges.setdefault(name, site)
                    elif node.args and _is_metrics_recv(recv):
                        yield Finding(
                            self.name, fi.rel, node.lineno, site[2],
                            f"dynamic metric name in .{attr}(...) — a "
                            f"computed name escapes this lint; put the "
                            f"variability in labels, or allowlist with the "
                            f"reason the name set is closed",
                            key=("dynamic", fi.rel, site[2]))
                elif attr == "describe" and _is_metrics_recv(recv):
                    name = _first_arg_literal(node)
                    if name is not None:
                        described.setdefault(name, site)
                elif attr in _SPAN_METHODS and _is_tracer_recv(recv) \
                        and not is_tracing_internals:
                    name = _first_arg_literal(node)
                    if name is not None:
                        used_spans.setdefault(name, site)
                    elif node.args:
                        yield Finding(
                            self.name, fi.rel, node.lineno, site[2],
                            f"dynamic span name in .{attr}(...) — record a "
                            f"literal in each branch (or allowlist with the "
                            f"reason the name set is closed and catalogued)",
                            key=("dynamic", fi.rel, site[2]))

        for name, (rel, line, func) in sorted(used_metrics.items()):
            if name not in described:
                yield Finding(
                    self.name, rel, line, func,
                    f"metric {name!r} emitted without a describe() HELP "
                    f"entry — scrapers see an untyped, undocumented family",
                    key=("undescribed", name))
            if readme is not None and name not in readme:
                yield Finding(
                    self.name, rel, line, func,
                    f"metric {name!r} missing from the README Observability "
                    f"catalogue — invisible telemetry nobody dashboards",
                    key=("metric", name))
        for name, (rel, line, func) in sorted(described.items()):
            if name not in used_metrics:
                yield Finding(
                    self.name, rel, line, func,
                    f"describe({name!r}) but no call site ever emits it — "
                    f"dead catalogue entry (renamed metric?)",
                    key=("unemitted", name))
        for name, (rel, line, func) in sorted(entity_gauges.items()):
            if name not in removal_names:
                yield Finding(
                    self.name, rel, line, func,
                    f"gauge {name!r} is set with a per-entity label "
                    f"({'/'.join(sorted(_ENTITY_LABEL_KEYS))}) but no "
                    f"remove_gauge({name!r}) exists anywhere — the series "
                    f"outlives its entity (the stalled-gauge-leak class): "
                    f"drop it from the delete/deregister path",
                    key=("leak", name))
        for name, (rel, line, func) in sorted(merged_counters.items()):
            if guarded is not None and name not in guarded:
                yield Finding(
                    self.name, rel, line, func,
                    f"heartbeat reads counter {name!r} cumulative but it "
                    f"is not in GUARDED_HEARTBEAT_COUNTERS — the registry "
                    f"tier differences these per beat, and an unguarded "
                    f"merge fabricates fleet deltas on replica restart: "
                    f"add it to the tuple (and RestartGuard the consumer)",
                    key=("merge-unguarded", name))
            if name not in zero_seeded:
                yield Finding(
                    self.name, rel, line, func,
                    f"heartbeat-merged counter {name!r} has no zero-seed "
                    f"incr({name!r}, 0, ...) site — a series first "
                    f"appearing mid-flight reads as a restart to the "
                    f"merge guards: seed it where it is described",
                    key=("merge-unseeded", name))
        if guarded:
            for name in sorted(guarded - set(merged_counters)):
                site = merged_counters.get(name) or ("fleet/registry.py",
                                                     1, "<module>")
                yield Finding(
                    self.name, site[0], site[1], site[2],
                    f"GUARDED_HEARTBEAT_COUNTERS lists {name!r} but no "
                    f"get_counter({name!r}) read exists in the heartbeat "
                    f"path — dead guard entry (renamed counter?)",
                    key=("merge-dead-guard", name))
        for name, (rel, line, func) in sorted(used_spans.items()):
            if readme is not None and name not in readme:
                yield Finding(
                    self.name, rel, line, func,
                    f"span {name!r} missing from the README Observability "
                    f"catalogue — trace consumers can't know to query it",
                    key=("span", name))

        if readme is None and len(index) > 20:
            # real-package run without the README resource: the catalogue
            # dimension silently passing would defeat the checker
            yield Finding(
                self.name, "", 1, "README.md",
                "README.md not indexed — run from the repo root (or pass "
                "--repo-root) so the catalogue checks actually run",
                key=("resource", "README.md"))
