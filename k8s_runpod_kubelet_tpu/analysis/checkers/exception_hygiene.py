"""Exception-hygiene checker: no silent broad excepts.

The framework port of ``tests/test_exception_hygiene.py`` (ISSUE 3
satellite) — same rule, same allowlist, one shared parse. Chaos bugs hide
inside ``except Exception: pass``; every broad handler (bare ``except``,
``Exception``, ``BaseException``) must do SOMETHING visible with the
failure:

- re-raise, or
- call a logger (``log.exception``/``error``/``warning`` preferred;
  ``info``/``debug`` accepted where a comment justifies the downgrade —
  the lint cares about silence, not volume), or
- USE the bound exception value (``except ... as e`` with ``e`` read in
  the body: folding the error into a response/result/error-list is
  handling, not swallowing).

True silent swallows are allowlisted by (file, enclosing function) with a
justification — adding one is a conscious, reviewed act, not an accident.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Checker, Finding
from ..index import PackageIndex

_LOG_METHODS = {"exception", "error", "warning", "info", "debug", "log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles(handler: ast.ExceptHandler) -> bool:
    bound = handler.name  # "e" in `except Exception as e`
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _LOG_METHODS:
                return True
        if bound and isinstance(node, ast.Name) and node.id == bound \
                and isinstance(node.ctx, ast.Load):
            return True  # the error value flows somewhere visible
    return False


class ExceptionHygieneChecker(Checker):
    name = "exception-hygiene"
    description = ("broad except blocks must re-raise, log, or use the "
                   "caught error — silent swallows are how chaos bugs hide")

    # (file, enclosing function) -> why a silent swallow is correct THERE.
    allowlist = {
        ("gang/exec.py", "remote_kill"):
            "best-effort disconnect-kill cleanup: worker gone / process "
            "exited",
        ("workloads/serving/scheduler.py", "_fail_future"):
            "racing future.cancel(); the future already carries a result",
        ("workloads/serving/engine.py", "_complete"):
            "future already resolved elsewhere; nothing to report",
        ("workloads/serve_main.py", "_triage_overflow"):
            "metrics bump around a raw-socket 503 must never block the "
            "reject",
        ("ops/attention.py", "_generation"):
            "backend not initialized; documented fallback to cpu kernels",
        ("logging_util.py", "_drain"):
            "the error sink must never raise; drops are counted "
            "(self.dropped)",
    }

    def collect(self, index: PackageIndex) -> Iterable[Finding]:
        for fi in index.files():
            for node in ast.walk(fi.tree):
                if not isinstance(node, ast.ExceptHandler) \
                        or not _is_broad(node):
                    continue
                if _handles(node):
                    continue
                func = fi.enclosing_function(node.lineno)
                yield Finding(
                    self.name, fi.rel, node.lineno, func,
                    "broad except that neither re-raises, nor logs, nor "
                    "uses the caught error — surface the failure or "
                    "(rarely, with justification) allowlist it",
                    key=(fi.rel, func))
