"""Determinism checker: no raw wall-clock / RNG calls in the control plane.

All six soak suites (chaos, fleet, elastic, straggler, serving-stress,
admission-race) depend on injected FakeClock/seeded-RNG determinism: one
raw ``time.time()`` buried in a control-plane module silently turns a
reproducible soak into a flaky one (the PR 3–6 review passes each caught
at least one). This checker mechanizes the rule:

- banned in scoped modules: calls to ``time.time/time_ns/monotonic/
  monotonic_ns/perf_counter/perf_counter_ns/sleep``, ``datetime.now/
  utcnow/today``, ``date.today``, and module-level ``random.*`` draws
  (``random.Random(seed)``/``SystemRandom`` CONSTRUCTION is fine — building
  an injectable rng is the seam, drawing from the shared global is not);
- allowed seams: the lazy-default idiom where the raw call only fires when
  an injected parameter was omitted —
  ``now = time.time() if now is None else now``,
  ``if clock is None: clock = time.time()``, ``p = p or time.time()`` —
  keeps the production default while tests inject;
- everything else is a finding, fixable by threading a ``clock``/``rng``
  parameter (constructor default-arg seam, the repo-wide idiom) or
  allowlisted by (file, function) with a written reason.

Scope: control-plane and fleet modules (cloud/fleet/node/provider/kube/
gang + the shared infra files) plus the serving stack the fleet soaks
drive. The ML tier (models/ops/parallel/training mains) measures real
wall time by design and stays out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Checker, Finding
from ..index import PackageIndex

SCOPED_DIRS = ("cloud/", "fleet/", "node/", "provider/", "kube/", "gang/",
               "workloads/serving/")
SCOPED_FILES = {
    "config.py", "health.py", "tracing.py", "metrics.py", "logging_util.py",
    "workloads/serve_main.py", "workloads/telemetry.py",
}

_TIME_BANNED = {"time", "time_ns", "monotonic", "monotonic_ns",
                "perf_counter", "perf_counter_ns", "sleep"}
_DATETIME_BANNED = {"now", "utcnow", "today"}
_RANDOM_OK = {"Random", "SystemRandom"}


def in_scope(rel: str) -> bool:
    return rel in SCOPED_FILES or rel.startswith(SCOPED_DIRS)


def _module_aliases(tree: ast.Module) -> dict[str, str]:
    """Local name -> stdlib module for ``import time [as _time]`` and the
    ``from datetime import datetime`` / ``from time import time`` forms
    (the latter mapped to pseudo-module ``time.time`` handled below)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("time", "random", "datetime"):
                    aliases[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module == "datetime":
            for a in node.names:
                if a.name in ("datetime", "date"):
                    aliases[a.asname or a.name] = f"datetime.{a.name}"
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _TIME_BANNED:
                    aliases[a.asname or a.name] = f"time.{a.name}"
    return aliases


def _banned_call(node: ast.Call, aliases: dict[str, str]) -> str | None:
    """Dotted name of a banned call, or None."""
    f = node.func
    if isinstance(f, ast.Name):  # from time import sleep; sleep(...)
        target = aliases.get(f.id, "")
        if target.startswith("time."):
            return target
        return None
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if isinstance(recv, ast.Name):
        mod = aliases.get(recv.id)
        if mod == "time" and f.attr in _TIME_BANNED:
            return f"time.{f.attr}"
        if mod == "random" and f.attr not in _RANDOM_OK:
            return f"random.{f.attr}"
        if mod in ("datetime.datetime", "datetime.date") \
                and f.attr in _DATETIME_BANNED:
            return f"{mod}.{f.attr}"
        return None
    # datetime.datetime.now(...) — Attribute(Attribute(Name))
    if isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name):
        if aliases.get(recv.value.id) == "datetime" \
                and recv.attr in ("datetime", "date") \
                and f.attr in _DATETIME_BANNED:
            return f"datetime.{recv.attr}.{f.attr}"
    return None


def _is_param_none_test(test: ast.expr, params: set[str]) -> bool:
    return (isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id in params
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.IsNot))
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None)


def _seam_lines(func: ast.AST) -> set[int]:
    """Line numbers covered by a lazy-default seam inside ``func``: an
    IfExp / if-statement / ``or`` fallback keyed on a parameter being
    None (or falsy), where the raw call is the documented default for an
    omitted injection."""
    if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set()
    params = {a.arg for a in (func.args.args + func.args.kwonlyargs
                              + func.args.posonlyargs)}
    lines: set[int] = set()

    def cover(node: ast.AST):
        for n in ast.walk(node):
            if hasattr(n, "lineno"):
                lines.add(n.lineno)

    for node in ast.walk(func):
        if isinstance(node, ast.IfExp) and _is_param_none_test(node.test, params):
            cover(node)  # cover both arms; only one holds the raw call
        elif isinstance(node, ast.If) and _is_param_none_test(node.test, params):
            cover(node)
        elif isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or) \
                and any(isinstance(v, ast.Name) and v.id in params
                        for v in node.values[:-1]):
            cover(node)
    return lines


class DeterminismChecker(Checker):
    name = "determinism"
    description = ("raw time/random calls in control-plane and fleet modules "
                   "break injected-clock soak determinism")

    # (file, enclosing function) -> why a raw call is correct THERE.
    allowlist: dict = {}

    def collect(self, index: PackageIndex) -> Iterable[Finding]:
        for fi in index.files():
            if not in_scope(fi.rel):
                continue
            aliases = _module_aliases(fi.tree)
            if not aliases:
                continue
            seam_cache: dict[int, set[int]] = {}
            for node in ast.walk(fi.tree):
                if not isinstance(node, ast.Call):
                    continue
                banned = _banned_call(node, aliases)
                if banned is None:
                    continue
                func_node = fi.enclosing_function_node(node.lineno)
                if func_node is not None:
                    key = id(func_node)
                    if key not in seam_cache:
                        seam_cache[key] = _seam_lines(func_node)
                    if node.lineno in seam_cache[key]:
                        continue  # lazy-default seam for an injected param
                func = fi.enclosing_function(node.lineno)
                yield Finding(
                    self.name, fi.rel, node.lineno, func,
                    f"raw {banned}() call: thread an injected clock/rng "
                    f"through (constructor default-arg seam) so soak tests "
                    f"stay deterministic",
                    key=(fi.rel, func))
