"""The graftlint checker registry.

Each checker mechanizes a bug class a real review pass kept re-finding;
the module docstrings cite the motivating PR. Add new checkers here and
they ride the shared single-parse index automatically — both under
``python -m k8s_runpod_kubelet_tpu.analysis`` and the tier-1 pytest gate
(``tests/test_static_analysis.py``).
"""

from .config_plumbing import ConfigPlumbingChecker
from .determinism import DeterminismChecker
from .exception_hygiene import ExceptionHygieneChecker
from .lock_discipline import LockDisciplineChecker
from .observability import ObservabilityChecker
from .thread_hygiene import ThreadHygieneChecker

ALL_CHECKERS = (
    DeterminismChecker,
    LockDisciplineChecker,
    ConfigPlumbingChecker,
    ObservabilityChecker,
    ThreadHygieneChecker,
    ExceptionHygieneChecker,
)

__all__ = ["ALL_CHECKERS", "ConfigPlumbingChecker", "DeterminismChecker",
           "ExceptionHygieneChecker", "LockDisciplineChecker",
           "ObservabilityChecker", "ThreadHygieneChecker"]
