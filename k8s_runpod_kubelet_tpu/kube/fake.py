"""In-memory fake Kubernetes API for hermetic tests.

Analog of client-go's fake.NewSimpleClientset (used by the reference's tests,
annotations_test.go:38) — but with watch streams and graceful-deletion semantics
so the L3' controllers and the full reconcile loop can run against it, which the
reference never achieved hermetically (SURVEY.md §4).

Graceful delete mimics the API server: DELETE with grace>0 (or default) sets
deletionTimestamp and emits MODIFIED — the object stays until a grace-0 delete
(what ForceDeletePod issues) actually removes it and emits DELETED.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Iterator, Optional

from .client import KubeApiError, KubeClient, WatchEvent
from . import objects as ko


class _Watcher:
    def __init__(self, field_selector: str, label_selector: str,
                 stop: Optional[threading.Event]):
        self.q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self.field_selector = field_selector
        self.label_selector = label_selector
        self.stop = stop or threading.Event()


class FakeKubeClient(KubeClient):
    def __init__(self):
        self.lock = threading.RLock()
        self.store: dict[tuple[str, str, str], dict] = {}
        self.events: list[dict] = []
        self._rv = 0
        self._watchers: list[_Watcher] = []
        # secret/configmap change watchers (the informer analog)
        self._obj_watchers: dict[str, list[_Watcher]] = {}
        # pod watch history for resourceVersion resume: (rv, type, snapshot)
        self._pod_history: list[tuple[int, str, dict]] = []
        self._compacted_rv = 0  # RVs <= this are gone (watch from them -> 410)
        # fault injection
        self.fail_next: dict[str, KubeApiError] = {}  # op name -> error (one-shot)

    # -- internals -------------------------------------------------------------

    def _maybe_fail(self, op: str):
        err = self.fail_next.pop(op, None)
        if err:
            raise err

    def _bump(self, obj: dict) -> dict:
        self._rv += 1
        ko.meta(obj)["resourceVersion"] = str(self._rv)
        return obj

    def _key(self, kind: str, obj: dict) -> tuple[str, str, str]:
        return (kind, ko.namespace(obj), ko.name(obj))

    def _get(self, kind: str, ns: str, name: str) -> dict:
        try:
            return self.store[(kind, ns, name)]
        except KeyError:
            raise KubeApiError(f"{kind} {ns}/{name} not found", status=404) from None

    def _create(self, kind: str, obj: dict) -> dict:
        key = self._key(kind, obj)
        if key in self.store:
            raise KubeApiError(f"{kind} {key[1]}/{key[2]} already exists", status=409)
        m = ko.meta(obj)
        m.setdefault("uid", str(uuid.uuid4()))
        m.setdefault("namespace", key[1])
        m.setdefault("creationTimestamp", ko.now_iso())
        self._bump(obj)
        self.store[key] = obj
        return ko.deep_copy(obj)

    def _notify(self, ev_type: str, pod: dict):
        """Caller holds self.lock (every mutator notifies inside its
        critical section, so history order == resourceVersion order)."""
        snapshot = ko.deep_copy(pod)
        rv = int(ko.meta(snapshot).get("resourceVersion", "0") or 0)
        self._pod_history.append((rv, ev_type, snapshot))
        for w in list(self._watchers):
            if w.stop.is_set():
                self._watchers.remove(w)
                continue
            if (ko.match_field_selector(snapshot, w.field_selector)
                    and ko.match_label_selector(snapshot, w.label_selector)):
                w.q.put(WatchEvent(type=ev_type, object=ko.deep_copy(snapshot)))

    # -- watch fault injection (for continuity tests) --------------------------

    def drop_watches(self):
        """Terminate every open watch stream, as the API server does every few
        minutes. Events emitted afterwards land only in the history, so a
        correct client must resume from its last-seen resourceVersion."""
        with self.lock:
            for w in self._watchers:
                w.q.put(None)
            self._watchers.clear()

    def compact(self, up_to_rv: Optional[int] = None):
        """Forget watch history up to ``up_to_rv`` (default: everything so
        far) — a resume from a compacted RV gets 410 Gone, like etcd."""
        with self.lock:
            self._compacted_rv = self._rv if up_to_rv is None else up_to_rv
            self._pod_history = [h for h in self._pod_history
                                 if h[0] > self._compacted_rv]

    # -- pods ------------------------------------------------------------------

    def get_pod(self, ns, name):
        with self.lock:
            self._maybe_fail("get_pod")
            return ko.deep_copy(self._get("pods", ns, name))

    def list_pods(self, ns=None, field_selector="", label_selector=""):
        return self.list_pods_rv(ns, field_selector, label_selector)[0]

    def list_pods_rv(self, ns=None, field_selector="", label_selector=""):
        with self.lock:
            self._maybe_fail("list_pods")
            out = []
            for (kind, ons, _), obj in self.store.items():
                if kind != "pods" or (ns and ons != ns):
                    continue
                if (ko.match_field_selector(obj, field_selector)
                        and ko.match_label_selector(obj, label_selector)):
                    out.append(ko.deep_copy(obj))
            return out, str(self._rv)

    def create_pod(self, pod):
        with self.lock:
            self._maybe_fail("create_pod")
            created = self._create("pods", pod)
            self._notify("ADDED", created)
            return created

    def update_pod(self, pod):
        with self.lock:
            self._maybe_fail("update_pod")
            key = self._key("pods", pod)
            if key not in self.store:
                raise KubeApiError(f"pod {key[1]}/{key[2]} not found", status=404)
            self._bump(pod)
            self.store[key] = ko.deep_copy(pod)
            self._notify("MODIFIED", pod)
            return ko.deep_copy(pod)

    def patch_pod(self, ns, name, patch):
        with self.lock:
            self._maybe_fail("patch_pod")
            obj = self._get("pods", ns, name)
            ko.merge_patch(obj, patch)
            self._bump(obj)
            self._notify("MODIFIED", obj)
            return ko.deep_copy(obj)

    def patch_pod_status(self, ns, name, patch):
        with self.lock:
            self._maybe_fail("patch_pod_status")
            obj = self._get("pods", ns, name)
            ko.merge_patch(obj.setdefault("status", {}), patch.get("status", patch))
            self._bump(obj)
            self._notify("MODIFIED", obj)
            return ko.deep_copy(obj)

    def delete_pod(self, ns, name, grace_period_s=None):
        with self.lock:
            self._maybe_fail("delete_pod")
            try:
                obj = self._get("pods", ns, name)
            except KubeApiError:
                return
            if grace_period_s == 0:
                self._bump(obj)  # deletes advance the RV, as in the real API
                del self.store[("pods", ns, name)]
                self._notify("DELETED", obj)
            else:
                ko.meta(obj)["deletionTimestamp"] = ko.now_iso()
                ko.meta(obj)["deletionGracePeriodSeconds"] = grace_period_s or 30
                self._bump(obj)
                self._notify("MODIFIED", obj)

    def watch_pods(self, field_selector="", label_selector="", stop=None,
                   resource_version=None) -> Iterator[WatchEvent]:
        w = _Watcher(field_selector, label_selector, stop)
        with self.lock:
            if resource_version is None:
                # fresh watch: initial ADDED burst (resourceVersion=0 style)
                for (kind, _, _), obj in self.store.items():
                    if kind == "pods" and ko.match_field_selector(obj, field_selector) \
                            and ko.match_label_selector(obj, label_selector):
                        w.q.put(WatchEvent(type="ADDED", object=ko.deep_copy(obj)))
            else:
                rv = int(resource_version or 0)
                if rv < self._compacted_rv:
                    raise KubeApiError(
                        f"too old resource version: {rv} (compacted to "
                        f"{self._compacted_rv})", status=410)
                # replay everything after the resume point, then go live
                for erv, et, obj in self._pod_history:
                    if erv > rv and ko.match_field_selector(obj, field_selector) \
                            and ko.match_label_selector(obj, label_selector):
                        w.q.put(WatchEvent(type=et, object=ko.deep_copy(obj)))
            self._watchers.append(w)

        def gen():
            while not w.stop.is_set():
                try:
                    ev = w.q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if ev is None:
                    return
                yield ev
        return gen()

    # -- secrets / configmaps / jobs -------------------------------------------

    def _put_object(self, kind: str, ns: str, name: str, obj: dict):
        """Upsert + notify object watchers (the informer analog)."""
        with self.lock:
            ev = "MODIFIED" if (kind, ns, name) in self.store else "ADDED"
            self.store[(kind, ns, name)] = self._bump(obj)
            for w in list(self._obj_watchers.get(kind, [])):
                if w.stop.is_set():
                    self._obj_watchers[kind].remove(w)
                    continue
                w.q.put(WatchEvent(type=ev, object=ko.deep_copy(obj)))

    def add_secret(self, ns: str, name: str, data: dict[str, str]):
        """Test helper; ``data`` values are plain strings (stored base64 like
        K8s). Re-adding an existing name = a rotation (MODIFIED event)."""
        import base64
        enc = {k: base64.b64encode(v.encode()).decode() for k, v in data.items()}
        self._put_object("secrets", ns, name, {
            "metadata": {"name": name, "namespace": ns}, "data": enc})

    def get_secret(self, ns, name):
        with self.lock:
            self._maybe_fail("get_secret")
            return ko.deep_copy(self._get("secrets", ns, name))

    def add_config_map(self, ns: str, name: str, data: dict[str, str]):
        """Test helper; configmap data is plain strings (no base64)."""
        self._put_object("configmaps", ns, name, {
            "metadata": {"name": name, "namespace": ns}, "data": dict(data)})

    def get_config_map(self, ns, name):
        with self.lock:
            self._maybe_fail("get_config_map")
            return ko.deep_copy(self._get("configmaps", ns, name))

    def watch_objects(self, kind, stop=None, resource_version=None):
        if kind not in ("secrets", "configmaps"):
            raise KubeApiError(f"unsupported watch kind {kind!r}", status=400)
        w = _Watcher("", "", stop)
        with self.lock:
            self._obj_watchers.setdefault(kind, []).append(w)

        def gen():
            try:
                while not w.stop.is_set():
                    try:
                        ev = w.q.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if ev is None:
                        return
                    yield ev
            finally:
                with self.lock:
                    if w in self._obj_watchers.get(kind, []):
                        self._obj_watchers[kind].remove(w)
        return gen()

    def add_job(self, job: dict):
        with self.lock:
            self._create("jobs", job)

    def get_job(self, ns, name):
        with self.lock:
            self._maybe_fail("get_job")
            return ko.deep_copy(self._get("jobs", ns, name))

    # -- nodes / leases --------------------------------------------------------

    def get_node(self, name):
        with self.lock:
            self._maybe_fail("get_node")
            return ko.deep_copy(self._get("nodes", "", name))

    def create_node(self, node):
        with self.lock:
            self._maybe_fail("create_node")
            ko.meta(node)["namespace"] = ""
            return self._create("nodes", node)

    def update_node(self, node):
        with self.lock:
            self._maybe_fail("update_node")
            key = ("nodes", "", ko.name(node))
            if key not in self.store:
                raise KubeApiError(f"node {ko.name(node)} not found", status=404)
            self._bump(node)
            self.store[key] = ko.deep_copy(node)
            return ko.deep_copy(node)

    def patch_node_status(self, name, patch):
        with self.lock:
            self._maybe_fail("patch_node_status")
            obj = self._get("nodes", "", name)
            ko.merge_patch(obj.setdefault("status", {}), patch.get("status", patch))
            self._bump(obj)
            return ko.deep_copy(obj)

    def get_lease(self, name):
        with self.lock:
            return ko.deep_copy(self._get("leases", "kube-node-lease", name))

    def create_lease(self, lease):
        with self.lock:
            ko.meta(lease)["namespace"] = "kube-node-lease"
            return self._create("leases", lease)

    def update_lease(self, lease):
        with self.lock:
            key = ("leases", "kube-node-lease", ko.name(lease))
            self._bump(lease)
            self.store[key] = ko.deep_copy(lease)
            return ko.deep_copy(lease)

    # -- events ----------------------------------------------------------------

    def create_event(self, ns, event):
        with self.lock:
            event.setdefault("metadata", {}).setdefault("namespace", ns)
            self.events.append(ko.deep_copy(event))
            return event
