"""Helpers over plain-dict Kubernetes objects.

We deliberately model K8s objects as the JSON dicts the API serves (the Python
idiom for untyped clients), with accessor helpers instead of a generated type
tree. Field paths mirror what the reference touches via client-go typed structs.
"""

from __future__ import annotations

import copy
import time
from typing import Any, Optional


def meta(obj: dict) -> dict:
    return obj.setdefault("metadata", {})


def name(obj: dict) -> str:
    return meta(obj).get("name", "")


def namespace(obj: dict) -> str:
    return meta(obj).get("namespace", "default")


def uid(obj: dict) -> str:
    return meta(obj).get("uid", "")


def namespaced_name(obj: dict) -> str:
    return f"{namespace(obj)}/{name(obj)}"


def annotations(obj: dict) -> dict[str, str]:
    return meta(obj).setdefault("annotations", {})


def labels(obj: dict) -> dict[str, str]:
    return meta(obj).setdefault("labels", {})


def owner_references(obj: dict) -> list[dict]:
    return meta(obj).get("ownerReferences", [])


def node_name(pod: dict) -> str:
    return pod.get("spec", {}).get("nodeName", "")


def containers(pod: dict) -> list[dict]:
    return pod.get("spec", {}).get("containers", [])


def phase(pod: dict) -> str:
    return pod.get("status", {}).get("phase", "")


def deletion_timestamp(obj: dict) -> Optional[str]:
    return meta(obj).get("deletionTimestamp")


def is_terminal(pod: dict) -> bool:
    return phase(pod) in ("Succeeded", "Failed")


def pod_references_object(pod: dict, kind: str, name: str) -> bool:
    """Does this pod's spec consume secret/configmap ``name``?
    (env valueFrom refs, envFrom refs, and volumes — the same surfaces
    translate.extract_env resolves.) ``kind``: "secrets" | "configmaps"."""
    secret = kind == "secrets"
    from_key, val_key = (("secretRef", "secretKeyRef") if secret
                         else ("configMapRef", "configMapKeyRef"))
    for c in containers(pod):
        for ef in c.get("envFrom", []):
            if ef.get(from_key, {}).get("name") == name:
                return True
        for e in c.get("env", []):
            if e.get("valueFrom", {}).get(val_key, {}).get("name") == name:
                return True
    for vol in pod.get("spec", {}).get("volumes", []):
        if secret and vol.get("secret", {}).get("secretName") == name:
            return True
        if not secret and vol.get("configMap", {}).get("name") == name:
            return True
    return False


def now_iso(ts: Optional[float] = None) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts if ts is not None else time.time()))


def deep_copy(obj: dict) -> dict:
    return copy.deepcopy(obj)


def tpu_chips_requested(pod: dict) -> int:
    """Sum of ``google.com/tpu`` limits across containers.

    The reference never reads the pod's nvidia.com/gpu request at deploy time
    (SURVEY.md §2.4 'multi-host orchestration' row) — this fixes that: the chip
    count drives slice selection.
    """
    total = 0
    for c in containers(pod):
        res = c.get("resources", {})
        for src in ("limits", "requests"):
            v = res.get(src, {}).get("google.com/tpu")
            if v is not None:
                total += int(str(v))
                break
    return total


def merge_patch(obj: dict, patch: dict) -> dict:
    """RFC 7386 JSON merge patch, applied in place (None deletes a key)."""
    for k, v in patch.items():
        if v is None:
            obj.pop(k, None)
        elif isinstance(v, dict) and isinstance(obj.get(k), dict):
            merge_patch(obj[k], v)
        else:
            obj[k] = copy.deepcopy(v)
    return obj


def match_field_selector(obj: dict, selector: str) -> bool:
    """Supports the subset the kubelet uses: ``spec.nodeName=X`` and
    ``metadata.name=X`` / ``metadata.namespace=X``, comma-separated, with ``!=``.
    (Parity: the reference scopes its pod informer with a spec.nodeName field
    selector, main.go:153.)"""
    if not selector:
        return True
    for clause in selector.split(","):
        if "!=" in clause:
            path, want = clause.split("!=", 1)
            negate = True
        else:
            path, want = clause.split("=", 1)
            negate = False
        cur: Any = obj
        for part in path.strip().split("."):
            cur = cur.get(part, {}) if isinstance(cur, dict) else None
        got = cur if isinstance(cur, str) else ""
        if negate == (got == want):
            return False
    return True


def match_label_selector(obj: dict, selector: str) -> bool:
    if not selector:
        return True
    lbls = meta(obj).get("labels", {})
    for clause in selector.split(","):
        if "!=" in clause:
            k, v = clause.split("!=", 1)
            if lbls.get(k.strip()) == v.strip():
                return False
        elif "=" in clause:
            k, v = clause.split("=", 1)
            if lbls.get(k.strip()) != v.strip():
                return False
        else:
            if clause.strip() not in lbls:
                return False
    return True
