"""Minimal Kubernetes API layer.

The reference gets client-go + informers for free; the `kubernetes` Python package
is not available in this image, so this is a from-scratch, stdlib-only client:

- ``objects``: helpers over plain-dict K8s objects (pods/nodes/leases/events).
- ``client``:  KubeClient protocol + RealKubeClient (in-cluster or kubeconfig,
  JSON over HTTP, streaming watch).
- ``fake``:    FakeKubeClient — in-memory API server double with resourceVersions,
  watch streams and field selectors, the analog of client-go's
  fake.NewSimpleClientset used by the reference's tests (annotations_test.go:38).
"""

from .client import KubeApiError, KubeClient, RealKubeClient, WatchEvent
from .fake import FakeKubeClient

__all__ = ["KubeApiError", "KubeClient", "RealKubeClient", "WatchEvent", "FakeKubeClient"]
