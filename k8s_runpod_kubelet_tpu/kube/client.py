"""Kubernetes API client protocol + real HTTP implementation.

Replaces client-go for the slice of the API the kubelet needs. The method set is
exactly what the reference's provider calls through client-go (SURVEY.md §2 rows
5-9,11: pods CRUD + status patch, secrets/jobs reads, node + lease writes, events)
plus streaming watch for the L3' pod controller.

Auth mirrors the reference's createK8sClient (main.go:464-494): in-cluster service
account if present, else kubeconfig.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import os
import ssl
import threading
import time
import urllib.parse
from typing import Iterator, Optional

from ..cloud.gcp_auth import CachingTokenProvider as _CachingProvider

log = logging.getLogger(__name__)

LEASE_NAMESPACE = "kube-node-lease"


class KubeApiError(Exception):
    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status

    @property
    def is_not_found(self) -> bool:
        return self.status == 404

    @property
    def is_conflict(self) -> bool:
        return self.status == 409


@dataclasses.dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED | BOOKMARK | ERROR
    object: dict


class KubeClient:
    """Protocol implemented by RealKubeClient and FakeKubeClient."""

    # pods
    def get_pod(self, ns: str, name: str) -> dict: raise NotImplementedError
    def list_pods(self, ns: Optional[str] = None, field_selector: str = "",
                  label_selector: str = "") -> list[dict]: raise NotImplementedError
    def list_pods_rv(self, ns: Optional[str] = None, field_selector: str = "",
                     label_selector: str = "") -> tuple[list[dict], str]:
        """List plus the PodList's resourceVersion — the anchor a subsequent
        watch starts from (client-go ListWatch semantics)."""
        raise NotImplementedError
    def create_pod(self, pod: dict) -> dict: raise NotImplementedError
    def update_pod(self, pod: dict) -> dict: raise NotImplementedError
    def patch_pod(self, ns: str, name: str, patch: dict) -> dict: raise NotImplementedError
    def patch_pod_status(self, ns: str, name: str, patch: dict) -> dict: raise NotImplementedError
    def delete_pod(self, ns: str, name: str,
                   grace_period_s: Optional[int] = None) -> None: raise NotImplementedError
    def watch_pods(self, field_selector: str = "", label_selector: str = "",
                   stop: Optional[threading.Event] = None,
                   resource_version: Optional[str] = None) -> Iterator[WatchEvent]:
        """``resource_version=None`` = fresh watch (server picks the start;
        callers should list first). A set value resumes after that RV; a
        compacted/too-old RV raises KubeApiError(status=410) — relist."""
        raise NotImplementedError

    # reads the spec translator needs
    def get_secret(self, ns: str, name: str) -> dict: raise NotImplementedError
    def get_config_map(self, ns: str, name: str) -> dict: raise NotImplementedError
    def get_job(self, ns: str, name: str) -> dict: raise NotImplementedError

    def watch_objects(self, kind: str,
                      stop: Optional[threading.Event] = None,
                      resource_version: Optional[str] = None
                      ) -> Iterator[WatchEvent]:
        """Cluster-wide watch on ``kind`` ("secrets" | "configmaps") — the
        analog of the reference controller's secret/configmap informers
        (main.go:180-193). Stream end = caller restarts (no RV continuity
        contract here: consumers react to change notifications, they don't
        mirror state the way the pod controller must)."""
        raise NotImplementedError

    # node + lease (L3')
    def get_node(self, name: str) -> dict: raise NotImplementedError
    def create_node(self, node: dict) -> dict: raise NotImplementedError
    def update_node(self, node: dict) -> dict: raise NotImplementedError
    def patch_node_status(self, name: str, patch: dict) -> dict: raise NotImplementedError
    def get_lease(self, name: str) -> dict: raise NotImplementedError
    def create_lease(self, lease: dict) -> dict: raise NotImplementedError
    def update_lease(self, lease: dict) -> dict: raise NotImplementedError

    # events
    def create_event(self, ns: str, event: dict) -> dict: raise NotImplementedError


def _pod_path(ns: str, name: str = "", sub: str = "") -> str:
    p = f"/api/v1/namespaces/{ns}/pods"
    if name:
        p += f"/{name}"
    if sub:
        p += f"/{sub}"
    return p


class ExecCredentialPlugin(_CachingProvider):
    """K8s client-go `exec` credential plugin driver (the auth mechanism
    real GKE kubeconfigs use: `gke-gcloud-auth-plugin`). Spawns the
    configured command, parses the ExecCredential it prints, and caches
    the token until its expirationTimestamp (missing expiry caches for
    the process lifetime, per the client-go contract). Cache/skew/
    invalidate machinery is cloud/gcp_auth.py's _CachingProvider — ONE
    token-cache implementation serves the GCP and K8s legs.
    Parity target: the reference's cluster-auth story is complete for
    ITS world (in-cluster or static kubeconfig,
    /root/reference/cmd/virtual_kubelet/main.go:464-494); GKE clusters
    need this third leg."""

    def __init__(self, command: str, args: Optional[list] = None,
                 env: Optional[list] = None,
                 api_version: str = "client.authentication.k8s.io/v1beta1",
                 cluster_info: Optional[dict] = None,
                 timeout_s: float = 30.0, now=time.time):
        super().__init__(now)
        self.command = command
        self.args = list(args or [])
        self.env_pairs = list(env or [])      # [{"name": .., "value": ..}]
        self.api_version = api_version
        self.cluster_info = cluster_info      # spec.cluster (provideClusterInfo)
        self.timeout_s = timeout_s

    def _fetch(self) -> tuple[str, float]:
        import subprocess
        env = dict(os.environ)
        for pair in self.env_pairs:
            env[pair["name"]] = pair.get("value", "")
        # client-go passes the request context via KUBERNETES_EXEC_INFO
        spec: dict = {"interactive": False}
        if self.cluster_info is not None:
            spec["cluster"] = self.cluster_info
        env["KUBERNETES_EXEC_INFO"] = json.dumps(
            {"apiVersion": self.api_version, "kind": "ExecCredential",
             "spec": spec})
        try:
            proc = subprocess.run([self.command] + self.args,
                                  capture_output=True, text=True,
                                  timeout=self.timeout_s, env=env)
        except FileNotFoundError:
            raise KubeApiError(
                f"exec credential plugin {self.command!r} not found on "
                f"PATH — is it installed? (GKE: gke-gcloud-auth-plugin)")
        except Exception as e:  # noqa: BLE001 — timeout, spawn failure
            raise KubeApiError(f"exec credential plugin {self.command!r} "
                               f"failed: {type(e).__name__}: {e}")
        if proc.returncode != 0:
            raise KubeApiError(
                f"exec credential plugin {self.command!r} exited "
                f"{proc.returncode}: {(proc.stderr or '')[:300]}")
        try:
            cred = json.loads(proc.stdout)
            status = cred["status"]
            token = status.get("token", "")
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            raise KubeApiError(
                f"exec credential plugin {self.command!r} printed invalid "
                f"ExecCredential: {e}: {(proc.stdout or '')[:200]}")
        if not token:
            # client-go also accepts clientCertificateData/clientKeyData;
            # GKE (and every cloud plugin this kubelet targets) issues
            # bearer tokens — reject cert-only creds loudly
            raise KubeApiError(
                f"exec plugin {self.command!r} returned no status.token "
                "(client-cert exec credentials are not supported)")
        exp = status.get("expirationTimestamp")
        # lifetime against the INJECTED clock (self._now), not wall time:
        # _CachingProvider's cache/skew bookkeeping runs on self._now, so
        # a wall-clock lifetime would disagree with it under injected or
        # adjusted clocks (ADVICE r5)
        lifetime = (max(0.0, _parse_rfc3339(exp) - self._now()) if exp
                    else float("inf"))   # no expiry = process lifetime
        return token, lifetime


def _parse_rfc3339(ts: str) -> float:
    """RFC3339 -> epoch seconds (K8s always emits UTC 'Z' or an offset)."""
    import datetime
    return datetime.datetime.fromisoformat(
        ts.replace("Z", "+00:00")).timestamp()


def _b64_to_tempfile(data_b64: str, suffix: str) -> str:
    """Write a kubeconfig *-data field to a private temp file and return
    its path (ssl wants file paths for cert chains; GKE kubeconfigs inline
    everything base64)."""
    import base64
    import tempfile
    f = tempfile.NamedTemporaryFile(mode="wb", suffix=suffix, delete=False)
    try:
        f.write(base64.b64decode(data_b64))
    finally:
        f.close()
    os.chmod(f.name, 0o600)
    return f.name


class RealKubeClient(KubeClient):
    """JSON-over-HTTP client with streaming watch (stdlib only)."""

    def __init__(self, server: str, token: str = "", ca_file: str = "",
                 client_cert: str = "", client_key: str = "",
                 insecure_skip_tls: bool = False, timeout_s: float = 30.0,
                 token_provider: Optional[ExecCredentialPlugin] = None,
                 ca_data: str = ""):
        u = urllib.parse.urlparse(server)
        self.host = u.hostname or "localhost"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.tls = u.scheme == "https"
        self.token = token
        self.token_provider = token_provider
        self.timeout_s = timeout_s
        self.ssl_ctx: Optional[ssl.SSLContext] = None
        if self.tls:
            # ca_data (PEM text, GKE's inline certificate-authority-data)
            # loads directly — no CA temp file touches disk
            self.ssl_ctx = ssl.create_default_context(cafile=ca_file or None,
                                                      cadata=ca_data or None)
            if client_cert:
                self.ssl_ctx.load_cert_chain(client_cert, client_key or None)
            if insecure_skip_tls:
                self.ssl_ctx.check_hostname = False
                self.ssl_ctx.verify_mode = ssl.CERT_NONE

    # -- construction from environment ----------------------------------------

    @classmethod
    def from_env(cls, kubeconfig: str = "") -> "RealKubeClient":
        """In-cluster config if the service-account mount exists, else kubeconfig
        (parity: main.go:468-485)."""
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        if not kubeconfig and os.path.exists(f"{sa}/token"):
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            with open(f"{sa}/token") as f:
                token = f.read().strip()
            return cls(f"https://{host}:{port}", token=token, ca_file=f"{sa}/ca.crt")
        return cls.from_kubeconfig(kubeconfig or os.path.expanduser("~/.kube/config"))

    @classmethod
    def from_kubeconfig(cls, path: str) -> "RealKubeClient":
        """Three user-auth legs, covering real GKE kubeconfigs:
        static ``token``, client certificates, and ``exec`` credential
        plugins (gke-gcloud-auth-plugin et al). Inline base64 ``*-data``
        fields (how GKE ships its CA and certs) are materialized to
        private temp files for ssl. Relative ``certificate-authority``/
        ``client-certificate``/``client-key`` paths resolve against the
        kubeconfig file's directory, matching kubectl/client-go — as-is
        they would only work when CWD happened to be that directory."""
        import yaml
        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = cfg.get("current-context")
        ctx = next(c["context"] for c in cfg["contexts"] if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"])
        user = next(u["user"] for u in cfg["users"] if u["name"] == ctx["user"])

        tempfiles: list[str] = []
        base_dir = os.path.dirname(os.path.abspath(path))

        def resolve(p: str) -> str:
            return os.path.join(base_dir, p) if p and not os.path.isabs(p) \
                else p

        def field(obj: dict, name: str, suffix: str) -> str:
            if obj.get(f"{name}-data"):
                path_ = _b64_to_tempfile(obj[f"{name}-data"], suffix)
                tempfiles.append(path_)
                return path_
            return resolve(obj.get(name, ""))

        provider = None
        if "exec" in user:
            ex = user["exec"]
            cluster_info = None
            if ex.get("provideClusterInfo"):
                cluster_info = {
                    "server": cluster["server"],
                    **({"certificate-authority-data":
                        cluster["certificate-authority-data"]}
                       if cluster.get("certificate-authority-data") else {}),
                }
            provider = ExecCredentialPlugin(
                ex["command"], ex.get("args"), ex.get("env"),
                api_version=ex.get(
                    "apiVersion", "client.authentication.k8s.io/v1beta1"),
                cluster_info=cluster_info)
        import base64
        ca_data = ""
        if cluster.get("certificate-authority-data"):
            ca_data = base64.b64decode(
                cluster["certificate-authority-data"]).decode()
        try:
            return cls(
                cluster["server"],
                token=user.get("token", ""),
                ca_file=resolve(cluster.get("certificate-authority", "")),
                ca_data=ca_data,
                client_cert=field(user, "client-certificate", ".crt"),
                client_key=field(user, "client-key", ".key"),
                insecure_skip_tls=cluster.get("insecure-skip-tls-verify",
                                              False),
                token_provider=provider,
            )
        finally:
            # load_cert_chain consumed the inline client cert/key in the
            # constructor; the PRIVATE KEY must not outlive it on disk
            for p in tempfiles:
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # -- plumbing --------------------------------------------------------------

    def _conn(self, timeout_s: Optional[float] = None) -> http.client.HTTPConnection:
        if self.tls:
            return http.client.HTTPSConnection(self.host, self.port,
                                               timeout=timeout_s or self.timeout_s,
                                               context=self.ssl_ctx)
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout_s or self.timeout_s)

    def _headers(self, content_type: str = "application/json") -> dict:
        h = {"Accept": "application/json", "Content-Type": content_type}
        if self.token_provider is not None:
            h["Authorization"] = f"Bearer {self.token_provider()}"
        elif self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json") -> dict:
        try:
            return self._request_once(method, path, body, content_type)
        except KubeApiError as e:
            # a 401 under exec auth means the cached token died before its
            # stated expiry (revocation, clock skew): re-exec the plugin
            # once — client-go's interceptor does the same
            if e.status != 401 or self.token_provider is None:
                raise
            self.token_provider.invalidate()
            return self._request_once(method, path, body, content_type)

    def _request_once(self, method: str, path: str,
                      body: Optional[dict] = None,
                      content_type: str = "application/json") -> dict:
        conn = self._conn()
        try:
            conn.request(method, path,
                         body=json.dumps(body) if body is not None else None,
                         headers=self._headers(content_type))
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status >= 400:
                raise KubeApiError(f"{method} {path}: HTTP {resp.status}: "
                                   f"{raw[:300].decode(errors='replace')}",
                                   status=resp.status)
            return json.loads(raw) if raw else {}
        finally:
            conn.close()

    @staticmethod
    def _selector_query(field_selector: str, label_selector: str, extra: str = "") -> str:
        parts = []
        if field_selector:
            parts.append("fieldSelector=" + urllib.parse.quote(field_selector))
        if label_selector:
            parts.append("labelSelector=" + urllib.parse.quote(label_selector))
        if extra:
            parts.append(extra)
        return ("?" + "&".join(parts)) if parts else ""

    # -- pods ------------------------------------------------------------------

    def get_pod(self, ns, name):
        return self._request("GET", _pod_path(ns, name))

    def list_pods(self, ns=None, field_selector="", label_selector=""):
        return self.list_pods_rv(ns, field_selector, label_selector)[0]

    def list_pods_rv(self, ns=None, field_selector="", label_selector=""):
        base = _pod_path(ns) if ns else "/api/v1/pods"
        q = self._selector_query(field_selector, label_selector)
        body = self._request("GET", base + q)
        return (body.get("items", []),
                body.get("metadata", {}).get("resourceVersion", ""))

    def create_pod(self, pod):
        ns = pod["metadata"].get("namespace", "default")
        return self._request("POST", _pod_path(ns), pod)

    def update_pod(self, pod):
        m = pod["metadata"]
        return self._request("PUT", _pod_path(m.get("namespace", "default"), m["name"]), pod)

    def patch_pod(self, ns, name, patch):
        return self._request("PATCH", _pod_path(ns, name), patch,
                             content_type="application/merge-patch+json")

    def patch_pod_status(self, ns, name, patch):
        return self._request("PATCH", _pod_path(ns, name, "status"), patch,
                             content_type="application/merge-patch+json")

    def delete_pod(self, ns, name, grace_period_s=None):
        body = None
        if grace_period_s is not None:
            body = {"gracePeriodSeconds": grace_period_s}
        try:
            self._request("DELETE", _pod_path(ns, name), body)
        except KubeApiError as e:
            if not e.is_not_found:
                raise

    def watch_pods(self, field_selector="", label_selector="", stop=None,
                   resource_version=None):
        """Streaming watch; reconnects are the caller's job (node/pod_controller
        tracks the last-seen resourceVersion and resumes from it, relisting on
        410 Gone — client-go Reflector semantics). Yields WatchEvents until the
        stream or ``stop`` ends."""
        yield from self._watch_stream("/api/v1/pods", "pods", field_selector,
                                      label_selector, stop, resource_version)

    def watch_objects(self, kind, stop=None, resource_version=None):
        if kind not in ("secrets", "configmaps"):
            raise ValueError(f"unsupported watch kind {kind!r}")
        yield from self._watch_stream(f"/api/v1/{kind}", kind, "", "", stop,
                                      resource_version)

    def _watch_stream(self, path, what, field_selector, label_selector,
                      stop, resource_version):
        extra = "watch=true&allowWatchBookmarks=true"
        if resource_version:
            extra += "&resourceVersion=" + urllib.parse.quote(resource_version)
        q = self._selector_query(field_selector, label_selector, extra=extra)
        conn = self._conn(timeout_s=330)  # server closes watches ~5min; outlive it
        try:
            conn.request("GET", path + q, headers=self._headers())
            resp = conn.getresponse()
            if resp.status >= 400:
                if resp.status == 401 and self.token_provider is not None:
                    # a revoked-before-expiry exec token would otherwise be
                    # replayed on EVERY watch reconnect until natural
                    # expiry (the controller's backoff loop calls straight
                    # back into _headers); drop it so the reconnect mints
                    # a fresh credential
                    self.token_provider.invalidate()
                raise KubeApiError(f"watch {what}: HTTP {resp.status}",
                                   status=resp.status)
            buf = b""
            while not (stop and stop.is_set()):
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    ev = json.loads(line)
                    ev_type = ev.get("type", "ERROR")
                    obj = ev.get("object", {})
                    if ev_type == "ERROR":
                        # the server reports expired RVs as an in-stream
                        # Status with code 410, not an HTTP error
                        code = obj.get("code", 0)
                        raise KubeApiError(
                            f"watch {what}: {obj.get('message', 'stream error')}",
                            status=code or 500)
                    yield WatchEvent(type=ev_type, object=obj)
        finally:
            conn.close()

    # -- secrets / configmaps / jobs -------------------------------------------

    def get_secret(self, ns, name):
        return self._request("GET", f"/api/v1/namespaces/{ns}/secrets/{name}")

    def get_config_map(self, ns, name):
        return self._request("GET",
                             f"/api/v1/namespaces/{ns}/configmaps/{name}")

    def get_job(self, ns, name):
        return self._request("GET", f"/apis/batch/v1/namespaces/{ns}/jobs/{name}")

    # -- nodes / leases --------------------------------------------------------

    def get_node(self, name):
        return self._request("GET", f"/api/v1/nodes/{name}")

    def create_node(self, node):
        return self._request("POST", "/api/v1/nodes", node)

    def update_node(self, node):
        return self._request("PUT", f"/api/v1/nodes/{node['metadata']['name']}", node)

    def patch_node_status(self, name, patch):
        return self._request("PATCH", f"/api/v1/nodes/{name}/status", patch,
                             content_type="application/merge-patch+json")

    def get_lease(self, name):
        return self._request(
            "GET", f"/apis/coordination.k8s.io/v1/namespaces/{LEASE_NAMESPACE}/leases/{name}")

    def create_lease(self, lease):
        return self._request(
            "POST", f"/apis/coordination.k8s.io/v1/namespaces/{LEASE_NAMESPACE}/leases", lease)

    def update_lease(self, lease):
        name = lease["metadata"]["name"]
        return self._request(
            "PUT", f"/apis/coordination.k8s.io/v1/namespaces/{LEASE_NAMESPACE}/leases/{name}",
            lease)

    # -- events ----------------------------------------------------------------

    def create_event(self, ns, event):
        return self._request("POST", f"/api/v1/namespaces/{ns}/events", event)
