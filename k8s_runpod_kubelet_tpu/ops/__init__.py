"""TPU compute ops: Pallas kernels with XLA fallbacks.

Every op has two paths:
- a Pallas TPU kernel (the hot path on real hardware), and
- a pure-XLA fallback (used on CPU test meshes and anywhere Pallas is
  unavailable) that is numerically equivalent.

``use_pallas=None`` auto-selects: Pallas on TPU backends, XLA elsewhere.
"""

from .rmsnorm import rms_norm
from .rope import apply_rope, rope_frequencies
from .attention import flash_attention, paged_attention
from .ring_attention import ring_attention
from .fused_ce import fused_cross_entropy
from .mla import mla_attention, mla_decode_step

__all__ = ["rms_norm", "apply_rope", "rope_frequencies", "flash_attention",
           "paged_attention", "ring_attention", "fused_cross_entropy",
           "mla_attention", "mla_decode_step"]
