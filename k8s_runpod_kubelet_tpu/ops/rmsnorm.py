"""RMSNorm: Pallas TPU kernel + XLA fallback.

The norm is HBM-bandwidth-bound; the kernel keeps each (block_rows, d) tile in
VMEM, does the reduction and scale in one pass, and writes once. The fallback
is the same math for XLA to fuse.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .common import use_pallas as _use_pallas


def _rms_norm_xla(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_pallas_diff(x, weight, eps, block_rows):
    return _rms_pallas(x, weight, eps, block_rows)


def _rms_diff_fwd(x, weight, eps, block_rows):
    return _rms_pallas(x, weight, eps, block_rows), (x, weight)


def _rms_diff_bwd(eps, block_rows, res, g):
    x, weight = res
    _, vjp = jax.vjp(lambda x_, w_: _rms_norm_xla(x_, w_, eps), x, weight)
    return vjp(g)


_rms_pallas_diff.defvjp(_rms_diff_fwd, _rms_diff_bwd)


@functools.partial(jax.jit, static_argnames=("eps", "use_pallas", "block_rows"))
def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6,
             use_pallas: Optional[bool] = None, block_rows: int = 256) -> jax.Array:
    """y = x * rsqrt(mean(x^2) + eps) * weight, over the last axis."""
    if not _use_pallas(use_pallas):
        return _rms_norm_xla(x, weight, eps)
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    if rows == 0 or rows % min(block_rows, rows) != 0:
        return _rms_norm_xla(x, weight, eps)  # empty or ragged: XLA handles it
    return _rms_pallas_diff(x, weight, eps, block_rows)


def _rms_pallas(x: jax.Array, weight: jax.Array, eps: float,
                block_rows: int) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_shape = x.shape
    d = orig_shape[-1]
    rows = 1
    for s in orig_shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    if rows % block_rows != 0:  # ragged: let XLA handle it
        return _rms_norm_xla(x, weight, eps)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
    )(x2, weight)
    return out.reshape(orig_shape)
