"""Rotary position embeddings (RoPE), Llama-3 style.

Pure XLA: RoPE is elementwise and fuses into the surrounding matmuls; a Pallas
kernel would only add launch overhead. Supports Llama-3's NTK-aware frequency
scaling for long context.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float = 500_000.0,
                     scaling: Optional[dict] = None) -> tuple[jax.Array, jax.Array]:
    """Precompute (cos, sin) tables of shape (max_seq_len, head_dim//2).

    ``scaling``: either the Llama-3.1 NTK recipe (dict with factor,
    low_freq_factor, high_freq_factor, original_max_position) or plain
    linear position interpolation ({"rope_type": "linear", "factor": f} —
    Gemma-3 global layers): all frequencies divided by f.
    """
    af = 1.0   # yarn attention factor folded into the tables (else 1)
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    rope_type = (scaling or {}).get("rope_type",
                                    (scaling or {}).get("type", "llama3"))
    if scaling and rope_type == "linear":
        inv_freq = inv_freq / scaling.get("factor", 1.0)
    elif scaling and rope_type == "default":
        pass  # HF "default" = plain unscaled RoPE
    elif scaling and rope_type == "yarn":
        # YaRN (arXiv:2309.00071), transformers' _compute_yarn_parameters
        # exactly — DeepSeek-V2/V3 ship rope_scaling type "yarn" (V2-Lite:
        # factor 40 past a 4k original window), so real checkpoints need
        # this for any context beyond original_max_position_embeddings.
        # Per-dim blend between interpolation (freq/factor) and
        # extrapolation (raw freq) over a linear ramp in "rotations at
        # the original window", plus a global attention scaling folded
        # into the cos/sin tables. NOTE: yarn with mscale_all_dim ALSO
        # scales the attention softmax — that half lives at the attention
        # call sites (llama.yarn_mscale_sq), not in these tables.
        factor = float(scaling.get("factor", 1.0))
        orig = float(scaling.get("original_max_position_embeddings",
                                 scaling.get("original_max_position",
                                             max_seq_len)))
        beta_fast = float(scaling.get("beta_fast") or 32)
        beta_slow = float(scaling.get("beta_slow") or 1)

        def get_mscale(scale, ms=1.0):
            return 1.0 if scale <= 1 else 0.1 * ms * math.log(scale) + 1.0

        attention_factor = scaling.get("attention_factor")
        if attention_factor is None:
            ms = scaling.get("mscale")
            ms_all = scaling.get("mscale_all_dim")
            if ms and ms_all:
                attention_factor = (get_mscale(factor, ms)
                                    / get_mscale(factor, ms_all))
            else:
                attention_factor = get_mscale(factor)

        def corr_dim(n_rot):
            return (head_dim * math.log(orig / (n_rot * 2 * math.pi))
                    / (2 * math.log(theta)))

        low, high = corr_dim(beta_fast), corr_dim(beta_slow)
        if scaling.get("truncate", True):
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, head_dim - 1)
        if low == high:
            high += 0.001
        ramp = jnp.clip((jnp.arange(head_dim // 2, dtype=jnp.float32)
                         - low) / (high - low), 0, 1)
        extrapolation_factor = 1.0 - ramp
        inv_freq = ((inv_freq / factor) * (1 - extrapolation_factor)
                    + inv_freq * extrapolation_factor)
        af = float(attention_factor)
    elif scaling and rope_type != "llama3":
        # refuse to silently misread a dynamic/... dict as the Llama-3.1
        # recipe — wrong tables degrade logits without erroring anywhere
        raise ValueError(f"unsupported rope_scaling type {rope_type!r} "
                         "(supported: linear, llama3, yarn, default)")
    elif scaling:
        factor = scaling.get("factor", 8.0)
        low = scaling.get("low_freq_factor", 1.0)
        high = scaling.get("high_freq_factor", 4.0)
        # HF configs spell this 'original_max_position_embeddings'; accept
        # the short key too (both pass the rope_type validation above)
        orig = scaling.get("original_max_position",
                           scaling.get("original_max_position_embeddings",
                                       8192))
        wavelen = 2 * jnp.pi / inv_freq
        low_wl = orig / low
        high_wl = orig / high
        smooth = (orig / wavelen - low) / (high - low)
        scaled = jnp.where(
            wavelen > low_wl, inv_freq / factor,
            jnp.where(wavelen < high_wl, inv_freq,
                      (1 - smooth) * inv_freq / factor + smooth * inv_freq))
        inv_freq = scaled
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (S, D/2)
    if af != 1.0:
        return jnp.cos(freqs) * af, jnp.sin(freqs) * af
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Rotate (B, S, H, D) by position. ``positions`` (B, S) overrides arange
    (needed for decode steps and sequence-parallel shards)."""
    b, s, h, d = x.shape
    if positions is None:
        c = cos[:s][None, :, None, :]
        si = sin[:s][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]
        si = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * si, x2 * c + x1 * si], axis=-1)
    return out.astype(x.dtype)
