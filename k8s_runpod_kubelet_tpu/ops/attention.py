"""Flash attention: blockwise online-softmax Pallas TPU kernels + XLA fallback.

Forward: grid (batch, q_heads, q_blocks, k_blocks) — K/V are STREAMED through
the innermost (sequential) grid dimension in (block_k, d) tiles, which Pallas
double-buffers HBM->VMEM automatically, so VMEM residency is O(block sizes)
and independent of sequence length: 32k context fits v5e VMEM alongside the
accumulators (VERDICT r1 item 4; the round-1 kernels kept the whole K/V
sequence resident per program). The online-softmax state (acc, m, l) is
carried across k blocks in VMEM scratch; causal programs skip compute for
blocks past their diagonal; the output block and row log-sum-exp are flushed
once at the last k block, which makes the backward exact without re-running
the softmax reduction. GQA is native: q heads index their KV head directly,
no repeated-K/V materialization.

Backward: two Pallas kernels (the standard flash-attention split):
  - dQ:    grid (b, hq, q_blocks, k_blocks); K/V tiles streamed exactly like
           the forward, dq accumulated in scratch.
  - dK/dV: grid (b, hkv, k_blocks, group*q_blocks) — gridded over KV heads,
           looping the GQA group's q heads through the innermost dimension,
           so dk/dv come out directly at (B, Hkv, S, D) in the input dtype:
           no per-q-head f32 HBM transient and no XLA group-sum afterwards
           (ADVICE r1: the old layout spiked ~16x-vs-bf16-kv HBM on 8:1 GQA).
δ = rowsum(dO ∘ O) is precomputed in XLA. All matmuls run in the input dtype
with f32 accumulation (MXU-native); only softmax/statistics math is f32.
No (S, S) buffer exists in either direction.

Block sizes default to a per-generation tuned pick (largest power-of-two
divisor of the sequence under the generation's cap); pass block_q/block_k to
override.

Layout: q (B, Hq, S, D); k, v (B, Hkv, S, D); Hq % Hkv == 0.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .common import tpu_compiler_params, use_pallas as _use_pallas

NEG_INF = -1e30
_STATS_LANES = 128  # stats scratch keeps a full 128-lane tile (Mosaic-native)

# per-generation caps for auto block sizing: (block_q_cap, block_k_cap).
# Bigger k blocks amortize grid overhead; v5p/v6e have the VMEM headroom.
_BLOCK_CAPS = {"v4": (512, 512), "v5e": (512, 512),
               "v5p": (512, 1024), "v6e": (512, 1024)}


def _generation() -> str:
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 - backend not up; caller falls back
        return "cpu"
    for gen in ("v6e", "v5p", "v4"):
        if gen in kind:
            return gen
    return "v5e" if "v5" in kind or "tpu" in kind else "cpu"


def _pick_block(seq: int, cap: int) -> int:
    """Largest power-of-two divisor of ``seq`` in [128, cap] (0 if none)."""
    if seq % 128 != 0:
        return 0
    b = 128
    while b * 2 <= cap and seq % (b * 2) == 0:
        b *= 2
    return b


def tuned_block_sizes(sq: int, sk: int,
                      generation: Optional[str] = None) -> tuple[int, int]:
    """Default (block_q, block_k) for this sequence shape and chip."""
    cap_q, cap_k = _BLOCK_CAPS.get(generation or _generation(), (256, 512))
    return _pick_block(sq, cap_q), _pick_block(sk, cap_k)


def _attention_xla(q, k, v, *, causal: bool, sm_scale: float,
                   q_offset: int = 0,
                   sliding_window: Optional[int] = None,
                   logit_soft_cap: Optional[float] = None) -> jax.Array:
    """Reference/fallback path; identical math, XLA-fused. Matmuls stay in
    the input dtype with f32 accumulation (bf16 inputs keep the MXU on its
    fast path); softmax statistics are f32. ``sliding_window`` (Mistral):
    each query attends only the last W positions (requires causal).
    ``logit_soft_cap`` (Gemma-2): scores pass cap*tanh(s/cap) before the
    mask, bounding attention logits smoothly."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    qg = (q * jnp.asarray(sm_scale, q.dtype)).reshape(b, hkv, group, sq, d)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32)
    if logit_soft_cap is not None:
        s = jnp.tanh(s / logit_soft_cap) * logit_soft_cap
    if causal:
        q_pos = jnp.arange(sq) + q_offset
        k_pos = jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]
        if sliding_window is not None:
            mask &= (q_pos[:, None] - k_pos[None, :]) < sliding_window
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    elif sliding_window is not None:
        raise ValueError("sliding_window requires causal attention")
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(q.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.reshape(b, hq, sq, d).astype(q.dtype)


def _causal_mask(s, qi, kj, block_q, block_k, window=None, q_offset=0):
    """``q_offset``: static global offset of the q block's positions vs the
    k positions — ring flash attention gives each visiting K/V chunk the
    fixed offset t*S_local, so the same mask/skip logic serves both the
    single-chunk and ring cases."""
    q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = q_pos >= k_pos
    if window is not None:
        keep &= (q_pos - k_pos) < window
    return jnp.where(keep, s, NEG_INF)


# -- forward kernel -----------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                block_q: int, block_k: int, num_k_blocks: int, causal: bool,
                sm_scale: float, window: Optional[int] = None,
                soft_cap: Optional[float] = None, q_offset: int = 0):
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale        # (bq, d)
        kc = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        vc = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if soft_cap is not None:
            s = jnp.tanh(s / soft_cap) * soft_cap
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, window, q_offset)
        m_prev = m_ref[:, :1]                                 # (bq, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, vc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # this k block participates iff its first k pos <= the last q pos
        # and (windowed) its last k pos is within the window of some q
        cond = kj * block_k < (qi + 1) * block_q + q_offset
        if window is not None:
            cond &= (kj + 1) * block_k > qi * block_q + q_offset - window + 1
        pl.when(cond)(_compute)
    else:
        _compute()

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, :1] + jnp.log(l)              # (bq, 1)


def _flash_fwd_pallas(q, k, v, causal: bool, scale: float, block_q: int,
                      block_k: int, interpret: bool = False,
                      window: Optional[int] = None,
                      soft_cap: Optional[float] = None, q_offset: int = 0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    num_k_blocks = sk // block_k
    kernel = functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                               num_k_blocks=num_k_blocks, causal=causal,
                               sm_scale=scale, window=window,
                               soft_cap=soft_cap, q_offset=q_offset)
    return pl.pallas_call(
        kernel,
        grid=(b, hq, sq // block_q, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i, j: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bb, h, i, j: (bb, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bb, h, i, j: (bb, h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i, j: (bb, h, i, 0)),
            # lse rides a trailing singleton dim: TPU tiling requires the last
            # two block dims to divide (8, 128) or equal the array dims, so a
            # rank-3 (1, 1, block_q) block can't lower; (block_q, 1) can
            pl.BlockSpec((1, 1, block_q, 1), lambda bb, h, i, j: (bb, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)


# -- backward kernels ---------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, block_q: int, block_k: int, num_k_blocks: int,
               causal: bool, sm_scale: float, window: Optional[int] = None,
               soft_cap: Optional[float] = None, q_offset: int = 0):
    import jax.experimental.pallas as pl  # noqa: F401
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale        # (bq, d)
        do = do_ref[0, 0].astype(jnp.float32)                 # (bq, d)
        lse = lse_ref[0, 0]                                   # (bq, 1)
        delta = delta_ref[0, 0]                               # (bq, 1)
        kc = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        vc = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if soft_cap is not None:
            t = jnp.tanh(s / soft_cap)
            s = t * soft_cap
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, window, q_offset)
        p = jnp.exp(s - lse)                                  # (bq, bk)
        dp = jax.lax.dot_general(do, vc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if soft_cap is not None:
            ds = ds * (1.0 - t * t)  # d/ds_raw of cap*tanh(s_raw/cap)
        acc_ref[...] += jax.lax.dot_general(
            ds, kc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        cond = kj * block_k < (qi + 1) * block_q + q_offset
        if window is not None:
            cond &= (kj + 1) * block_k > qi * block_q + q_offset - window + 1
        pl.when(cond)(_compute)
    else:
        _compute()

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = (acc_ref[...] * sm_scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int, block_k: int,
                num_q_blocks: int, num_t: int, causal: bool, sm_scale: float,
                window: Optional[int] = None,
                soft_cap: Optional[float] = None, q_offset: int = 0):
    import jax.experimental.pallas as pl  # noqa: F401
    kj = pl.program_id(2)
    t = pl.program_id(3)          # t = qh_in_group * num_q_blocks + q_block
    qi = t % num_q_blocks

    @pl.when(t == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        k = k_ref[0, 0].astype(jnp.float32)                   # (bk, d)
        v = v_ref[0, 0].astype(jnp.float32)
        qc = q_ref[0, 0].astype(jnp.float32)                  # (bq, d)
        doc = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]                                   # (bq, 1)
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(qc * sm_scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if soft_cap is not None:
            th = jnp.tanh(s / soft_cap)  # NOT `t` — that's the grid index
            s = th * soft_cap
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, window, q_offset)
        p = jnp.exp(s - lse)                                  # (bq, bk)
        dv_acc[...] += jax.lax.dot_general(
            p, doc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)
        dp = jax.lax.dot_general(doc, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                 # (bq, bk)
        if soft_cap is not None:
            ds = ds * (1.0 - th * th)
        dk_acc[...] += jax.lax.dot_general(
            ds, qc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)

    if causal:
        # this q block contributes iff its last q pos >= the first k pos
        # and (windowed) its first q pos still sees this k block
        cond = (qi + 1) * block_q + q_offset > kj * block_k
        if window is not None:
            cond &= qi * block_q + q_offset < (kj + 1) * block_k + window - 1
        pl.when(cond)(_compute)
    else:
        _compute()

    @pl.when(t == num_t - 1)
    def _finalize():
        dk_ref[0, 0] = (dk_acc[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal: bool, scale: float,
                      block_q: int, block_k: int, interpret: bool = False,
                      window: Optional[int] = None,
                      soft_cap: Optional[float] = None, q_offset: int = 0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    num_q_blocks = sq // block_q
    num_k_blocks = sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # (b, hq, sq, 1)

    dq_kernel = functools.partial(_dq_kernel, block_q=block_q,
                                  block_k=block_k, num_k_blocks=num_k_blocks,
                                  causal=causal, sm_scale=scale, window=window,
                                  soft_cap=soft_cap, q_offset=q_offset)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, hq, num_q_blocks, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i, j: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bb, h, i, j: (bb, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bb, h, i, j: (bb, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i, j: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bb, h, i, j: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bb, h, i, j: (bb, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bb, h, i, j: (bb, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv per KV head: the innermost grid dim walks the GQA group's q heads
    # x q blocks, so the group reduction happens in VMEM scratch and the
    # outputs materialize directly at (B, Hkv, S, D) in the input dtype
    num_t = group * num_q_blocks
    dkv_kernel = functools.partial(_dkv_kernel, block_q=block_q,
                                   block_k=block_k,
                                   num_q_blocks=num_q_blocks, num_t=num_t,
                                   causal=causal, sm_scale=scale,
                                   window=window, soft_cap=soft_cap,
                                   q_offset=q_offset)

    def _qh(bb, kh, j, t):
        return kh * group + t // num_q_blocks

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, hkv, num_k_blocks, num_t),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bb, kh, j, t: (bb, _qh(bb, kh, j, t),
                                               t % num_q_blocks, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, kh, j, t: (bb, kh, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, kh, j, t: (bb, kh, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bb, kh, j, t: (bb, _qh(bb, kh, j, t),
                                               t % num_q_blocks, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bb, kh, j, t: (bb, _qh(bb, kh, j, t),
                                               t % num_q_blocks, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bb, kh, j, t: (bb, _qh(bb, kh, j, t),
                                               t % num_q_blocks, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bb, kh, j, t: (bb, kh, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, kh, j, t: (bb, kh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# -- differentiable wrapper ---------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_diff(q, k, v, causal, scale, block_q, block_k, interpret, window,
                soft_cap):
    o, _ = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                             interpret, window, soft_cap)
    return o


def _flash_diff_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                    window, soft_cap):
    o, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                               interpret, window, soft_cap)
    return o, (q, k, v, o, lse)


def _flash_diff_bwd(causal, scale, block_q, block_k, interpret, window,
                    soft_cap, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale, block_q,
                             block_k, interpret, window, soft_cap)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


# -- paged-attention decode kernel (ISSUE 8) ----------------------------------

def _paged_valid(n_tokens: int, lengths, window: Optional[int]):
    """(B, S) mask of attendable positions for a decode query at position
    ``lengths - 1``: causal (< length) and — for uniform sliding-window
    models — within the last ``window`` positions (>= length - window).
    One definition shared by every paged reference path, so the window
    semantics can't drift between layouts."""
    pos = jnp.arange(n_tokens)[None, :]
    valid = pos < lengths[:, None]
    if window is not None:
        valid &= pos >= lengths[:, None] - window
    return valid


def _paged_attention_xla(q, k_pages, v_pages, page_table, lengths, *,
                         sm_scale: float,
                         logit_soft_cap: Optional[float] = None,
                         sliding_window: Optional[int] = None) -> jax.Array:
    """Pure-jnp reference path: gather the page table back into a
    contiguous (B, S, Hkv, D) view and run ordinary masked decode
    attention. Identical math to the Pallas kernel (f32 statistics, input
    dtype matmuls via f32 here — decode is 1 query so precision is cheap);
    also the CPU/odd-shape fallback."""
    b, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    k = k_pages[page_table].reshape(b, n * t, hkv, d)      # (B, S, Hkv, D)
    v = v_pages[page_table].reshape(b, n * t, hkv, d)
    qg = (q.astype(jnp.float32) * sm_scale).reshape(b, hkv, group, d)
    s = jnp.einsum("bhgd,bLhd->bhgL", qg, k.astype(jnp.float32))
    if logit_soft_cap is not None:
        s = jnp.tanh(s / logit_soft_cap) * logit_soft_cap
    valid = _paged_valid(n * t, lengths, sliding_window)   # (B, S)
    s = jnp.where(valid[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgL,bLhd->bhgd", p, v.astype(jnp.float32))
    return o.reshape(b, hq, d).astype(q.dtype)


def _paged_fwd_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                      acc_ref, m_ref, l_ref, *, page_tokens: int,
                      num_pages: int, sm_scale: float,
                      soft_cap: Optional[float] = None,
                      window: Optional[int] = None):
    """One (batch row, kv head, page) program: online-softmax accumulate
    the page's contribution. The PAGE TABLE is scalar-prefetched, so the
    BlockSpec index map DMAs exactly the page this program needs — the
    K/V gather over non-contiguous HBM pages IS the index map; no
    contiguous copy of the sequence ever exists. ``window`` (uniform
    sliding-window models on a paged ring run): pages fully behind
    ``length - window`` are SKIPPED entirely — their table entries may
    alias recycled physical pages, so they must never be read — making
    the per-step work O(window), not O(context)."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    live = i * page_tokens < length
    if window is not None:
        live &= (i + 1) * page_tokens > length - window

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale      # (Gp, D)
        kc = k_ref[0, :, 0].astype(jnp.float32)             # (T, D)
        vc = v_ref[0, :, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (Gp, T)
        if soft_cap is not None:
            s = jnp.tanh(s / soft_cap) * soft_cap
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = pos < length
        if window is not None:
            keep &= pos >= length - window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_ref[:, :1]                               # (Gp, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, vc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, page_table, lengths,
                            scale: float, interpret: bool,
                            soft_cap: Optional[float] = None,
                            window: Optional[int] = None) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    # pad the GQA group to a full sublane tile (f32 min 8): padded q rows
    # are zeros, their outputs are sliced off — wasted lanes, not wrong math
    gp = -(-group // 8) * 8
    qr = q.reshape(b, hkv, group, d)
    if gp != group:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    kernel = functools.partial(_paged_fwd_kernel, page_tokens=t, num_pages=n,
                               sm_scale=scale, soft_cap=soft_cap,
                               window=window)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, hkv, n),
        in_specs=[
            pl.BlockSpec((1, 1, gp, d),
                         lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
            # THE paged gather: the k/v block for program (b, h, i) is
            # page page_table[b, i] — non-contiguous pages stream through
            # VMEM without ever materializing a contiguous sequence
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, d),
                               lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      qr, k_pages, v_pages)
    return out[:, :, :group].reshape(b, hq, d)


def _paged_head_specs(mesh, hq: int, hkv: Optional[int]):
    """The TP layout decision for one paged dispatch under ``shard_map``
    over ``tensor``: shard the head axes when the counts divide (GQA
    stays aligned — a shard's contiguous q-head block maps exactly onto
    its contiguous kv-head block, zero cross-shard attention traffic),
    else fall back to FULLY REPLICATED specs (every device redundantly
    computes the whole dispatch — correct, no TP win; the price of a
    head count the mesh doesn't divide). ``hkv=None`` for MLA latents
    (headless pages always replicate; only q shards). Returns the head
    axis name or None."""
    from ..parallel.mesh import AXES
    tp = mesh.shape.get(AXES.TENSOR, 1)
    shard = tp > 1 and hq % tp == 0 and (hkv is None or hkv % tp == 0)
    return AXES.TENSOR if shard else None


def _shard_paged_call(mesh, local, in_specs, out_specs, *args):
    """Run one paged-attention dispatch under shard_map over the serving
    mesh. check=False is the PR 1 Pallas-in-shard_map plumbing: a
    pallas_call's outputs carry no vma/replication typing, which strict
    shard_map rejects even when the values are honestly sharded. Used
    for EVERY multi-device mesh — a bare pallas_call in a GSPMD program
    over >1 device fails with "Mosaic kernels cannot be automatically
    partitioned" regardless of the tensor degree (the int4 kernel
    learned the same lesson)."""
    from .ring_attention import shard_map_compat
    fn = shard_map_compat(local, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check=False)
    return fn(*args)


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "logit_soft_cap",
                                             "sliding_window", "mesh",
                                             "shard_heads"))
def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_table: jax.Array, lengths: jax.Array, *,
                    sm_scale: Optional[float] = None,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False,
                    logit_soft_cap: Optional[float] = None,
                    sliding_window: Optional[int] = None,
                    mesh=None, shard_heads: bool = True) -> jax.Array:
    """Paged-attention DECODE: one query token per sequence attends over
    KV scattered across fixed-size pages of a shared arena (the serving
    engine's paged prefix pool; ROADMAP item 2's transfer unit).

    Shapes: q (B, Hq, D); k_pages/v_pages (P, T, Hkv, D) — the whole
    arena, page-major; page_table (B, N) int32 page ids, row b's logical
    positions [i*T, (i+1)*T) living in page page_table[b, i]; lengths (B,)
    valid token counts (position length-1 is the newest written KV).
    Entries of page_table at/after ceil(length/T) are never READ for
    attention but must still be VALID page indices (the grid touches them;
    callers keep them 0). Returns (B, Hq, D) in q's dtype.

    The Pallas kernel scalar-prefetches the page table so each (b, head,
    page) program DMAs its page directly HBM->VMEM (no contiguous copy of
    the sequence exists anywhere), accumulating online softmax across the
    page grid dimension. GQA is native: the group's q heads ride one
    program, padded to a full sublane tile. Falls back to the pure-jnp
    gather reference off-TPU or when (T, D) don't tile (T % 8, D % 128).

    ``sliding_window`` (uniform-window models on a paged ring run): the
    query attends only the last W positions — table entries whose pages
    sit fully behind the window are never read (the engine recycles their
    physical pages through the slot's ring run), so they only need to be
    VALID indices, not live data.

    Composes with TP sharding exactly like the contiguous cache:
    k/v_pages shard the kv-heads axis (kv_cache_pspec — same rank/axis as
    the engine cache), q/o shard heads. Pass ``mesh`` (ISSUE 12) to run
    the dispatch under shard_map over ``tensor`` with the page table and
    lengths replicated and the kv-head axis LOCAL to each shard — the
    TP serving engine's paged hot path; head counts the mesh doesn't
    divide degrade to replicated (redundant) compute, never wrong
    math. ``shard_heads=False`` pins the replicated specs — for a
    REPLICATED arena (kv_arena_sharding="replicate"), where sharded
    specs would reshard the whole arena every step."""
    b, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    if v_pages.shape != k_pages.shape:
        raise ValueError(f"k_pages {k_pages.shape} != v_pages "
                         f"{v_pages.shape}")
    if logit_soft_cap is not None and logit_soft_cap <= 0:
        raise ValueError(f"logit_soft_cap must be positive, "
                         f"got {logit_soft_cap}")
    if sliding_window is not None and sliding_window <= 0:
        raise ValueError(f"sliding_window must be positive, "
                         f"got {sliding_window}")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) \
        and d % 128 == 0 and t % 8 == 0

    def dispatch(qs, ks, vs, pt, ln):
        if not pallas_ok:
            return _paged_attention_xla(qs, ks, vs, pt, ln, sm_scale=scale,
                                        logit_soft_cap=logit_soft_cap,
                                        sliding_window=sliding_window)
        return _paged_attention_pallas(qs, ks, vs, pt, ln, scale, interpret,
                                       logit_soft_cap, sliding_window)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, hkv) if shard_heads else None
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, hs, None), P(None, None, hs, None),
             P(None, None, hs, None), P(), P()),
            P(None, hs, None),
            q, k_pages, v_pages, page_table, lengths)
    return dispatch(q, k_pages, v_pages, page_table, lengths)


# -- paged-attention variants: int8-KV (dequant in kernel) + MLA latents ------
# (ISSUE 10: the paged decode LOOP covered plain dense K/V only; these are
# the kernels that let int8-KV and MLA arenas serve zero-copy per-slot page
# tables — and adopt handed-off pages without a gather.)

def _paged_attention_quant_xla(q, k_pages, v_pages, k_scale, v_scale,
                               page_table, lengths, *, sm_scale: float,
                               logit_soft_cap: Optional[float] = None,
                               sliding_window: Optional[int] = None
                               ) -> jax.Array:
    """Reference path: gather the page table's WORKING SET first, then
    dequantize only that — identical math to the contiguous int8 decode
    (dequant then f32 attention), so parity tests compare the same
    numbers. Order matters for memory: dequantizing the whole arena
    before the gather would materialize ~8x the arena's int8 bytes in
    f32 per layer per step (the arena is sized to hold every slot's full
    residency — on the fallback path that transient could OOM HBM)."""
    b, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    k = (k_pages[page_table].astype(jnp.float32)
         * k_scale[page_table][..., None]).reshape(b, n * t, hkv, d)
    v = (v_pages[page_table].astype(jnp.float32)
         * v_scale[page_table][..., None]).reshape(b, n * t, hkv, d)
    qg = (q.astype(jnp.float32) * sm_scale).reshape(b, hkv, group, d)
    s = jnp.einsum("bhgd,bLhd->bhgL", qg, k)
    if logit_soft_cap is not None:
        s = jnp.tanh(s / logit_soft_cap) * logit_soft_cap
    valid = _paged_valid(n * t, lengths, sliding_window)
    s = jnp.where(valid[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgL,bLhd->bhgd", p, v)
    return o.reshape(b, hq, d).astype(q.dtype)


def _paged_fwd_quant_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref,
                            ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                            page_tokens: int, num_pages: int, n_kv: int,
                            sm_scale: float,
                            soft_cap: Optional[float] = None,
                            window: Optional[int] = None):
    """The plain paged kernel with int8 K/V pages dequantized IN KERNEL:
    HBM reads stay int8 (the bandwidth win), the f32 scales ride a small
    (T, Hkv) block per page and this program's head column is selected by
    an iota mask (a (T, 1) lane slice cannot tile). ``window``: same
    page-skip + position mask as the plain kernel (out-of-window table
    entries may alias recycled pages and must never be read)."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    h = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    live = i * page_tokens < length
    if window is not None:
        live &= (i + 1) * page_tokens > length - window

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale      # (Gp, D)
        hsel = jax.lax.broadcasted_iota(
            jnp.int32, (page_tokens, n_kv), 1) == h
        k_s = jnp.sum(jnp.where(hsel, ks_ref[0], 0.0), axis=1,
                      keepdims=True)                        # (T, 1)
        v_s = jnp.sum(jnp.where(hsel, vs_ref[0], 0.0), axis=1,
                      keepdims=True)
        kc = k_ref[0, :, 0].astype(jnp.float32) * k_s       # (T, D)
        vc = v_ref[0, :, 0].astype(jnp.float32) * v_s
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (Gp, T)
        if soft_cap is not None:
            s = jnp.tanh(s / soft_cap) * soft_cap
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = pos < length
        if window is not None:
            keep &= pos >= length - window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, vc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_quant_pallas(q, k_pages, v_pages, k_scale, v_scale,
                                  page_table, lengths, scale: float,
                                  interpret: bool,
                                  soft_cap: Optional[float] = None,
                                  window: Optional[int] = None
                                  ) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    gp = -(-group // 8) * 8
    qr = q.reshape(b, hkv, group, d)
    if gp != group:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    kernel = functools.partial(_paged_fwd_quant_kernel, page_tokens=t,
                               num_pages=n, n_kv=hkv, sm_scale=scale,
                               soft_cap=soft_cap, window=window)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, hkv, n),
        in_specs=[
            pl.BlockSpec((1, 1, gp, d),
                         lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
            # scales: the whole (T, Hkv) tile per page — a (T, 1) head
            # column cannot tile on lanes, and the tile is tiny next to
            # the int8 payload it scales
            pl.BlockSpec((1, t, hkv),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((1, t, hkv),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, d),
                               lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      qr, k_pages, v_pages, k_scale, v_scale)
    return out[:, :, :group].reshape(b, hq, d)


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "logit_soft_cap",
                                             "sliding_window", "mesh",
                                             "shard_heads"))
def paged_attention_quant(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, k_scale: jax.Array,
                          v_scale: jax.Array, page_table: jax.Array,
                          lengths: jax.Array, *,
                          sm_scale: Optional[float] = None,
                          use_pallas: Optional[bool] = None,
                          interpret: bool = False,
                          logit_soft_cap: Optional[float] = None,
                          sliding_window: Optional[int] = None,
                          mesh=None, shard_heads: bool = True) -> jax.Array:
    """``paged_attention`` over an int8-quantized KV arena: k/v_pages are
    int8 (P, T, Hkv, D) with per-(position, kv-head) f32 scales (P, T,
    Hkv) paged alongside — the same per-row symmetric scheme the
    contiguous int8 cache uses (models/llama.py _kv_quant), so an int8-KV
    engine's pages serve the paged decode loop AND hand off through the
    codec without requantization. Dequantization happens after the VMEM
    load; HBM reads stay int8, which is the entire point of the layout on
    a bandwidth-bound decode step. Same shape/validity contract as
    paged_attention; falls back to the dequant-reference off-TPU or when
    (T, D) don't tile. ``mesh``: run under shard_map over ``tensor``
    (paged_attention's TP contract) — int8 pages AND their scale
    sections keep the kv-head axis local to each shard."""
    b, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    if k_scale.shape != k_pages.shape[:3] \
            or v_scale.shape != v_pages.shape[:3]:
        raise ValueError(
            f"scale shapes {k_scale.shape}/{v_scale.shape} must be the "
            f"pages' (P, T, Hkv) = {k_pages.shape[:3]}")
    if logit_soft_cap is not None and logit_soft_cap <= 0:
        raise ValueError(f"logit_soft_cap must be positive, "
                         f"got {logit_soft_cap}")
    if sliding_window is not None and sliding_window <= 0:
        raise ValueError(f"sliding_window must be positive, "
                         f"got {sliding_window}")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) \
        and d % 128 == 0 and t % 8 == 0

    def dispatch(qs, ks, vs, kss, vss, pt, ln):
        if not pallas_ok:
            return _paged_attention_quant_xla(qs, ks, vs, kss, vss, pt, ln,
                                              sm_scale=scale,
                                              logit_soft_cap=logit_soft_cap,
                                              sliding_window=sliding_window)
        return _paged_attention_quant_pallas(qs, ks, vs, kss, vss, pt, ln,
                                             scale, interpret,
                                             logit_soft_cap, sliding_window)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, hkv) if shard_heads else None
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, hs, None), P(None, None, hs, None),
             P(None, None, hs, None), P(None, None, hs),
             P(None, None, hs), P(), P()),
            P(None, hs, None),
            q, k_pages, v_pages, k_scale, v_scale, page_table, lengths)
    return dispatch(q, k_pages, v_pages, k_scale, v_scale, page_table,
                    lengths)


def _paged_attention_mla_xla(q_lat, q_rope, c_pages, kr_pages, page_table,
                             lengths, *, sm_scale: float) -> jax.Array:
    """Reference path for MLA paged decode, in the ABSORBED form: scores
    are a latent-space dot plus the decoupled-RoPE term, the output is the
    attention-weighted LATENT (the caller up-projects through w_uv) —
    exactly the per-layer math of llama.py's MLA decode, over gathered
    pages. Latents have no heads axis: every query head reads the same
    (L, r + dr) cache rows."""
    b, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    n = page_table.shape[1]
    c = c_pages[page_table].reshape(b, n * t, r).astype(jnp.float32)
    kr = kr_pages[page_table].reshape(b, n * t, -1).astype(jnp.float32)
    s = (jnp.einsum("bhr,bLr->bhL",
                    q_lat.astype(jnp.float32) * sm_scale, c)
         + jnp.einsum("bhd,bLd->bhL",
                      q_rope.astype(jnp.float32) * sm_scale, kr))
    valid = jnp.arange(n * t)[None, :] < lengths[:, None]
    s = jnp.where(valid[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhL,bLr->bhr", p, c)
    return o.astype(q_lat.dtype)


def _paged_fwd_mla_kernel(pt_ref, len_ref, ql_ref, qr_ref, c_ref, kr_ref,
                          o_ref, acc_ref, m_ref, l_ref, *, page_tokens: int,
                          num_pages: int, sm_scale: float):
    """One (batch row, page) program: latent pages are HEADLESS, so the
    grid drops the kv-head dimension and every query head shares the one
    streamed (T, r)+(T, dr) tile — the bandwidth shape MLA exists for."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]

    @pl.when(i * page_tokens < length)
    def _compute():
        ql = ql_ref[0].astype(jnp.float32) * sm_scale       # (Gp, R)
        qr = qr_ref[0].astype(jnp.float32) * sm_scale       # (Gp, Dr)
        cc = c_ref[0].astype(jnp.float32)                   # (T, R)
        krc = kr_ref[0].astype(jnp.float32)                 # (T, Dr)
        s = (jax.lax.dot_general(ql, cc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
             + jax.lax.dot_general(qr, krc, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32))
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, cc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_mla_pallas(q_lat, q_rope, c_pages, kr_pages, page_table,
                                lengths, scale: float,
                                interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, r = q_lat.shape
    # lane alignment: latent (r) and rope (dr) blocks ride at their
    # NATIVE widths — a block whose minor dims EQUAL the array dims is
    # always tileable, and Mosaic pads sub-128 lane tiles internally
    # (the score tile (Gp, T) is already sub-128 at T=8/16), so
    # DeepSeek's dr=64 runs the real kernel with wasted lanes, not wrong
    # math — and crucially with NO per-step pad copy of the page arena
    # (an early draft padded kr_pages to 128 per dispatch: O(pool) bytes
    # per layer per token, dwarfing the kernel's O(attended pages) reads)
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    n = page_table.shape[1]
    gp = -(-hq // 8) * 8
    if gp != hq:
        q_lat = jnp.pad(q_lat, ((0, 0), (0, gp - hq), (0, 0)))
        q_rope = jnp.pad(q_rope, ((0, 0), (0, gp - hq), (0, 0)))
    kernel = functools.partial(_paged_fwd_mla_kernel, page_tokens=t,
                               num_pages=n, sm_scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, n),
        in_specs=[
            pl.BlockSpec((1, gp, r), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, gp, dr), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, t, r), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((1, t, dr), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, gp, r), lambda bb, i, pt, ln: (bb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, r), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, gp, r), q_lat.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q_lat, q_rope, c_pages, kr_pages)
    return out[:, :hq]


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "mesh"))
def paged_attention_mla(q_lat: jax.Array, q_rope: jax.Array,
                        c_pages: jax.Array, kr_pages: jax.Array,
                        page_table: jax.Array, lengths: jax.Array, *,
                        sm_scale: Optional[float] = None,
                        use_pallas: Optional[bool] = None,
                        interpret: bool = False, mesh=None) -> jax.Array:
    """Paged-attention decode over an MLA LATENT arena (absorbed form):
    q_lat (B, Hq, R) is the w_uk-absorbed query, q_rope (B, Hq, Dr) the
    decoupled-RoPE query; c_pages (P, T, R) / kr_pages (P, T, Dr) are the
    latent pages — no kv-heads axis, every head attends the same rows.
    Returns the attention-weighted latent (B, Hq, R) in q_lat's dtype;
    the caller up-projects it through w_uv (exactly the contiguous MLA
    decode split in models/llama.py). Same page-table/lengths contract as
    paged_attention. Pallas needs T %% 8; R and Dr ride NATIVE-width
    blocks (minor dims equal to the array dims always tile; Mosaic pads
    sub-128 lane tiles in registers — wasted lanes, not wrong math, and
    no pad copy of the arena), so DeepSeek's dr=64 runs the real kernel
    and only an untileable page size falls to the gathered reference.
    ``mesh``: run under shard_map over ``tensor`` — latent pages are
    HEADLESS so they stay REPLICATED per shard (every head attends the
    same rows; the replicated latent cache is still 8-57x smaller than
    a sharded K/V cache), while q_lat/q_rope/o shard the head axis."""
    b, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    if q_rope.shape != (b, hq, dr):
        raise ValueError(f"q_rope {q_rope.shape} != (B, Hq, Dr) = "
                         f"{(b, hq, dr)}")
    if c_pages.shape[:2] != kr_pages.shape[:2]:
        raise ValueError(f"c_pages {c_pages.shape} / kr_pages "
                         f"{kr_pages.shape} disagree on (P, T)")
    scale = sm_scale if sm_scale is not None else (r + dr) ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) and t % 8 == 0

    def dispatch(ql, qr, cp, krp, pt, ln):
        if not pallas_ok:
            return _paged_attention_mla_xla(ql, qr, cp, krp, pt, ln,
                                            sm_scale=scale)
        return _paged_attention_mla_pallas(ql, qr, cp, krp, pt, ln, scale,
                                           interpret)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, None)
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, hs, None), P(None, hs, None), P(), P(), P(), P()),
            P(None, hs, None),
            q_lat, q_rope, c_pages, kr_pages, page_table, lengths)
    return dispatch(q_lat, q_rope, c_pages, kr_pages, page_table, lengths)


def _paged_attention_mla_quant_xla(q_lat, q_rope, c_pages, kr_pages,
                                   c_scale, kr_scale, page_table, lengths, *,
                                   sm_scale: float) -> jax.Array:
    """Reference path for int8-LATENT MLA paged decode: gather the page
    table's working set, dequantize it (per-position f32 scales — the
    same scheme as the contiguous int8 latent cache in _verify_step_mla),
    then the absorbed-form attention. Working-set-first like the int8-K/V
    reference: dequantizing the whole arena would materialize 4x its
    bytes in f32 per layer per step."""
    b, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    n = page_table.shape[1]
    c = (c_pages[page_table].astype(jnp.float32)
         * c_scale[page_table][..., None]).reshape(b, n * t, r)
    kr = (kr_pages[page_table].astype(jnp.float32)
          * kr_scale[page_table][..., None]).reshape(b, n * t, -1)
    s = (jnp.einsum("bhr,bLr->bhL",
                    q_lat.astype(jnp.float32) * sm_scale, c)
         + jnp.einsum("bhd,bLd->bhL",
                      q_rope.astype(jnp.float32) * sm_scale, kr))
    valid = jnp.arange(n * t)[None, :] < lengths[:, None]
    s = jnp.where(valid[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhL,bLr->bhr", p, c)
    return o.astype(q_lat.dtype)


def _paged_fwd_mla_quant_kernel(pt_ref, len_ref, ql_ref, qr_ref, c_ref,
                                kr_ref, cs_ref, krs_ref, o_ref, acc_ref,
                                m_ref, l_ref, *, page_tokens: int,
                                num_pages: int, sm_scale: float):
    """The MLA paged kernel over int8 latent pages, dequantized IN KERNEL
    without ever transposing the scale: a per-POSITION scale factors out
    of the latent dot — ql·(c*s_t) = (ql·c)*s_t and p@(c*s) = (p⊙s)@c —
    so the (1, T) scale row broadcasts along the score LANE axis instead
    of needing a (T, 1) reshape Mosaic can't tile. HBM reads stay int8
    (int8 latents are the smallest KV representation this engine has: r+dr
    bytes/position/layer)."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]

    @pl.when(i * page_tokens < length)
    def _compute():
        ql = ql_ref[0].astype(jnp.float32) * sm_scale       # (Gp, R)
        qr = qr_ref[0].astype(jnp.float32) * sm_scale       # (Gp, Dr)
        cc = c_ref[0].astype(jnp.float32)                   # (T, R) int8->f32
        krc = kr_ref[0].astype(jnp.float32)                 # (T, Dr)
        cs = cs_ref[...]                                    # (1, T) f32
        krs = krs_ref[...]
        s = (jax.lax.dot_general(ql, cc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * cs
             + jax.lax.dot_general(qr, krc, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * krs)
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        # output = p @ (c * scale) == (p ⊙ scale_row) @ c: dequant rides
        # the probability row, never a transposed scale column
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p * cs, cc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_mla_quant_pallas(q_lat, q_rope, c_pages, kr_pages,
                                      c_scale, kr_scale, page_table, lengths,
                                      scale: float,
                                      interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, r = q_lat.shape
    # native-width latent blocks, like the unquantized dispatch: block
    # minor dims equal to the array dims always tile, sub-128 lanes are
    # wasted (not wrong) — and the int8 page arena is never pad-copied
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    n = page_table.shape[1]
    gp = -(-hq // 8) * 8
    if gp != hq:
        q_lat = jnp.pad(q_lat, ((0, 0), (0, gp - hq), (0, 0)))
        q_rope = jnp.pad(q_rope, ((0, 0), (0, gp - hq), (0, 0)))
    kernel = functools.partial(_paged_fwd_mla_quant_kernel, page_tokens=t,
                               num_pages=n, sm_scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, n),
        in_specs=[
            pl.BlockSpec((1, gp, r), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, gp, dr), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, t, r), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((1, t, dr), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
            # per-position scales: one (1, T) row per page — T is the full
            # minor dim, so the block tiles; the row broadcasts over lanes
            pl.BlockSpec((1, t), lambda bb, i, pt, ln: (pt[bb, i], 0)),
            pl.BlockSpec((1, t), lambda bb, i, pt, ln: (pt[bb, i], 0)),
        ],
        out_specs=pl.BlockSpec((1, gp, r), lambda bb, i, pt, ln: (bb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, r), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
            pltpu.VMEM((gp, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, gp, r), q_lat.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q_lat, q_rope, c_pages, kr_pages, c_scale, kr_scale)
    return out[:, :hq]


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "mesh"))
def paged_attention_mla_quant(q_lat: jax.Array, q_rope: jax.Array,
                              c_pages: jax.Array, kr_pages: jax.Array,
                              c_scale: jax.Array, kr_scale: jax.Array,
                              page_table: jax.Array, lengths: jax.Array, *,
                              sm_scale: Optional[float] = None,
                              use_pallas: Optional[bool] = None,
                              interpret: bool = False,
                              mesh=None) -> jax.Array:
    """``paged_attention_mla`` over an int8-quantized latent arena — the
    MLA+int8 combination the paged matrix was missing (ISSUE 11).
    c_pages/kr_pages are int8 (P, T, R)/(P, T, Dr) with per-POSITION f32
    scales (P, T) paged alongside — the same per-row symmetric scheme the
    contiguous int8 latent cache uses (llama.py _kv_quant over the last
    axis), so pages serve the paged decode loop AND hand off through the
    codec without requantization. Dequantization happens after the VMEM
    load in score space (scales broadcast on the lane axis; see the
    kernel); HBM reads stay int8, the densest KV representation in the
    repo: (r + dr) BYTES per position per layer. Same shape/validity
    contract as paged_attention_mla; native-width latent blocks like
    it, and the same TP contract (``mesh``: latent pages + scales
    replicated per shard, q/o head-sharded)."""
    b, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    if q_rope.shape != (b, hq, dr):
        raise ValueError(f"q_rope {q_rope.shape} != (B, Hq, Dr) = "
                         f"{(b, hq, dr)}")
    if c_pages.shape[:2] != kr_pages.shape[:2]:
        raise ValueError(f"c_pages {c_pages.shape} / kr_pages "
                         f"{kr_pages.shape} disagree on (P, T)")
    if c_scale.shape != c_pages.shape[:2] \
            or kr_scale.shape != kr_pages.shape[:2]:
        raise ValueError(
            f"scale shapes {c_scale.shape}/{kr_scale.shape} must be the "
            f"pages' (P, T) = {c_pages.shape[:2]}")
    scale = sm_scale if sm_scale is not None else (r + dr) ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) and t % 8 == 0

    def dispatch(ql, qr, cp, krp, cs, krs, pt, ln):
        if not pallas_ok:
            return _paged_attention_mla_quant_xla(ql, qr, cp, krp, cs, krs,
                                                  pt, ln, sm_scale=scale)
        return _paged_attention_mla_quant_pallas(ql, qr, cp, krp, cs, krs,
                                                 pt, ln, scale, interpret)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, None)
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, hs, None), P(None, hs, None), P(), P(), P(), P(),
             P(), P()),
            P(None, hs, None),
            q_lat, q_rope, c_pages, kr_pages, c_scale, kr_scale,
            page_table, lengths)
    return dispatch(q_lat, q_rope, c_pages, kr_pages, c_scale, kr_scale,
                    page_table, lengths)


# -- paged-attention MULTI-TOKEN kernels (ISSUE 14) ---------------------------
# K query tokens per sequence against page-table-indexed KV with a CAUSAL
# intra-block mask: query j of row b sits at absolute position
# lengths[b] - K + j (``lengths`` INCLUDES the K tokens being attended/
# written this call). K folds into the kernels' sublane axis — each
# (batch, [kv head,] page) program carries all K queries' online-softmax
# state, and the per-row query index recovers causality in-kernel — so
# speculative verify (K = k+1 drafts) and paged-native prefill chunks
# (K = chunk bucket) ride the SAME paged gather as single-token decode.
# At K=1 the math reduces exactly to the single-token dispatches.

def _paged_valid_multi(n_tokens: int, lengths, kq: int,
                       window: Optional[int]):
    """(B, K, S) mask of attendable positions for K queries whose last
    token sits at ``lengths - 1``: query j attends positions <= lengths -
    kq + j (causal across the block's own tokens) and — for uniform
    sliding-window models — only the ``window`` positions ending at its
    own. The multi-token generalization of _paged_valid (identical at
    kq=1); one definition shared by every multi reference path."""
    pos = jnp.arange(n_tokens)[None, None, :]
    qpos = (lengths[:, None] - kq + jnp.arange(kq)[None, :])[:, :, None]
    valid = pos <= qpos
    if window is not None:
        valid &= pos > qpos - window
    return valid


def _paged_attention_multi_xla(q, k_pages, v_pages, page_table, lengths, *,
                               sm_scale: float,
                               logit_soft_cap: Optional[float] = None,
                               sliding_window: Optional[int] = None
                               ) -> jax.Array:
    """Pure-jnp reference: gather the page table back into a contiguous
    view and run masked multi-query decode attention with the per-query
    causal mask. Also the CPU/odd-shape fallback."""
    b, kq, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    k = k_pages[page_table].reshape(b, n * t, hkv, d)
    v = v_pages[page_table].reshape(b, n * t, hkv, d)
    qg = (q.astype(jnp.float32) * sm_scale).reshape(b, kq, hkv, group, d)
    s = jnp.einsum("bkhgd,bLhd->bkhgL", qg, k.astype(jnp.float32))
    if logit_soft_cap is not None:
        s = jnp.tanh(s / logit_soft_cap) * logit_soft_cap
    valid = _paged_valid_multi(n * t, lengths, kq, sliding_window)
    s = jnp.where(valid[:, :, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkhgL,bLhd->bkhgd", p, v.astype(jnp.float32))
    return o.reshape(b, kq, hq, d).astype(q.dtype)


def _paged_fwd_multi_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                            acc_ref, m_ref, l_ref, *, page_tokens: int,
                            num_pages: int, n_q: int, gp: int,
                            sm_scale: float,
                            soft_cap: Optional[float] = None,
                            window: Optional[int] = None):
    """One (batch row, kv head, page) program over K queries: the sublane
    axis carries the K queries' padded GQA groups stacked query-major
    (row = j * gp + g), so one page stream feeds every query's online
    softmax and the CAUSAL intra-block mask is just a per-row position
    floor recovered from the row index. ``window``: pages fully behind
    the OLDEST query's window are skipped (their table entries may alias
    recycled pages — never read them)."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    live = i * page_tokens < length
    if window is not None:
        # the oldest query (j=0, position length - n_q) still attends
        # back to length - n_q - window + 1; reduces to the single-token
        # skip at n_q=1
        live &= (i + 1) * page_tokens > length - n_q - window + 1

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale      # (K*Gp, D)
        kc = k_ref[0, :, 0].astype(jnp.float32)             # (T, D)
        vc = v_ref[0, :, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (K*Gp, T)
        if soft_cap is not None:
            s = jnp.tanh(s / soft_cap) * soft_cap
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # row r = j * gp + g: query index j = r // gp; query j's absolute
        # position is length - n_q + j — the causal intra-block floor
        qpos = length - n_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // gp
        keep = pos <= qpos
        if window is not None:
            keep &= pos > qpos - window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_ref[:, :1]                               # (K*Gp, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if window is not None:
            # a live page can still be FULLY behind an older query's
            # window (live keys off the oldest floor, this row's floor is
            # later): that row's stats are all NEG_INF and exp(s - m)
            # would turn the masked row into uniform 1s — zero the masked
            # probabilities explicitly
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, vc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_multi_q(q, hkv: int, group: int, gp: int):
    """(B, K, Hq, D) -> (B, Hkv, K*gp, D): split GQA groups, pad each to a
    full sublane tile, stack query-major so the kernel's row -> query-index
    division is exact."""
    b, kq, hq, d = q.shape
    qr = q.reshape(b, kq, hkv, group, d)
    if gp != group:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, 0), (0, gp - group), (0, 0)))
    return qr.transpose(0, 2, 1, 3, 4).reshape(b, hkv, kq * gp, d)


def _paged_multi_o(out, kq: int, hq: int, group: int, gp: int):
    """(B, Hkv, K*gp, D) -> (B, K, Hq, D): undo _paged_multi_q, dropping
    the padded group rows."""
    b, hkv, _, d = out.shape
    o = out.reshape(b, hkv, kq, gp, d)[:, :, :, :group]
    return o.transpose(0, 2, 1, 3, 4).reshape(b, kq, hq, d)


def _paged_attention_multi_pallas(q, k_pages, v_pages, page_table, lengths,
                                  scale: float, interpret: bool,
                                  soft_cap: Optional[float] = None,
                                  window: Optional[int] = None) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, kq, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    gp = -(-group // 8) * 8
    qr = _paged_multi_q(q, hkv, group, gp)
    rows = kq * gp
    kernel = functools.partial(_paged_fwd_multi_kernel, page_tokens=t,
                               num_pages=n, n_q=kq, gp=gp, sm_scale=scale,
                               soft_cap=soft_cap, window=window)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, hkv, n),
        in_specs=[
            pl.BlockSpec((1, 1, rows, d),
                         lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rows, d),
                               lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, d), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      qr, k_pages, v_pages)
    return _paged_multi_o(out, kq, hq, group, gp)


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "logit_soft_cap",
                                             "sliding_window", "mesh",
                                             "shard_heads"))
def paged_attention_multi(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, page_table: jax.Array,
                          lengths: jax.Array, *,
                          sm_scale: Optional[float] = None,
                          use_pallas: Optional[bool] = None,
                          interpret: bool = False,
                          logit_soft_cap: Optional[float] = None,
                          sliding_window: Optional[int] = None,
                          mesh=None, shard_heads: bool = True) -> jax.Array:
    """``paged_attention`` over K query tokens per sequence (ISSUE 14):
    the multi-token form that speculative verify (K = k+1 drafts) and
    paged-native prefill chunks ride. q is (B, K, Hq, D); ``lengths``
    counts valid tokens INCLUDING the K being attended (query j sits at
    position lengths - K + j, and its KV row must already be written —
    the model steps scatter the block's K/V before dispatching), so the
    intra-block mask is causal: query j sees positions <= lengths - K + j.
    At K=1 this is exactly ``paged_attention``. Same page-table validity
    contract (entries at/after ceil(lengths/T) never read, must be valid
    ids), same sliding-window page-skip semantics (relative to the OLDEST
    query), same TP contract via ``mesh``/``shard_heads``. Returns
    (B, K, Hq, D) in q's dtype."""
    b, kq, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    if v_pages.shape != k_pages.shape:
        raise ValueError(f"k_pages {k_pages.shape} != v_pages "
                         f"{v_pages.shape}")
    if logit_soft_cap is not None and logit_soft_cap <= 0:
        raise ValueError(f"logit_soft_cap must be positive, "
                         f"got {logit_soft_cap}")
    if sliding_window is not None and sliding_window <= 0:
        raise ValueError(f"sliding_window must be positive, "
                         f"got {sliding_window}")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) \
        and d % 128 == 0 and t % 8 == 0

    def dispatch(qs, ks, vs, pt, ln):
        if not pallas_ok:
            return _paged_attention_multi_xla(qs, ks, vs, pt, ln,
                                              sm_scale=scale,
                                              logit_soft_cap=logit_soft_cap,
                                              sliding_window=sliding_window)
        return _paged_attention_multi_pallas(qs, ks, vs, pt, ln, scale,
                                             interpret, logit_soft_cap,
                                             sliding_window)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, hkv) if shard_heads else None
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, None, hs, None), P(None, None, hs, None),
             P(None, None, hs, None), P(), P()),
            P(None, None, hs, None),
            q, k_pages, v_pages, page_table, lengths)
    return dispatch(q, k_pages, v_pages, page_table, lengths)


def _paged_attention_multi_quant_xla(q, k_pages, v_pages, k_scale, v_scale,
                                     page_table, lengths, *, sm_scale: float,
                                     logit_soft_cap: Optional[float] = None,
                                     sliding_window: Optional[int] = None
                                     ) -> jax.Array:
    """Multi-token int8 reference: working-set gather first, dequantize
    only that (the memory-order argument of _paged_attention_quant_xla),
    then the per-query causal mask."""
    b, kq, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    k = (k_pages[page_table].astype(jnp.float32)
         * k_scale[page_table][..., None]).reshape(b, n * t, hkv, d)
    v = (v_pages[page_table].astype(jnp.float32)
         * v_scale[page_table][..., None]).reshape(b, n * t, hkv, d)
    qg = (q.astype(jnp.float32) * sm_scale).reshape(b, kq, hkv, group, d)
    s = jnp.einsum("bkhgd,bLhd->bkhgL", qg, k)
    if logit_soft_cap is not None:
        s = jnp.tanh(s / logit_soft_cap) * logit_soft_cap
    valid = _paged_valid_multi(n * t, lengths, kq, sliding_window)
    s = jnp.where(valid[:, :, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkhgL,bLhd->bkhgd", p, v)
    return o.reshape(b, kq, hq, d).astype(q.dtype)


def _paged_fwd_multi_quant_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref,
                                  ks_ref, vs_ref, o_ref, acc_ref, m_ref,
                                  l_ref, *, page_tokens: int, num_pages: int,
                                  n_kv: int, n_q: int, gp: int,
                                  sm_scale: float,
                                  soft_cap: Optional[float] = None,
                                  window: Optional[int] = None):
    """The multi-token kernel with int8 K/V pages dequantized in kernel —
    the iota head-select of _paged_fwd_quant_kernel composed with the
    per-row causal floor of _paged_fwd_multi_kernel."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    h = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    live = i * page_tokens < length
    if window is not None:
        live &= (i + 1) * page_tokens > length - n_q - window + 1

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale      # (K*Gp, D)
        hsel = jax.lax.broadcasted_iota(
            jnp.int32, (page_tokens, n_kv), 1) == h
        k_s = jnp.sum(jnp.where(hsel, ks_ref[0], 0.0), axis=1,
                      keepdims=True)                        # (T, 1)
        v_s = jnp.sum(jnp.where(hsel, vs_ref[0], 0.0), axis=1,
                      keepdims=True)
        kc = k_ref[0, :, 0].astype(jnp.float32) * k_s       # (T, D)
        vc = v_ref[0, :, 0].astype(jnp.float32) * v_s
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (K*Gp, T)
        if soft_cap is not None:
            s = jnp.tanh(s / soft_cap) * soft_cap
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        qpos = length - n_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // gp
        keep = pos <= qpos
        if window is not None:
            keep &= pos > qpos - window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if window is not None:
            # see _paged_fwd_multi_kernel: zero rows whose window starts
            # past this (live-for-the-oldest-query) page
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, vc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_multi_quant_pallas(q, k_pages, v_pages, k_scale,
                                        v_scale, page_table, lengths,
                                        scale: float, interpret: bool,
                                        soft_cap: Optional[float] = None,
                                        window: Optional[int] = None
                                        ) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, kq, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    n = page_table.shape[1]
    group = hq // hkv
    gp = -(-group // 8) * 8
    qr = _paged_multi_q(q, hkv, group, gp)
    rows = kq * gp
    kernel = functools.partial(_paged_fwd_multi_quant_kernel, page_tokens=t,
                               num_pages=n, n_kv=hkv, n_q=kq, gp=gp,
                               sm_scale=scale, soft_cap=soft_cap,
                               window=window)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, hkv, n),
        in_specs=[
            pl.BlockSpec((1, 1, rows, d),
                         lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
            pl.BlockSpec((1, t, 1, d),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, h, 0)),
            pl.BlockSpec((1, t, hkv),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((1, t, hkv),
                         lambda bb, h, i, pt, ln: (pt[bb, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rows, d),
                               lambda bb, h, i, pt, ln: (bb, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, d), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      qr, k_pages, v_pages, k_scale, v_scale)
    return _paged_multi_o(out, kq, hq, group, gp)


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "logit_soft_cap",
                                             "sliding_window", "mesh",
                                             "shard_heads"))
def paged_attention_multi_quant(q: jax.Array, k_pages: jax.Array,
                                v_pages: jax.Array, k_scale: jax.Array,
                                v_scale: jax.Array, page_table: jax.Array,
                                lengths: jax.Array, *,
                                sm_scale: Optional[float] = None,
                                use_pallas: Optional[bool] = None,
                                interpret: bool = False,
                                logit_soft_cap: Optional[float] = None,
                                sliding_window: Optional[int] = None,
                                mesh=None,
                                shard_heads: bool = True) -> jax.Array:
    """``paged_attention_multi`` over an int8-quantized KV arena: K query
    tokens, int8 pages dequantized in kernel (paged_attention_quant's
    scheme), per-query causal intra-block mask. Same shape/validity/TP
    contracts as paged_attention_multi with paged_attention_quant's scale
    sections."""
    b, kq, hq, d = q.shape
    _, t, hkv, _ = k_pages.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    if k_scale.shape != k_pages.shape[:3] \
            or v_scale.shape != v_pages.shape[:3]:
        raise ValueError(
            f"scale shapes {k_scale.shape}/{v_scale.shape} must be the "
            f"pages' (P, T, Hkv) = {k_pages.shape[:3]}")
    if logit_soft_cap is not None and logit_soft_cap <= 0:
        raise ValueError(f"logit_soft_cap must be positive, "
                         f"got {logit_soft_cap}")
    if sliding_window is not None and sliding_window <= 0:
        raise ValueError(f"sliding_window must be positive, "
                         f"got {sliding_window}")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) \
        and d % 128 == 0 and t % 8 == 0

    def dispatch(qs, ks, vs, kss, vss, pt, ln):
        if not pallas_ok:
            return _paged_attention_multi_quant_xla(
                qs, ks, vs, kss, vss, pt, ln, sm_scale=scale,
                logit_soft_cap=logit_soft_cap,
                sliding_window=sliding_window)
        return _paged_attention_multi_quant_pallas(
            qs, ks, vs, kss, vss, pt, ln, scale, interpret,
            logit_soft_cap, sliding_window)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, hkv) if shard_heads else None
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, None, hs, None), P(None, None, hs, None),
             P(None, None, hs, None), P(None, None, hs),
             P(None, None, hs), P(), P()),
            P(None, None, hs, None),
            q, k_pages, v_pages, k_scale, v_scale, page_table, lengths)
    return dispatch(q, k_pages, v_pages, k_scale, v_scale, page_table,
                    lengths)


def _paged_attention_multi_mla_xla(q_lat, q_rope, c_pages, kr_pages,
                                   page_table, lengths, *,
                                   sm_scale: float) -> jax.Array:
    """Multi-token MLA reference in the absorbed form, per-query causal
    mask over gathered latent pages."""
    b, kq, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    n = page_table.shape[1]
    c = c_pages[page_table].reshape(b, n * t, r).astype(jnp.float32)
    kr = kr_pages[page_table].reshape(b, n * t, -1).astype(jnp.float32)
    s = (jnp.einsum("bkhr,bLr->bkhL",
                    q_lat.astype(jnp.float32) * sm_scale, c)
         + jnp.einsum("bkhd,bLd->bkhL",
                      q_rope.astype(jnp.float32) * sm_scale, kr))
    valid = _paged_valid_multi(n * t, lengths, kq, None)
    s = jnp.where(valid[:, :, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkhL,bLr->bkhr", p, c)
    return o.astype(q_lat.dtype)


def _paged_fwd_multi_mla_kernel(pt_ref, len_ref, ql_ref, qr_ref, c_ref,
                                kr_ref, o_ref, acc_ref, m_ref, l_ref, *,
                                page_tokens: int, num_pages: int, n_q: int,
                                gp: int, sm_scale: float):
    """One (batch row, page) program over K queries' padded head blocks
    stacked query-major on the sublane axis (row = j * gp + h): headless
    latent pages stream once for all K queries, causality comes back from
    the row index."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]

    @pl.when(i * page_tokens < length)
    def _compute():
        ql = ql_ref[0].astype(jnp.float32) * sm_scale       # (K*Gp, R)
        qr = qr_ref[0].astype(jnp.float32) * sm_scale       # (K*Gp, Dr)
        cc = c_ref[0].astype(jnp.float32)                   # (T, R)
        krc = kr_ref[0].astype(jnp.float32)                 # (T, Dr)
        s = (jax.lax.dot_general(ql, cc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
             + jax.lax.dot_general(qr, krc, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32))
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        qpos = length - n_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // gp
        s = jnp.where(pos <= qpos, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, cc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_multi_mla_q(q, gp: int):
    """(B, K, Hq, R) -> (B, K*gp, R): pad the head axis to a sublane tile,
    stack query-major."""
    b, kq, hq, r = q.shape
    if gp != hq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, gp - hq), (0, 0)))
    return q.reshape(b, kq * gp, r)


def _paged_attention_multi_mla_pallas(q_lat, q_rope, c_pages, kr_pages,
                                      page_table, lengths, scale: float,
                                      interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, kq, hq, r = q_lat.shape
    # native-width latent blocks (see _paged_attention_mla_pallas)
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    n = page_table.shape[1]
    gp = -(-hq // 8) * 8
    ql = _paged_multi_mla_q(q_lat, gp)
    qr = _paged_multi_mla_q(q_rope, gp)
    rows = kq * gp
    kernel = functools.partial(_paged_fwd_multi_mla_kernel, page_tokens=t,
                               num_pages=n, n_q=kq, gp=gp, sm_scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, n),
        in_specs=[
            pl.BlockSpec((1, rows, r), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, rows, dr), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, t, r), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((1, t, dr), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, rows, r), lambda bb, i, pt, ln: (bb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, r), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, r), q_lat.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      ql, qr, c_pages, kr_pages)
    return out.reshape(b, kq, gp, r)[:, :, :hq]


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "mesh"))
def paged_attention_multi_mla(q_lat: jax.Array, q_rope: jax.Array,
                              c_pages: jax.Array, kr_pages: jax.Array,
                              page_table: jax.Array, lengths: jax.Array, *,
                              sm_scale: Optional[float] = None,
                              use_pallas: Optional[bool] = None,
                              interpret: bool = False,
                              mesh=None) -> jax.Array:
    """``paged_attention_mla`` over K query tokens (absorbed form): q_lat
    (B, K, Hq, R), q_rope (B, K, Hq, Dr); ``lengths`` includes the K
    tokens (paged_attention_multi's position convention). Returns the
    attention-weighted latent (B, K, Hq, R). Same native-width latent
    blocks and TP contract as paged_attention_mla."""
    b, kq, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    if q_rope.shape != (b, kq, hq, dr):
        raise ValueError(f"q_rope {q_rope.shape} != (B, K, Hq, Dr) = "
                         f"{(b, kq, hq, dr)}")
    if c_pages.shape[:2] != kr_pages.shape[:2]:
        raise ValueError(f"c_pages {c_pages.shape} / kr_pages "
                         f"{kr_pages.shape} disagree on (P, T)")
    scale = sm_scale if sm_scale is not None else (r + dr) ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) and t % 8 == 0

    def dispatch(ql, qr, cp, krp, pt, ln):
        if not pallas_ok:
            return _paged_attention_multi_mla_xla(ql, qr, cp, krp, pt, ln,
                                                  sm_scale=scale)
        return _paged_attention_multi_mla_pallas(ql, qr, cp, krp, pt, ln,
                                                 scale, interpret)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, None)
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, None, hs, None), P(None, None, hs, None),
             P(), P(), P(), P()),
            P(None, None, hs, None),
            q_lat, q_rope, c_pages, kr_pages, page_table, lengths)
    return dispatch(q_lat, q_rope, c_pages, kr_pages, page_table, lengths)


def _paged_attention_multi_mla_quant_xla(q_lat, q_rope, c_pages, kr_pages,
                                         c_scale, kr_scale, page_table,
                                         lengths, *,
                                         sm_scale: float) -> jax.Array:
    """Multi-token int8-latent MLA reference: working-set gather,
    per-position dequant, per-query causal mask."""
    b, kq, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    n = page_table.shape[1]
    c = (c_pages[page_table].astype(jnp.float32)
         * c_scale[page_table][..., None]).reshape(b, n * t, r)
    kr = (kr_pages[page_table].astype(jnp.float32)
          * kr_scale[page_table][..., None]).reshape(b, n * t, -1)
    s = (jnp.einsum("bkhr,bLr->bkhL",
                    q_lat.astype(jnp.float32) * sm_scale, c)
         + jnp.einsum("bkhd,bLd->bkhL",
                      q_rope.astype(jnp.float32) * sm_scale, kr))
    valid = _paged_valid_multi(n * t, lengths, kq, None)
    s = jnp.where(valid[:, :, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkhL,bLr->bkhr", p, c)
    return o.astype(q_lat.dtype)


def _paged_fwd_multi_mla_quant_kernel(pt_ref, len_ref, ql_ref, qr_ref,
                                      c_ref, kr_ref, cs_ref, krs_ref, o_ref,
                                      acc_ref, m_ref, l_ref, *,
                                      page_tokens: int, num_pages: int,
                                      n_q: int, gp: int, sm_scale: float):
    """Multi-token int8-latent MLA kernel: the score-space dequant of
    _paged_fwd_mla_quant_kernel (per-position scales broadcast on lanes,
    never transposed) composed with the per-row causal floor."""
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]

    @pl.when(i * page_tokens < length)
    def _compute():
        ql = ql_ref[0].astype(jnp.float32) * sm_scale       # (K*Gp, R)
        qr = qr_ref[0].astype(jnp.float32) * sm_scale       # (K*Gp, Dr)
        cc = c_ref[0].astype(jnp.float32)                   # (T, R) int8->f32
        krc = kr_ref[0].astype(jnp.float32)                 # (T, Dr)
        cs = cs_ref[...]                                    # (1, T) f32
        krs = krs_ref[...]
        s = (jax.lax.dot_general(ql, cc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * cs
             + jax.lax.dot_general(qr, krc, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * krs)
        pos = i * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        qpos = length - n_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // gp
        s = jnp.where(pos <= qpos, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p * cs, cc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_multi_mla_quant_pallas(q_lat, q_rope, c_pages,
                                            kr_pages, c_scale, kr_scale,
                                            page_table, lengths,
                                            scale: float,
                                            interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, kq, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    n = page_table.shape[1]
    gp = -(-hq // 8) * 8
    ql = _paged_multi_mla_q(q_lat, gp)
    qr = _paged_multi_mla_q(q_rope, gp)
    rows = kq * gp
    kernel = functools.partial(_paged_fwd_multi_mla_quant_kernel,
                               page_tokens=t, num_pages=n, n_q=kq, gp=gp,
                               sm_scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, n),
        in_specs=[
            pl.BlockSpec((1, rows, r), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, rows, dr), lambda bb, i, pt, ln: (bb, 0, 0)),
            pl.BlockSpec((1, t, r), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((1, t, dr), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((1, t), lambda bb, i, pt, ln: (pt[bb, i], 0)),
            pl.BlockSpec((1, t), lambda bb, i, pt, ln: (pt[bb, i], 0)),
        ],
        out_specs=pl.BlockSpec((1, rows, r), lambda bb, i, pt, ln: (bb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, r), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, r), q_lat.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      ql, qr, c_pages, kr_pages, c_scale, kr_scale)
    return out.reshape(b, kq, gp, r)[:, :, :hq]


@functools.partial(jax.jit, static_argnames=("sm_scale", "use_pallas",
                                             "interpret", "mesh"))
def paged_attention_multi_mla_quant(q_lat: jax.Array, q_rope: jax.Array,
                                    c_pages: jax.Array, kr_pages: jax.Array,
                                    c_scale: jax.Array, kr_scale: jax.Array,
                                    page_table: jax.Array,
                                    lengths: jax.Array, *,
                                    sm_scale: Optional[float] = None,
                                    use_pallas: Optional[bool] = None,
                                    interpret: bool = False,
                                    mesh=None) -> jax.Array:
    """``paged_attention_multi_mla`` over an int8-quantized latent arena:
    K query tokens, score-space in-kernel dequant
    (paged_attention_mla_quant's scheme), per-query causal mask. Same
    contracts as paged_attention_multi_mla with
    paged_attention_mla_quant's scale sections."""
    b, kq, hq, r = q_lat.shape
    _, t, _ = c_pages.shape
    dr = kr_pages.shape[2]
    if q_rope.shape != (b, kq, hq, dr):
        raise ValueError(f"q_rope {q_rope.shape} != (B, K, Hq, Dr) = "
                         f"{(b, kq, hq, dr)}")
    if c_pages.shape[:2] != kr_pages.shape[:2]:
        raise ValueError(f"c_pages {c_pages.shape} / kr_pages "
                         f"{kr_pages.shape} disagree on (P, T)")
    if c_scale.shape != c_pages.shape[:2] \
            or kr_scale.shape != kr_pages.shape[:2]:
        raise ValueError(
            f"scale shapes {c_scale.shape}/{kr_scale.shape} must be the "
            f"pages' (P, T) = {c_pages.shape[:2]}")
    scale = sm_scale if sm_scale is not None else (r + dr) ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) and t % 8 == 0

    def dispatch(ql, qr, cp, krp, cs, krs, pt, ln):
        if not pallas_ok:
            return _paged_attention_multi_mla_quant_xla(
                ql, qr, cp, krp, cs, krs, pt, ln, sm_scale=scale)
        return _paged_attention_multi_mla_quant_pallas(
            ql, qr, cp, krp, cs, krs, pt, ln, scale, interpret)

    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P
        hs = _paged_head_specs(mesh, hq, None)
        return _shard_paged_call(
            mesh, dispatch,
            (P(None, None, hs, None), P(None, None, hs, None),
             P(), P(), P(), P(), P(), P()),
            P(None, None, hs, None),
            q_lat, q_rope, c_pages, kr_pages, c_scale, kr_scale,
            page_table, lengths)
    return dispatch(q_lat, q_rope, c_pages, kr_pages, c_scale, kr_scale,
                    page_table, lengths)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "use_pallas",
                                             "block_q", "block_k", "interpret",
                                             "sliding_window",
                                             "logit_soft_cap"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    use_pallas: Optional[bool] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: bool = False,
                    sliding_window: Optional[int] = None,
                    logit_soft_cap: Optional[float] = None) -> jax.Array:
    """Multi-head attention with GQA. Shapes: q (B,Hq,S,D), k/v (B,Hkv,S,D).
    ``block_q``/``block_k`` default to the per-generation tuned pick.
    ``interpret=True`` forces the Pallas kernels through the interpreter
    (CPU-testable path for the exact kernel code). ``sliding_window``
    (Mistral-style) limits each query to the last W positions — the causal
    kernels skip blocks fully outside the band, so long-context windowed
    attention costs O(S*W) not O(S^2). ``logit_soft_cap`` (Gemma-2-style)
    passes scores through cap*tanh(s/cap) before masking; the backward
    kernels carry the tanh derivative exactly."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    if sliding_window is not None:
        if not causal:
            raise ValueError("sliding_window requires causal attention")
        if sliding_window <= 0:
            raise ValueError(f"sliding_window must be positive, "
                             f"got {sliding_window}")
    if logit_soft_cap is not None and logit_soft_cap <= 0:
        raise ValueError(f"logit_soft_cap must be positive, "
                         f"got {logit_soft_cap}")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    auto_q, auto_k = tuned_block_sizes(sq, sk)
    bq = block_q or auto_q
    bk = block_k or auto_k
    pallas_ok = (_use_pallas(use_pallas) or interpret) and bq and bk and \
        sq % bq == 0 and sk % bk == 0 and sq >= bq
    if not pallas_ok:
        return _attention_xla(q, k, v, causal=causal, sm_scale=scale,
                              sliding_window=sliding_window,
                              logit_soft_cap=logit_soft_cap)
    return _flash_diff(q, k, v, causal, scale, bq, bk, interpret,
                       sliding_window, logit_soft_cap)
