"""Flash attention: blockwise online-softmax Pallas TPU kernel + XLA fallback.

Kernel shape: grid over (batch, q_heads, q_blocks); K/V for the matching KV
head (GQA native — no repeat materialization) live in VMEM and are consumed in
block_k chunks with the online-softmax recurrence, so HBM sees each K/V tile
once and the (S, S) score matrix never exists. Causal programs stop at their
diagonal block (no wasted FLOPs past it).

Layout: q (B, Hq, S, D); k, v (B, Hkv, S, D); Hq % Hkv == 0.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .common import use_pallas as _use_pallas

NEG_INF = -1e30


def _attention_xla(q, k, v, *, causal: bool, sm_scale: float,
                   q_offset: int = 0) -> jax.Array:
    """Reference/fallback path; identical math, XLA-fused."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    qf = q.astype(jnp.float32) * sm_scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    qg = qf.reshape(b, hkv, group, sq, d)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kf)
    if causal:
        q_pos = jnp.arange(sq) + q_offset
        k_pos = jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p, vf)
    return o.reshape(b, hq, sq, d).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  seq_k: int, causal: bool, sm_scale: float):
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale          # (bq, d)
    d = q.shape[-1]

    num_k_blocks = seq_k // block_k
    if causal:
        # highest k index this q block can see: (qi+1)*block_q - 1
        last = (qi + 1) * block_q - 1
        k_blocks = jnp.minimum((last // block_k) + 1, num_k_blocks)
    else:
        k_blocks = num_k_blocks

    def body(j, carry):
        acc, m, l = carry
        kc = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vc = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, vc, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, k_blocks, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_pallas(q, k, v, causal: bool, scale: float, block_q: int, block_k: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    kernel = functools.partial(_flash_kernel, block_q=block_q, block_k=block_k,
                               seq_k=sk, causal=causal, sm_scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(b, hq, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i: (bb, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, sk, d), lambda bb, h, i: (bb, h // group, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, sk, d), lambda bb, h, i: (bb, h // group, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i: (bb, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal, scale, block_q, block_k):
    return _flash_pallas(q, k, v, causal, scale, block_q, block_k)


def _flash_diff_fwd(q, k, v, causal, scale, block_q, block_k):
    return _flash_pallas(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _flash_diff_bwd(causal, scale, block_q, block_k, res, g):
    # Backward recomputes through the XLA reference path (same math as the
    # kernel) — flash-attention's standard recompute-in-bwd trade, with XLA
    # doing the fusion. A fused Pallas bwd kernel can slot in here later.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_xla(q_, k_, v_, causal=causal,
                                          sm_scale=scale), q, k, v)
    return vjp(g)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "use_pallas",
                                             "block_q", "block_k"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    use_pallas: Optional[bool] = None,
                    block_q: int = 128, block_k: int = 128) -> jax.Array:
    """Multi-head attention with GQA. Shapes: q (B,Hq,S,D), k/v (B,Hkv,S,D)."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if (not _use_pallas(use_pallas) or sq % block_q != 0 or sk % block_k != 0
            or sq < block_q):
        return _attention_xla(q, k, v, causal=causal, sm_scale=scale)
    return _flash_diff(q, k, v, causal, scale, block_q, block_k)
