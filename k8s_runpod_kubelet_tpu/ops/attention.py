"""Flash attention: blockwise online-softmax Pallas TPU kernels + XLA fallback.

Forward: grid over (batch, q_heads, q_blocks); K/V for the matching KV head
(GQA native — no repeat materialization) live in VMEM and are consumed in
block_k chunks with the online-softmax recurrence, so HBM sees each K/V tile
once and the (S, S) score matrix never exists. Causal programs stop at their
diagonal block (no wasted FLOPs past it). The kernel also emits the row
log-sum-exp, which makes the backward exact without re-running the softmax
reduction.

Backward: two Pallas kernels (the standard flash-attention split):
  - dQ:    grid (b, hq, q_blocks); streams K/V tiles, rebuilds p from the
           saved LSE, accumulates dq = sum_j (p∘(dp-δ)) Kj.
  - dK/dV: grid (b, hq, k_blocks); streams Q/dO tiles, accumulates per-q-head
           dk/dv, which XLA then sum-reduces over each GQA group.
δ = rowsum(dO ∘ O) is precomputed in XLA. All matmuls run in the input dtype
with f32 accumulation (MXU-native); only softmax/statistics math is f32.
No (S, S) buffer exists in either direction — memory stays O(S·d) per
program, which is what lets long-context batches fit HBM.

Layout: q (B, Hq, S, D); k, v (B, Hkv, S, D); Hq % Hkv == 0.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .common import use_pallas as _use_pallas

NEG_INF = -1e30


def _attention_xla(q, k, v, *, causal: bool, sm_scale: float,
                   q_offset: int = 0) -> jax.Array:
    """Reference/fallback path; identical math, XLA-fused. Matmuls stay in
    the input dtype with f32 accumulation (bf16 inputs keep the MXU on its
    fast path); softmax statistics are f32."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    qg = (q * jnp.asarray(sm_scale, q.dtype)).reshape(b, hkv, group, sq, d)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32)
    if causal:
        q_pos = jnp.arange(sq) + q_offset
        k_pos = jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(q.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.reshape(b, hq, sq, d).astype(q.dtype)


# -- forward kernel -----------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                block_k: int, seq_k: int, causal: bool, sm_scale: float):
    import jax.experimental.pallas as pl  # noqa: F401 (kernel-only import)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale          # (bq, d)
    d = q.shape[-1]

    num_k_blocks = seq_k // block_k
    if causal:
        # highest k index this q block can see: (qi+1)*block_q - 1
        last = (qi + 1) * block_q - 1
        k_blocks = jnp.minimum((last // block_k) + 1, num_k_blocks)
    else:
        k_blocks = num_k_blocks

    def body(j, carry):
        acc, m, l = carry
        kc = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vc = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, vc, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, k_blocks, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _flash_fwd_pallas(q, k, v, causal: bool, scale: float, block_q: int,
                      block_k: int, interpret: bool = False):
    from jax.experimental import pallas as pl

    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    kernel = functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                               seq_k=sk, causal=causal, sm_scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(b, hq, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bb, h, i: (bb, h // group, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bb, h, i: (bb, h // group, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bb, h, i: (bb, h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# -- backward kernels ---------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_q: int, block_k: int, seq_k: int, causal: bool,
               sm_scale: float):
    import jax.experimental.pallas as pl  # noqa: F401
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale           # (bq, d)
    do = do_ref[0, 0].astype(jnp.float32)                    # (bq, d)
    lse = lse_ref[0, 0][:, None]                             # (bq, 1)
    delta = delta_ref[0, 0][:, None]                         # (bq, 1)
    d = q.shape[-1]

    num_k_blocks = seq_k // block_k
    if causal:
        last = (qi + 1) * block_q - 1
        k_blocks = jnp.minimum((last // block_k) + 1, num_k_blocks)
    else:
        k_blocks = num_k_blocks

    def body(j, dq):
        kc = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vc = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (bq, bk)
        dp = jax.lax.dot_general(do, vc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, kc, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, k_blocks, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = (dq * sm_scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q: int, block_k: int, seq_q: int,
                causal: bool, sm_scale: float):
    import jax.experimental.pallas as pl  # noqa: F401
    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)                      # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)                      # (bk, d)
    d = k.shape[-1]

    num_q_blocks = seq_q // block_q
    # causal: q blocks strictly before this k block's first row see nothing
    q_start = (ki * block_k) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        qc = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        doc = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(qc * sm_scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (bq, bk)
        dv_new = dv + jax.lax.dot_general(
            p, doc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)
        dp = jax.lax.dot_general(doc, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                # (bq, bk)
        dk_new = dk + jax.lax.dot_general(
            ds, qc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_start, num_q_blocks, body, (dk0, dv0))
    dk_ref[0, 0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal: bool, scale: float,
                      block_q: int, block_k: int, interpret: bool = False):
    from jax.experimental import pallas as pl

    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                  # (b, hq, sq)

    dq_kernel = functools.partial(_dq_kernel, block_q=block_q,
                                  block_k=block_k, seq_k=sk, causal=causal,
                                  sm_scale=scale)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, hq, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bb, h, i: (bb, h // group, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bb, h, i: (bb, h // group, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bb, h, i: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bb, h, i: (bb, h, i)),
            pl.BlockSpec((1, 1, block_q), lambda bb, h, i: (bb, h, i)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bb, h, i: (bb, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dkv_kernel = functools.partial(_dkv_kernel, block_q=block_q,
                                   block_k=block_k, seq_q=sq, causal=causal,
                                   sm_scale=scale)
    # per-q-head dk/dv (f32 accumulators); the GQA group-sum happens in XLA
    dk_h, dv_h = pl.pallas_call(
        dkv_kernel,
        grid=(b, hq, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, sq, d), lambda bb, h, j: (bb, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, h, j: (bb, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, h, j: (bb, h // group, j, 0)),
            pl.BlockSpec((1, 1, sq, d), lambda bb, h, j: (bb, h, 0, 0)),
            pl.BlockSpec((1, 1, sq), lambda bb, h, j: (bb, h, 0)),
            pl.BlockSpec((1, 1, sq), lambda bb, h, j: (bb, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bb, h, j: (bb, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, h, j: (bb, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk = dk_h.reshape(b, hkv, group, sk, d).sum(axis=2).astype(k.dtype)
    dv = dv_h.reshape(b, hkv, group, sk, d).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


# -- differentiable wrapper ---------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_diff(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                             interpret)
    return o


def _flash_diff_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                               interpret)
    return o, (q, k, v, o, lse)


def _flash_diff_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale, block_q,
                             block_k, interpret)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "use_pallas",
                                             "block_q", "block_k", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    use_pallas: Optional[bool] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False) -> jax.Array:
    """Multi-head attention with GQA. Shapes: q (B,Hq,S,D), k/v (B,Hkv,S,D).
    ``interpret=True`` forces the Pallas kernels through the interpreter
    (CPU-testable path for the exact kernel code)."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    pallas_ok = (_use_pallas(use_pallas) or interpret) and \
        sq % block_q == 0 and sk % block_k == 0 and sq >= block_q
    if not pallas_ok:
        return _attention_xla(q, k, v, causal=causal, sm_scale=scale)
    return _flash_diff(q, k, v, causal, scale, block_q, block_k, interpret)
