"""Pallas TPU kernel: matmul against int4-packed weights, unpacked in VMEM.

Why a kernel: XLA:TPU fuses epilogues into a dot but NOT elementwise
producer chains into the dot's operands, so the nibble unpack
(mask/shift/offset/cast) of `models/quant._quantize_leaf_int4` weights
materializes somewhere between HBM and the MXU. The AOT cost model measured
it (bench_results/aot_v5e.json): an interleave-based XLA path tripled the
int8 decode bytes (19.6GB vs 6.3GB), and even the fusion-friendly even/odd
split still accessed 9.0GB — the dequantized planes land in HBM. This
kernel streams the PACKED bytes HBM->VMEM (Pallas double-buffers the
innermost grid dim), unpacks in registers, and accumulates — HBM traffic is
the int4 payload, a quarter of bf16 and half of int8, which is the whole
point of 4-bit weights on a bandwidth-bound decode.

Layout contract (quant.py): q4 (in/2, out) uint8 — in-element 2i in the low
nibble, 2i+1 in the high; scale (g, 1, out) f32, one group per 128
(INT4_GROUP) contraction elements. The kernel contracts h's even strides
against the low-nibble plane and odd strides against the high plane — the
planes stay contiguous (no interleave permute), and both halves of a group
share its scale, applied to the per-group partial AFTER the matmul.

Grid (n_out, g): out-tiles parallel, groups innermost/sequential; one
scale group per in step keeps the scale application exact. Forward-only
(serving decode/prefill); there is deliberately no VJP — training never
sees int4 weights.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .common import tpu_compiler_params, use_pallas as _use_pallas

__all__ = ["int4_matmul", "int4_matmul_sharded", "int4_expert_matmul"]


def _pick_block_out(out: int, cap: int = 512) -> int:
    for b in range(min(cap, out), 127, -128):
        if out % b == 0:
            return b
    return out  # out < 128 or no 128-multiple divisor: whole axis


def _matmul_2d(h2, q4, scale, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, kin = h2.shape
    kin2, out = q4.shape
    g = scale.shape[0]
    half = kin2 // g
    block_out = _pick_block_out(out)
    # row blocks must tile (8, ...): pad the handful of decode rows up
    pad = (-b) % 8
    if pad:
        h2 = jnp.pad(h2, ((0, pad), (0, 0)))
    he = h2[:, 0::2].reshape(h2.shape[0], g, half).swapaxes(0, 1)  # (g, B, half)
    ho = h2[:, 1::2].reshape(h2.shape[0], g, half).swapaxes(0, 1)
    q4g = q4.reshape(g, half, out)
    res = pl.pallas_call(
        functools.partial(_kernel, n_in=g),
        grid=(out // block_out, g),
        in_specs=[
            pl.BlockSpec((1, h2.shape[0], half), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((1, h2.shape[0], half), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((1, half, block_out), lambda i, j: (j, 0, i)),
            pl.BlockSpec((1, 1, block_out), lambda i, j: (j, 0, i)),
        ],
        out_specs=pl.BlockSpec((h2.shape[0], block_out), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((h2.shape[0], out), h2.dtype),
        scratch_shapes=[pltpu.VMEM((h2.shape[0], block_out), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(he, ho, q4g, scale)
    return res[:b] if pad else res


def _kernel(he_ref, ho_ref, q4_ref, scale_ref, o_ref, acc_ref, *, n_in: int):
    # refs carry a leading singleton group axis from the blocked layout
    import jax.experimental.pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # nibble math in int32: Mosaic has no 8-bit subi legalization (the
    # first kernel draft died there); i32 ops are native and the tiles are
    # register-resident anyway
    q = q4_ref[0].astype(jnp.int32)                   # (half, out_t)
    dt = he_ref.dtype
    lo = ((q & 0xF) - 8).astype(dt)
    hi = ((q >> 4) - 8).astype(dt)
    part = (jax.lax.dot(he_ref[0], lo, preferred_element_type=jnp.float32)
            + jax.lax.dot(ho_ref[0], hi, preferred_element_type=jnp.float32))
    acc_ref[...] += part * scale_ref[0, 0, :]

    @pl.when(j == n_in - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _fallback_2d(h2, q4, scale):
    # fallback compute in f32 throughout: exact for the integer nibbles,
    # matches the kernel's f32 accumulation, and sidesteps CPU dot thunks
    # that reject mixed bf16-operand/f32-result dots; the cast back to
    # h.dtype is the only rounding
    kin2, out = q4.shape
    g = scale.shape[0]
    half = kin2 // g
    lo = ((q4 & 0xF).astype(jnp.int8) - 8).astype(jnp.float32)
    hi = ((q4 >> 4).astype(jnp.int8) - 8).astype(jnp.float32)
    hf = h2.astype(jnp.float32)
    he = hf[:, 0::2].reshape(h2.shape[0], g, half)
    ho = hf[:, 1::2].reshape(h2.shape[0], g, half)
    part = (jnp.einsum("bgk,gko->bgo", he, lo.reshape(g, half, out))
            + jnp.einsum("bgk,gko->bgo", ho, hi.reshape(g, half, out)))
    return jnp.einsum("bgo,go->bo", part, scale[:, 0, :]).astype(h2.dtype)


def _dispatch_2d(h2, q4, scale):
    """Backend pick at trace time: Pallas kernel on TPU, XLA fallback
    elsewhere. Shared by the unpartitioned path and the per-shard
    lower_fn, so single-chip and sharded serving run the same kernel."""
    if _use_pallas(None):
        return _matmul_2d(h2, q4, scale, interpret=False)
    return _fallback_2d(h2, q4, scale)


# -- tensor-parallel int4 (shard_map) ---------------------------------------
#
# A pallas_call is an opaque custom call: the SPMD partitioner cannot shard
# it on its own, which is why int4 and --tensor-parallel used to be
# mutually exclusive. shard_map supplies the missing partitioning — same
# mechanism as ops/ring_attention.py, and unlike custom_partitioning its
# manual sharding lives IN the IR, so the AOT evidence tool can compile it
# without a live backend (custom_partitioning's Python callback has no
# emitter under the device-less compile client: "Custom emitter for
# CustomSPMDPartitioning not found").
#
# Layout contract with quant.quantized_logical_axes(bits=4): every int4
# weight shards its OUTPUT axis over `tensor`, packed contraction + group
# axes replicated. Per shard the kernel runs unmodified on its out-slice
# with the FULL contraction — no psum, groups never straddle shard
# boundaries, and the WEIGHTS (the 4-bit point of all this) stay fully
# distributed. Activations replicate going in (KBs per decode step vs the
# GBs of weight traffic the sharding splits); serving meshes are
# tensor-only, so the blanket P() on h costs nothing extra.


def int4_matmul_sharded(h: jax.Array, q4: jax.Array, scale: jax.Array,
                        mesh, axis: str = "tensor") -> jax.Array:
    """Tensor-parallel int4 matmul: out-sharded weights, per-shard kernel.
    ``q4``/``scale`` must be placed with their out axis sharded over
    ``axis`` (quantized_logical_axes bits=4 does this)."""
    from jax.sharding import PartitionSpec as P
    from .ring_attention import shard_map_compat

    kin = h.shape[-1]
    out = q4.shape[1]
    h2 = h.reshape(-1, kin)
    fn = shard_map_compat(
        _dispatch_2d, mesh,
        in_specs=(P(), P(None, axis), P(None, None, axis)),
        out_specs=P(None, axis),
        # no replication rule exists for pallas_call, and h replicates
        # over every mesh axis (and the weights over any >1 axis beyond
        # ``axis``, e.g. ``expert`` on an EP x TP serving mesh) — the
        # older-jax rep check cannot type this even though the values
        # are replicated (shard_map_compat docstring)
        check=False)
    return fn(h2, q4, scale).reshape(*h.shape[:-1], out)


def int4_expert_matmul(h: jax.Array, q4: jax.Array,
                       scale: jax.Array) -> jax.Array:
    """Batched per-expert int4 matmul: h (X, C, in) @ q4 (X, in/2, out) ->
    (X, C, out), scale (X, g, 1, out).

    Each expert's (capacity, in) tokens contract against its own packed
    weight through the SAME 2D kernel/fallback dispatch as the dense path
    (_dispatch_2d) — ``lax.map`` compiles the kernel ONCE and runs it per
    expert, so a 256-expert layer does not trace 256 kernels. MoE decode
    is expert-weight-bandwidth-bound exactly like dense decode, so the
    packed-payload HBM story carries over unchanged. Called per expert
    SHARD under moe._expert_ffn_sharded (shard_map partitions the expert
    axis; inside the body this sees only the local X/ep experts)."""
    def one(args):
        h_i, q_i, s_i = args
        return _dispatch_2d(h_i, q_i, s_i)

    return jax.lax.map(one, (h, q4, scale))


def int4_matmul(h: jax.Array, q4: jax.Array, scale: jax.Array,
                use_pallas: Optional[bool] = None,
                interpret: bool = False) -> jax.Array:
    """h (..., in) @ packed int4 weight (in/2, out) -> (..., out).

    Kernel path on TPU (or ``interpret=True`` anywhere); XLA even/odd-split
    fallback otherwise — same contraction order and f32 accumulation/scale
    discipline (the fallback simply computes in f32 end to end, exact for
    the integer nibbles), so the two paths agree to the final h.dtype
    rounding; used by tests as the parity reference and by CPU/sharded
    paths. Mesh serving goes through ``int4_matmul_sharded``."""
    kin = h.shape[-1]
    out = q4.shape[1]
    h2 = h.reshape(-1, kin)
    if _use_pallas(use_pallas) or interpret:
        res = _matmul_2d(h2, q4, scale, interpret)
    else:
        res = _fallback_2d(h2, q4, scale)
    return res.reshape(*h.shape[:-1], out)
