"""Ring attention: sequence/context parallelism over the mesh's ``seq`` axis.

Long-context path (SURVEY.md §5.7): the sequence is sharded across devices;
each device keeps its Q shard resident and the K/V shards rotate around the
ring via ``lax.ppermute`` (ICI neighbor exchange), with the online-softmax
recurrence merging each visiting chunk — so attention over a sequence S costs
each device O(S_local * S) compute and O(S_local) memory, and the K/V transfer
overlaps with the chunk compute that XLA schedules.

Built on shard_map so the collective schedule is explicit; the per-chunk math
matches ops/attention.py exactly (same masks, same recurrence).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..parallel.mesh import AXES
from .attention import (NEG_INF, _flash_bwd_pallas, _flash_fwd_pallas,
                        tuned_block_sizes)


def _chunk_update(q, kc, vc, acc, m, l, *, q_offset, k_offset, causal, sm_scale,
                  soft_cap=None, window=None):
    """One online-softmax step: fold K/V chunk (global offset k_offset) into the
    running (acc, m, l) for Q (global offset q_offset). Shapes:
    q (B,Hq,Sq,D), kc/vc (B,Hkv,Sk,D); GQA via group reshape.

    ``soft_cap`` (Gemma-2): cap*tanh(s/cap) before the mask — same
    scale→cap→mask order as ops/attention.py, and because this path is
    plain jnp, JAX autodiff carries the tanh derivative exactly (the
    Pallas kernels do it by hand; here it is free). ``window``: the
    sliding-window band mask, composed with causal."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = kc.shape
    group = hq // hkv
    qg = (q.astype(jnp.float32) * sm_scale).reshape(b, hkv, group, sq, d)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kc.astype(jnp.float32))
    s = s.reshape(b, hq, sq, sk)
    if soft_cap is not None:
        s = jnp.tanh(s / soft_cap) * soft_cap
    if causal:
        q_pos = q_offset + jnp.arange(sq)
        k_pos = k_offset + jnp.arange(sk)
        keep = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            keep &= (q_pos[:, None] - k_pos[None, :]) < window
        s = jnp.where(keep[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    pg = p.reshape(b, hkv, group, sq, sk)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", pg, vc.astype(jnp.float32))
    acc_new = acc * corr + o.reshape(b, hq, sq, d)
    return acc_new, m_new, l_new


def shard_map_compat(f, mesh, in_specs, out_specs, check: bool = True):
    """shard_map with vma typing off when the kwarg exists: pallas_call
    out_shapes carry no vma annotations, which jax>=0.8 shard_map rejects
    under its default varying-mesh-axes typing. Only the CONSTRUCTOR probe
    sits in the try: a TypeError from tracing ``f`` later must surface as
    itself, not as a retry.

    ``check=False`` additionally disables the replication CHECK on older
    jax (check_rep): a pallas_call whose inputs are replicated over an
    unmentioned mesh axis has no replication rule there, so bodies like
    the int4 expert FFN (moe._expert_ffn_sharded, weights replicated over
    ``tensor``) cannot type-check even though the values ARE replicated."""
    try:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover — older jax: no check_vma kwarg
        if not check:
            return shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


def _ring_steps(n: int, s_local: int, window: Optional[int]) -> int:
    """How many ring steps carry any in-band work. Step t's chunk sits at
    the FIXED offset delta = t*s_local behind the local queries (for the
    devices where it is relevant at all), so with a sliding window the
    band dies at a STATIC step: min qpos-kpos in step t is
    (t-1)*s_local + 1 > window-1 => chunk fully out of band. Truncating
    the loop there saves both the chunk compute and the remaining K/V
    rotations — the O(S·W) block-skip property, at ring granularity."""
    if window is None:
        return n
    # step t relevant iff t*s_local - (s_local - 1) < window
    t_max = (window + s_local - 2) // s_local  # last relevant step index
    return min(n, t_max + 1)


def _ring_flash(qs, ks, vs, idx, *, n: int, axis: str, scale: float,
                window: Optional[int], soft_cap: Optional[float],
                block_q: int, block_k: int, interpret: bool):
    """Ring attention with the STREAMED Pallas kernels per chunk ("ring
    flash attention"): each visiting K/V chunk runs the flash forward at
    its static global offset (t*s_local), chunk outputs merge by their
    row log-sum-exp, and the backward makes the same ring pass feeding
    the kernels the GLOBAL (o, lse) — exp(s - lse_global) is exactly the
    global softmax row, so per-chunk grads sum to the exact gradient.
    The XLA fallback path (_chunk_update) materializes each (Sq, Sk)
    score chunk in HBM twice per step; the kernels stream it through
    VMEM. Shapes per device: qs (B,Hq,Sq,D), ks/vs (B,Hkv,Sq,D)."""
    s_local = qs.shape[2]
    steps = _ring_steps(n, s_local, window)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def chunk_fwd(qs, t, kc, vc):
        # t == 0: the device's own chunk — plain causal (+band). t >= 1:
        # every k precedes every q by the fixed delta; causal=True stays
        # correct (the mask test is always true) and the window mask/skip
        # prune in-chunk blocks outside the band. qs is threaded, not
        # closed over: custom_vjp re-traces with fresh tracers.
        return _flash_fwd_pallas(qs, kc, vc, True, scale, block_q, block_k,
                                 interpret, window, soft_cap,
                                 q_offset=t * s_local)

    def fwd_pass(qs, ks, vs, idx):
        b, hq, sq, d = qs.shape
        o_acc = jnp.zeros((b, hq, sq, d), jnp.float32)
        lse_acc = jnp.full((b, hq, sq, 1), NEG_INF, jnp.float32)
        kc, vc = ks, vs
        for t in range(steps):
            def run(qs=qs, kc=kc, vc=vc, t=t):
                o_c, lse_c = chunk_fwd(qs, t, kc, vc)
                return o_c.astype(jnp.float32), lse_c

            def skip():
                return (jnp.zeros_like(o_acc),
                        jnp.full_like(lse_acc, NEG_INF))

            # relevance is per-DEVICE (idx >= t: devices near the ring
            # start have fewer prior chunks); both branches cost one
            # kernel shape, cond picks at runtime. t=0 (the diagonal) is
            # always relevant, so lse_acc is finite from the first merge
            # and the -inf/-inf nan case never arises.
            o_c, lse_c = jax.lax.cond(idx >= t, run, skip)
            new_lse = jnp.logaddexp(lse_acc, lse_c)
            o_acc = (o_acc * jnp.exp(lse_acc - new_lse)
                     + o_c * jnp.exp(lse_c - new_lse))
            lse_acc = new_lse
            if t + 1 < steps:
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
        return o_acc.astype(qs.dtype), lse_acc

    @jax.custom_vjp
    def ring(qs, ks, vs, idx):
        return fwd_pass(qs, ks, vs, idx)[0]

    def ring_fwd(qs, ks, vs, idx):
        o, lse = fwd_pass(qs, ks, vs, idx)
        return o, (qs, ks, vs, o, lse, idx)

    def ring_bwd(res, g):
        qs, ks, vs, o, lse, idx = res
        dq = jnp.zeros(qs.shape, jnp.float32)
        # dk/dv accumulators ROTATE with their chunks: after the loop each
        # has collected every device's contribution for the chunk it rides
        kc, vc = ks, vs
        dk = jnp.zeros(ks.shape, jnp.float32)
        dv = jnp.zeros(vs.shape, jnp.float32)
        for t in range(steps):
            def run(kc=kc, vc=vc, t=t):
                # global (o, lse): exp(s - lse) is the GLOBAL softmax row,
                # so these are the exact per-chunk gradient contributions
                return _flash_bwd_pallas(qs, kc, vc, o, lse, g, True, scale,
                                         block_q, block_k, interpret,
                                         window, soft_cap,
                                         q_offset=t * s_local)

            def skip():
                return (jnp.zeros(qs.shape, qs.dtype),
                        jnp.zeros(ks.shape, ks.dtype),
                        jnp.zeros(vs.shape, vs.dtype))

            dq_c, dk_c, dv_c = jax.lax.cond(idx >= t, run, skip)
            dq = dq + dq_c.astype(jnp.float32)
            dk = dk + dk_c.astype(jnp.float32)
            dv = dv + dv_c.astype(jnp.float32)
            if t + 1 < steps:
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
                dk = jax.lax.ppermute(dk, axis, perm)
                dv = jax.lax.ppermute(dv, axis, perm)
        # bring each chunk's accumulated dk/dv home: it has rotated
        # steps-1 hops forward, so n - (steps-1) more completes the cycle
        hops = (n - (steps - 1)) % n
        if hops:
            home = [(i, (i + hops) % n) for i in range(n)]
            dk = jax.lax.ppermute(dk, axis, home)
            dv = jax.lax.ppermute(dv, axis, home)
        return (dq.astype(qs.dtype), dk.astype(ks.dtype),
                dv.astype(vs.dtype), None)

    ring.defvjp(ring_fwd, ring_bwd)
    return ring(qs, ks, vs, idx)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh, *,
                   causal: bool = True, sm_scale: Optional[float] = None,
                   logit_soft_cap: Optional[float] = None,
                   sliding_window: Optional[int] = None,
                   use_flash: bool = False,
                   block_q: Optional[int] = None,
                   block_k: Optional[int] = None,
                   interpret: bool = False,
                   axis: str = AXES.SEQ) -> jax.Array:
    """Attention over sequence sharded on ``axis``. Global shapes:
    q (B,Hq,S,D), k/v (B,Hkv,S,D), S divisible by the axis size.

    ``logit_soft_cap`` and ``sliding_window`` match flash_attention's
    semantics, so Gemma-2/3 interleaves run under sequence parallelism:
    windowed sublayers band-mask each visiting chunk and skip chunks fully
    outside the band (the K/V still rotates — the ring schedule is fixed —
    but the O(Sq*Sk) chunk math is conditional, so the per-device cost is
    O(S_local * min(S, W + S_local)) like the Pallas block-skip).

    ``use_flash=True`` runs each chunk through the streamed Pallas kernels
    instead of the XLA einsum recurrence ("ring flash attention"): the
    per-chunk (S_local, S_local) scores never materialize in HBM, windowed
    rings additionally TRUNCATE the rotation at the last in-band step, and
    a custom VJP re-runs the ring feeding the kernels the global (o, lse)
    — exact gradients without storing per-chunk probabilities. Requires
    causal=True and S_local divisible by the block sizes; ``interpret``
    runs the exact kernel code on CPU (tests)."""
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if sliding_window is not None and not causal:
        raise ValueError("sliding_window requires causal attention")
    if use_flash and not causal:
        raise ValueError("ring flash attention requires causal=True")
    if use_flash and not interpret and jax.default_backend() != "tpu":
        use_flash = False  # kernels are TPU lowerings; XLA ring off-chip
                           # (flash_attention's use_pallas auto-off, same)
    n = mesh.shape[axis]
    if n == 1:
        from .attention import flash_attention
        return flash_attention(q, k, v, causal=causal, sm_scale=scale,
                               logit_soft_cap=logit_soft_cap,
                               sliding_window=sliding_window,
                               use_pallas=use_flash or None,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)
    if use_flash:
        s_local = q.shape[2] // n
        bq_t, bk_t = tuned_block_sizes(s_local, s_local)
        bq = min(block_q or bq_t, s_local)
        bk = min(block_k or bk_t, s_local)
        if not bq or not bk or s_local % bq or s_local % bk:
            # tuned_block_sizes returns 0 for non-multiple-of-128 shards
            if block_q or block_k:  # explicit request that can't be honored
                raise ValueError(f"S_local {s_local} not divisible by "
                                 f"blocks ({bq}, {bk})")
            use_flash = False  # no kernel-shaped blocking: XLA ring instead
    if use_flash:
        def local_flash(qs, ks, vs):
            idx = jax.lax.axis_index(axis)
            return _ring_flash(qs, ks, vs, idx, n=n, axis=axis, scale=scale,
                               window=sliding_window, soft_cap=logit_soft_cap,
                               block_q=bq, block_k=bk, interpret=interpret)

        spec = P(None, None, axis, None)
        # check=False: the flash chunk kernels are pallas_calls, which the
        # older-jax replication checker has no rule for whenever a mesh
        # axis beyond ``axis`` exists (the 8-device test mesh; a seq-only
        # mesh never trips it) — same reasoning as int4_matmul_sharded
        fn = shard_map_compat(local_flash, mesh=mesh,
                              in_specs=(spec, spec, spec), out_specs=spec,
                              check=False)
        return fn(q, k, v)

    def local(qs, ks, vs):
        idx = jax.lax.axis_index(axis)
        b, hq, sq, dd = qs.shape
        s_local = sq
        # mark the accumulators device-varying over the ring axis so the scan
        # carry type matches after the masked updates (jax >= 0.8 vma typing)
        def varying(x):
            try:
                return jax.lax.pcast(x, (axis,), to="varying")
            except (AttributeError, TypeError):
                return x
        acc0 = varying(jnp.zeros((b, hq, sq, dd), jnp.float32))
        m0 = varying(jnp.full((b, hq, sq, 1), NEG_INF, jnp.float32))
        l0 = varying(jnp.zeros((b, hq, sq, 1), jnp.float32))
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(t, carry):
            acc, m, l, kc, vc = carry
            src = (idx - t) % n  # whose shard we currently hold
            q_off = idx * s_local
            k_off = src * s_local

            def update(args):
                acc, m, l = args
                return _chunk_update(
                    qs, kc, vc, acc, m, l,
                    q_offset=q_off, k_offset=k_off,
                    causal=causal, sm_scale=scale,
                    soft_cap=logit_soft_cap, window=sliding_window)

            # chunk relevance: causal needs its first k pos <= the last
            # q pos; windowed additionally needs its last k pos inside the
            # band of some q. Skipping is pure compute saving — masks make
            # an irrelevant chunk a no-op anyway (t=0 is always relevant:
            # src==idx holds the diagonal, so m is finite from step one
            # and the exp(s - m) math never sees NEG_INF - NEG_INF).
            if causal:
                relevant = k_off <= q_off + (s_local - 1)
                if sliding_window is not None:
                    relevant &= (q_off - (k_off + s_local - 1)) < sliding_window
                acc, m, l = jax.lax.cond(relevant, update,
                                         lambda args: args, (acc, m, l))
            else:
                acc, m, l = update((acc, m, l))
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return acc, m, l, kc, vc

        acc, m, l, _, _ = jax.lax.fori_loop(
            0, n, step, (acc0, m0, l0, ks, vs))
        return (acc / jnp.maximum(l, 1e-30)).astype(qs.dtype)

    spec = P(None, None, axis, None)
    # check=False: the masked lax.cond over ppermute'd carries trips the
    # older-jax replication checker ("branches produced mismatched
    # replication types") even though both branches carry the same
    # device-varying values — the pcast fallback above covers the newer
    # vma typing, this covers the old rep check
    return shard_map_compat(local, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check=False)(q, k, v)
