"""Ring attention: sequence/context parallelism over the mesh's ``seq`` axis.

Long-context path (SURVEY.md §5.7): the sequence is sharded across devices;
each device keeps its Q shard resident and the K/V shards rotate around the
ring via ``lax.ppermute`` (ICI neighbor exchange), with the online-softmax
recurrence merging each visiting chunk — so attention over a sequence S costs
each device O(S_local * S) compute and O(S_local) memory, and the K/V transfer
overlaps with the chunk compute that XLA schedules.

Built on shard_map so the collective schedule is explicit; the per-chunk math
matches ops/attention.py exactly (same masks, same recurrence).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..parallel.mesh import AXES
from .attention import NEG_INF


def _chunk_update(q, kc, vc, acc, m, l, *, q_offset, k_offset, causal, sm_scale,
                  soft_cap=None, window=None):
    """One online-softmax step: fold K/V chunk (global offset k_offset) into the
    running (acc, m, l) for Q (global offset q_offset). Shapes:
    q (B,Hq,Sq,D), kc/vc (B,Hkv,Sk,D); GQA via group reshape.

    ``soft_cap`` (Gemma-2): cap*tanh(s/cap) before the mask — same
    scale→cap→mask order as ops/attention.py, and because this path is
    plain jnp, JAX autodiff carries the tanh derivative exactly (the
    Pallas kernels do it by hand; here it is free). ``window``: the
    sliding-window band mask, composed with causal."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = kc.shape
    group = hq // hkv
    qg = (q.astype(jnp.float32) * sm_scale).reshape(b, hkv, group, sq, d)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kc.astype(jnp.float32))
    s = s.reshape(b, hq, sq, sk)
    if soft_cap is not None:
        s = jnp.tanh(s / soft_cap) * soft_cap
    if causal:
        q_pos = q_offset + jnp.arange(sq)
        k_pos = k_offset + jnp.arange(sk)
        keep = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            keep &= (q_pos[:, None] - k_pos[None, :]) < window
        s = jnp.where(keep[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    pg = p.reshape(b, hkv, group, sq, sk)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", pg, vc.astype(jnp.float32))
    acc_new = acc * corr + o.reshape(b, hq, sq, d)
    return acc_new, m_new, l_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh, *,
                   causal: bool = True, sm_scale: Optional[float] = None,
                   logit_soft_cap: Optional[float] = None,
                   sliding_window: Optional[int] = None,
                   axis: str = AXES.SEQ) -> jax.Array:
    """Attention over sequence sharded on ``axis``. Global shapes:
    q (B,Hq,S,D), k/v (B,Hkv,S,D), S divisible by the axis size.

    ``logit_soft_cap`` and ``sliding_window`` match flash_attention's
    semantics, so Gemma-2/3 interleaves run under sequence parallelism:
    windowed sublayers band-mask each visiting chunk and skip chunks fully
    outside the band (the K/V still rotates — the ring schedule is fixed —
    but the O(Sq*Sk) chunk math is conditional, so the per-device cost is
    O(S_local * min(S, W + S_local)) like the Pallas block-skip)."""
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if sliding_window is not None and not causal:
        raise ValueError("sliding_window requires causal attention")
    n = mesh.shape[axis]
    if n == 1:
        from .attention import flash_attention
        return flash_attention(q, k, v, causal=causal, sm_scale=scale,
                               logit_soft_cap=logit_soft_cap,
                               sliding_window=sliding_window)

    def local(qs, ks, vs):
        idx = jax.lax.axis_index(axis)
        b, hq, sq, dd = qs.shape
        s_local = sq
        # mark the accumulators device-varying over the ring axis so the scan
        # carry type matches after the masked updates (jax >= 0.8 vma typing)
        def varying(x):
            try:
                return jax.lax.pcast(x, (axis,), to="varying")
            except (AttributeError, TypeError):
                return x
        acc0 = varying(jnp.zeros((b, hq, sq, dd), jnp.float32))
        m0 = varying(jnp.full((b, hq, sq, 1), NEG_INF, jnp.float32))
        l0 = varying(jnp.zeros((b, hq, sq, 1), jnp.float32))
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(t, carry):
            acc, m, l, kc, vc = carry
            src = (idx - t) % n  # whose shard we currently hold
            q_off = idx * s_local
            k_off = src * s_local

            def update(args):
                acc, m, l = args
                return _chunk_update(
                    qs, kc, vc, acc, m, l,
                    q_offset=q_off, k_offset=k_off,
                    causal=causal, sm_scale=scale,
                    soft_cap=logit_soft_cap, window=sliding_window)

            # chunk relevance: causal needs its first k pos <= the last
            # q pos; windowed additionally needs its last k pos inside the
            # band of some q. Skipping is pure compute saving — masks make
            # an irrelevant chunk a no-op anyway (t=0 is always relevant:
            # src==idx holds the diagonal, so m is finite from step one
            # and the exp(s - m) math never sees NEG_INF - NEG_INF).
            if causal:
                relevant = k_off <= q_off + (s_local - 1)
                if sliding_window is not None:
                    relevant &= (q_off - (k_off + s_local - 1)) < sliding_window
                acc, m, l = jax.lax.cond(relevant, update,
                                         lambda args: args, (acc, m, l))
            else:
                acc, m, l = update((acc, m, l))
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return acc, m, l, kc, vc

        acc, m, l, _, _ = jax.lax.fori_loop(
            0, n, step, (acc0, m0, l0, ks, vs))
        return (acc / jnp.maximum(l, 1e-30)).astype(qs.dtype)

    spec = P(None, None, axis, None)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
