"""Chunked-vocab fused cross-entropy: the LM-head loss without the logits.

The train step's single largest activation is the (B, S, V) logits tensor —
at the 260M bench geometry (B=8, S=2048, V=32k) that is ~1 GB bf16 from the
head matmul plus ~2.1 GB once the naive loss upcasts to f32, all of it HBM
traffic on both passes. The reference has no training stack at all
(SURVEY.md §2.4 absence table; it ships opaque container images,
runpod_client.go:1334-1342), so this op is net-new TPU capability: compute

    ce  = mean_n( logsumexp_v(h_n · W) - (h_n · W)[t_n] )
    z   = z_loss_coef * mean_n( logsumexp_v(h_n · W)^2 )

by streaming the vocab axis in chunks — an online (max, sumexp) reduction
exactly like flash attention's — so no (N, V) tensor ever exists. The
backward pass recomputes each chunk's logits from the saved logsumexp
(softmax_k = exp(logits_k - lse)), trading one extra head-matmul pass for
the 3 GB of logits HBM, which is the right trade on an HBM-bound profile
(the r4 AOT sweep: "full" remat beating "dots" for the same reason).

Supports the tied head (W = tok_embed^T, scanned over embedding ROWS so no
transposed copy is materialized), the untied (E, V) lm_head, and Gemma-2's
tanh logit softcap (whose exact Jacobian 1 - (logits/cap)^2 rides the
recompute). Pure XLA — chunk matmuls are MXU-shaped (N x E x V/K) and the
online reduction fuses into their epilogues; a Pallas kernel would only
re-schedule what the compiler already streams here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["fused_cross_entropy"]


def _pick_chunks(v: int, requested: int) -> int:
    """Largest chunk count <= requested that divides the vocab evenly (static
    shapes: every chunk matmul must be identical for one compiled program)."""
    for k in range(min(requested, v), 0, -1):
        if v % k == 0:
            return k
    return 1


def _chunk_logits(h2: jax.Array, head, start, size: int,
                  softcap: Optional[float]) -> jax.Array:
    """f32 logits for vocab slice [start, start+size): one MXU matmul with
    f32 accumulation (strictly better numerics than the naive path's
    bf16-matmul-then-upcast). ``head`` is ("tied", tok_embed (V, E)) or
    ("untied", lm_head (E, V)); the tied path slices ROWS so the (V, E)
    table is never transposed into a copy. ``start`` may be a tracer
    (lax.scan chunk index)."""
    kind, w = head
    if kind == "tied":
        wk = jax.lax.dynamic_slice_in_dim(w, start, size, axis=0)
        spec = "ne,ve->nv"
    else:
        wk = jax.lax.dynamic_slice_in_dim(w, start, size, axis=1)
        spec = "ne,ev->nv"
    # cast the slice to the COMPUTE dtype (matches _head_logits, llama.py
    # _mm: param_dtype may be f32 while activations are bf16 — without the
    # cast the einsum promotes to an f32 MXU matmul at ~1/6 throughput on
    # exactly the large-vocab geometry this op exists for); accumulation
    # stays f32 via preferred_element_type
    logits = jnp.einsum(spec, h2, wk.astype(h2.dtype),
                        preferred_element_type=jnp.float32)
    if softcap:
        cap = jnp.float32(softcap)
        logits = jnp.tanh(logits / cap) * cap
    return logits


def _fwd_scan(h2, head, targets, n_chunks, softcap):
    """Online logsumexp + target-logit pick, lax.scan'd over vocab chunks.

    A scan (not a Python unroll) is load-bearing for memory: it forces the
    chunks to execute sequentially, so exactly ONE (N, V/K) logits block is
    live at a time — unrolled, XLA's scheduler may overlap chunks and peak
    at several blocks, eating the very HBM this op exists to free (observed
    in the first AOT pass: fused dots_b8 peaked ABOVE the naive cell)."""
    n = h2.shape[0]
    kind, w = head
    v = w.shape[0] if kind == "tied" else w.shape[1]
    size = v // n_chunks

    def body(carry, k):
        m, s, picked = carry
        start = k * size
        logits = _chunk_logits(h2, head, start, size, softcap)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        idx = targets - start
        in_chunk = (idx >= 0) & (idx < size)
        got = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, size - 1)[:, None], axis=-1)[:, 0]
        picked = picked + jnp.where(in_chunk, got, 0.0)
        return (m_new, s, picked), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),   # running max
            jnp.zeros((n,), jnp.float32),            # sumexp rescaled to max
            jnp.zeros((n,), jnp.float32))            # picked target logit
    (m, s, picked), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    return lse, picked


def _ce_z(lse, picked, z_loss_coef):
    ce = jnp.mean(lse - picked)
    z = (jnp.float32(z_loss_coef) * jnp.mean(jnp.square(lse))
         if z_loss_coef else jnp.float32(0.0))
    return ce, z


def fused_cross_entropy(hidden: jax.Array, head_w: jax.Array,
                        targets: jax.Array, *, tied: bool = False,
                        z_loss_coef: float = 0.0,
                        logit_softcap: Optional[float] = None,
                        n_chunks: int = 8) -> tuple[jax.Array, jax.Array]:
    """(mean NLL, z-loss) of softmax(hidden @ head) vs targets, never
    materializing the (N, V) logits.

    hidden (..., E); targets (...) int32 matching hidden's leading shape;
    head_w is lm_head (E, V), or tok_embed (V, E) with ``tied=True``.
    Semantics match workloads.train._ce_and_zloss (one shared logsumexp
    reduction feeding both terms); numerics differ only by the f32 matmul
    accumulation. Differentiable in hidden and head_w.
    """
    n_chunks = _pick_chunks(head_w.shape[0] if tied else head_w.shape[1],
                            n_chunks)
    h2 = hidden.reshape(-1, hidden.shape[-1])
    t1 = targets.reshape(-1)
    kind = "tied" if tied else "untied"
    return _fused_ce(h2, head_w, t1, kind, float(z_loss_coef),
                     logit_softcap, n_chunks)


# ``kind``/``z_loss_coef``/``softcap``/``n_chunks`` are static (hashable)
# config, not tracers: nondiff_argnums keeps them out of differentiation
# and lets the chunk loop unroll at trace time.
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_ce(h2, w, t1, kind, z_loss_coef, softcap, n_chunks):
    lse, picked = _fwd_scan(h2, (kind, w), t1, n_chunks, softcap)
    return _ce_z(lse, picked, z_loss_coef)


def _fce_fwd(h2, w, t1, kind, z_loss_coef, softcap, n_chunks):
    lse, picked = _fwd_scan(h2, (kind, w), t1, n_chunks, softcap)
    return _ce_z(lse, picked, z_loss_coef), (h2, w, t1, lse)


def _fce_bwd(kind, z_loss_coef, softcap, n_chunks, res, cts):
    """Recompute each chunk's logits from the saved lse; one lax.scan'd pass
    (sequential — see _fwd_scan on why) produces d_hidden (carry-accumulated)
    and d_head (written chunk-by-chunk into the full-size buffer via
    dynamic_update_slice, so no stacked (K, ...) copy + concatenate)."""
    h2, w, t1, lse = res
    g_ce, g_z = cts
    n = h2.shape[0]
    v = w.shape[0] if kind == "tied" else w.shape[1]
    size = v // n_chunks
    inv_n = 1.0 / n
    # d(loss)/d(logits)[n, v] = softmax * (g_ce + 2*coef*lse_n*g_z)/N
    #                           - onehot[target] * g_ce/N
    row_coef = (g_ce + (2.0 * z_loss_coef) * lse * g_z) * inv_n   # (N,)
    g_pick = g_ce * inv_n
    head = (kind, w)
    axis = 0 if kind == "tied" else 1
    rows = jnp.arange(n)

    def body(carry, k):
        dh, dw = carry
        start = k * size
        logits = _chunk_logits(h2, head, start, size, softcap)
        d_logits = jnp.exp(logits - lse[:, None]) * row_coef[:, None]
        # the -onehot term as a scatter-add: no (N, V/K) one-hot tensor
        idx = t1 - start
        in_chunk = (idx >= 0) & (idx < size)
        d_logits = d_logits.at[rows, jnp.clip(idx, 0, size - 1)].add(
            jnp.where(in_chunk, -g_pick, 0.0))
        if softcap:
            # chain through cap*tanh(x/cap): logits here are POST-cap, so
            # the Jacobian is exactly 1 - (logits/cap)^2
            d_logits = d_logits * (1.0 - jnp.square(logits / softcap))
        # bf16 operands for the two grad matmuls (f32 accumulation via
        # preferred_element_type) — same dtype discipline as the forward
        d16 = d_logits.astype(h2.dtype)
        if kind == "tied":
            wk = jax.lax.dynamic_slice_in_dim(w, start, size, axis=0)
            dh = dh + jnp.einsum("nv,ve->ne", d16, wk.astype(h2.dtype),
                                 preferred_element_type=jnp.float32)
            dwk = jnp.einsum("nv,ne->ve", d16, h2,
                             preferred_element_type=jnp.float32)
        else:
            wk = jax.lax.dynamic_slice_in_dim(w, start, size, axis=1)
            dh = dh + jnp.einsum("nv,ev->ne", d16, wk.astype(h2.dtype),
                                 preferred_element_type=jnp.float32)
            dwk = jnp.einsum("ne,nv->ev", h2, d16,
                             preferred_element_type=jnp.float32)
        dw = jax.lax.dynamic_update_slice_in_dim(dw, dwk.astype(w.dtype),
                                                 start, axis=axis)
        return (dh, dw), None

    init = (jnp.zeros(h2.shape, jnp.float32),
            jnp.zeros(w.shape, w.dtype))
    (dh, dw), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    return dh.astype(h2.dtype), dw, None   # no cotangent for int targets


_fused_ce.defvjp(_fce_fwd, _fce_bwd)
