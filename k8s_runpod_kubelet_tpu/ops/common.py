"""Shared kernel-selection policy."""

from __future__ import annotations

import os
from typing import Optional

import jax


def use_pallas(flag: Optional[bool]) -> bool:
    """Auto-select the Pallas path: explicit flag wins; env kill-switch
    (TPU_KUBELET_NO_PALLAS=1) next; else Pallas on TPU backends only."""
    if flag is not None:
        return flag
    if os.environ.get("TPU_KUBELET_NO_PALLAS") == "1":
        return False
    return jax.default_backend() == "tpu"
