"""Shared kernel-selection policy."""

from __future__ import annotations

import os
from typing import Optional

import jax


def tpu_compiler_params(**kw):
    """Pallas TPU compiler params across jax releases: the class was
    renamed TPUCompilerParams -> CompilerParams; resolve whichever this
    jax ships (the pinned image and newer toolchains disagree)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def use_pallas(flag: Optional[bool]) -> bool:
    """Auto-select the Pallas path: explicit flag wins; env kill-switch
    (TPU_KUBELET_NO_PALLAS=1) next; force-on (TPU_KUBELET_FORCE_PALLAS=1,
    for AOT compiles against a device-less TPU topology where the default
    backend is the CPU host) next; else Pallas on TPU backends only."""
    if flag is not None:
        return flag
    if os.environ.get("TPU_KUBELET_NO_PALLAS") == "1":
        return False
    if os.environ.get("TPU_KUBELET_FORCE_PALLAS") == "1":
        return True
    return jax.default_backend() == "tpu"
