"""Multi-head Latent Attention (MLA): DeepSeek-V2-style KV compression.

The serving stack's cache economics (ring caches, int8 KV, donation) all
attack the same number: KV bytes read per decode step. MLA attacks it at
the ARCHITECTURE level — instead of caching per-head K/V
(2 * n_heads * head_dim floats per token), cache one shared latent
``c = h @ W_dkv`` of rank r plus one shared RoPE key of dim dr
(r + dr floats per token; DeepSeek-V2 geometry: 512+64 = 576 vs
2*128*128 = 32768 — 56.9x fewer).
Per-head keys/values are LINEAR functions of the latent (k_h = c @ W_uk_h,
v_h = c @ W_uv_h), which makes two decode-time forms equivalent:

  direct:   materialize k/v from the cached latents, attend normally.
  absorbed: fold W_uk into the query (q_lat_h = q_h @ W_uk_h^T) and W_uv
            into the output — attention runs ENTIRELY in latent space:
            scores = q_lat @ c^T (+ decoupled-RoPE term), out = (p @ c)
            @ W_uv. Per step this reads r-dim latents instead of
            H*dh-dim keys: the bandwidth win the cache compression
            promised, realized at compute time too.

RoPE cannot ride the latent (rotation does not commute with W_uk), so MLA
splits the query per head into a no-position part (dh) scored against the
latent and a positional part (dr) scored against ONE shared rope key per
token — the "decoupled RoPE" of the paper (arXiv:2405.04434; net-new vs
the reference, SURVEY.md §2.4: it has no model code at all).

This module is the self-contained op + latent cache: mla_attention
(prefill, full-sequence), mla_decode_step (absorbed, one token), and
init_mla_cache, parity-tested against each other and against a dense
reference. The cache carries a PER-ROW index (each slot at its own
length) like the engine's caches; active-row masking and ring/int8
composition are the engine-integration work a DeepSeek model family
needs next round.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .rope import apply_rope

__all__ = ["init_mla_params", "init_mla_cache",
           "mla_attention", "mla_decode_step", "kv_bytes_per_token"]


def init_mla_params(key, *, embed_dim: int, n_heads: int, head_dim: int,
                    latent_dim: int, rope_dim: int,
                    dtype=jnp.float32) -> dict:
    """{w_q (E,H,dh+dr), w_dkv (E,r), w_uk (r,H,dh), w_uv (r,H,dh),
    w_o (H*dh,E)} — the minimal MLA parameter set (the paper also
    low-ranks the query; orthogonal to the cache story)."""
    ks = jax.random.split(key, 5)
    e, h, dh, dr, r = embed_dim, n_heads, head_dim, rope_dim, latent_dim

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        "w_q": init(ks[0], (e, h, dh + dr), e),
        "w_dkv": init(ks[1], (e, r + dr), e),   # latent + shared rope key
        "w_uk": init(ks[2], (r, h, dh), r),
        "w_uv": init(ks[3], (r, h, dh), r),
        "w_o": init(ks[4], (h * dh, e), h * dh),
    }


def kv_bytes_per_token(*, n_heads: int, head_dim: int, latent_dim: int,
                       rope_dim: int, bytes_per_el: int = 2) -> tuple[int, int]:
    """(standard MHA cache bytes, MLA cache bytes) per token — the claim."""
    return (2 * n_heads * head_dim * bytes_per_el,
            (latent_dim + rope_dim) * bytes_per_el)


def _project(h2, params, cos, sin, positions=None):
    """Shared projections: q (B,S,H,dh+dr) with RoPE on its dr tail,
    latent c (B,S,r), shared rope key kr (B,S,dr) (RoPE'd)."""
    e, hn, dhr = params["w_q"].shape
    r = params["w_uk"].shape[0]
    dr = dhr - params["w_uk"].shape[2]
    q = jnp.einsum("bse,ehd->bshd", h2, params["w_q"])
    ckr = jnp.einsum("bse,er->bsr", h2, params["w_dkv"])
    c, kr = ckr[..., :r], ckr[..., r:]
    # decoupled RoPE: q's dr tail and the ONE shared key rotate; the
    # latent-scored parts carry no position
    q_nope, q_rope = q[..., :-dr], q[..., -dr:]
    q_rope = apply_rope(q_rope, cos, sin, positions)
    kr = apply_rope(kr[:, :, None, :], cos, sin, positions)[:, :, 0, :]
    return q_nope, q_rope, c, kr


def mla_attention(h2: jax.Array, params: dict, cos, sin,
                  positions=None) -> tuple[jax.Array, dict]:
    """Full-sequence (prefill/training) MLA, causal. Returns (out (B,S,E),
    {"c": (B,S,r), "kr": (B,S,dr)}) — the latter IS the KV cache content.
    Direct form: materializes per-head k/v for the sequence (prefill is
    compute-bound; the latent trick matters for the DECODE reads)."""
    q_nope, q_rope, c, kr = _project(h2, params, cos, sin, positions)
    b, s, hn, dh = q_nope.shape
    k_nope = jnp.einsum("bsr,rhd->bshd", c, params["w_uk"])
    v = jnp.einsum("bsr,rhd->bshd", c, params["w_uv"])
    scale = (dh + q_rope.shape[-1]) ** -0.5
    scores = (jnp.einsum("bqhd,bkhd->bhqk", q_nope, k_nope)
              + jnp.einsum("bqhd,bkd->bhqk", q_rope, kr)) * scale
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask[None, None], scores.astype(jnp.float32),
                       -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1).astype(h2.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, s, hn * dh)
    out = o @ params["w_o"]
    return out, {"c": c, "kr": kr}


def init_mla_cache(batch: int, max_len: int, *, latent_dim: int,
                   rope_dim: int, dtype=jnp.float32) -> dict:
    """Latent KV cache: (latent_dim + rope_dim) per position instead of
    2*H*dh — the whole point. ``index`` follows the engine's cache
    contract (positions < index are committed)."""
    return {
        "c": jnp.zeros((batch, max_len, latent_dim), dtype),
        "kr": jnp.zeros((batch, max_len, rope_dim), dtype),
        "index": jnp.zeros((batch,), jnp.int32),   # per row, engine-style
    }


def mla_decode_step(h1: jax.Array, params: dict, cache: dict, cos, sin
                    ) -> tuple[jax.Array, dict]:
    """One-token decode in the ABSORBED form: the step reads the (L, r)
    latents and the (L, dr) rope keys — never materializing per-head K/V.

      q_lat_h = q_nope_h @ W_uk_h^T          (fold W_uk into the query)
      scores  = q_lat @ c^T + q_rope @ kr^T  (latent-space attention)
      out     = ((p @ c) @ W_uv) . W_o       (fold W_uv into the output)

    h1 (B, 1, E); each row's position comes from its cache["index"][b]
    (slots at different lengths, the continuous-batching shape). Returns
    (out (B, 1, E), updated cache)."""
    # Guard a full row (ADVICE r4): without the clamp, a scatter at
    # idx == max_len is silently DROPPED (JAX OOB semantics) while index
    # keeps advancing, and the live mask (arange <= idx) then admits every
    # position — zero latents included — into the softmax: silently wrong
    # attention. Clamping pins a full row at its last slot (that slot is
    # overwritten, attention stays over real latents); callers (the serving
    # engine) must retire rows at max_len — this is the op-level backstop.
    idx = jnp.minimum(cache["index"], cache["c"].shape[1] - 1)  # (B,)
    pos = idx[:, None]                                # (B, 1)
    q_nope, q_rope, c1, kr1 = _project(h1, params, cos, sin, pos)
    b, _, hn, dh = q_nope.shape
    dr = q_rope.shape[-1]
    # commit this token's latent before scoring (self-attention sees it);
    # per-row positions -> scatter, not a slice update
    rows = jnp.arange(b)
    cache = dict(cache)
    cache["c"] = cache["c"].at[rows, idx].set(c1[:, 0])
    cache["kr"] = cache["kr"].at[rows, idx].set(kr1[:, 0])
    c, kr = cache["c"], cache["kr"]
    q_lat = jnp.einsum("bohd,rhd->bohr", q_nope, params["w_uk"])  # (B,1,H,r)
    scale = (dh + dr) ** -0.5
    scores = (jnp.einsum("bohr,blr->bhol", q_lat, c)
              + jnp.einsum("bohd,bld->bhol", q_rope, kr)) * scale
    live = (jnp.arange(c.shape[1])[None] <= idx[:, None])[:, None, None, :]
    scores = jnp.where(live, scores.astype(jnp.float32), -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1).astype(h1.dtype)
    o_lat = jnp.einsum("bhol,blr->bohr", p, c)                    # (B,1,H,r)
    o = jnp.einsum("bohr,rhd->bohd", o_lat, params["w_uv"])
    out = o.reshape(b, 1, hn * dh) @ params["w_o"]
    cache["index"] = idx + 1
    return out, cache
