"""The per-generation TPU roofline + price table — ONE source of truth.

Every layer that reasons about generations reads THIS module:

- ``workloads/telemetry.py`` re-exports ``PEAK_TFLOPS_BF16`` /
  ``generation_of`` for the training-side MFU math (back-compat names);
- ``cloud/types.py`` prices its accelerator catalog from
  ``cost_per_chip_hr`` here;
- ``fleet/scheduler.py`` seeds its effective-throughput matrix from the
  FLOPs and HBM-bandwidth columns (prefill is FLOPs-bound, decode is
  HBM-bandwidth-bound — the disagg roofline split, ISSUE 9/19);
- ``bench.py`` reports roofline fractions against the same numbers.

PR 19 review history: PEAK_TFLOPS_BF16 used to live in telemetry.py with
a drifting copy in bench.py — ``tests/test_generations.py`` now pins the
dict literal to this module alone.

Deliberately stdlib-only and import-light: the kubelet control plane and
the router import it, neither may pull jax.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class GenerationSpec:
    """One TPU generation's public roofline + on-demand list price."""

    name: str                 # catalog/node-label key ("v5e", not "v5litepod")
    peak_tflops_bf16: float   # per chip, public spec sheets
    peak_hbm_gbps: float      # per chip HBM bandwidth, GB/s
    cost_per_chip_hr: float   # USD, on-demand list price

    @property
    def flops_per_dollar(self) -> float:
        """TFLOP/s per $/hr — the prefill/training-side value ratio."""
        return self.peak_tflops_bf16 / self.cost_per_chip_hr

    @property
    def hbm_gbps_per_dollar(self) -> float:
        """HBM GB/s per $/hr — the decode-side value ratio."""
        return self.peak_hbm_gbps / self.cost_per_chip_hr


# Public spec-sheet rooflines and on-demand list prices. ``cpu`` is the
# honest floor for local dev runs so MFU/placement math never divides by
# zero (same convention the old telemetry table used).
GENERATIONS = {
    "v4": GenerationSpec("v4", peak_tflops_bf16=275.0,
                         peak_hbm_gbps=1228.0, cost_per_chip_hr=3.22),
    "v5e": GenerationSpec("v5e", peak_tflops_bf16=197.0,
                          peak_hbm_gbps=819.0, cost_per_chip_hr=1.20),
    "v5p": GenerationSpec("v5p", peak_tflops_bf16=459.0,
                          peak_hbm_gbps=2765.0, cost_per_chip_hr=4.20),
    "v6e": GenerationSpec("v6e", peak_tflops_bf16=918.0,
                          peak_hbm_gbps=1640.0, cost_per_chip_hr=2.70),
    "cpu": GenerationSpec("cpu", peak_tflops_bf16=0.1,
                          peak_hbm_gbps=10.0, cost_per_chip_hr=0.01),
}

# the back-compat view telemetry/bench historically exposed
PEAK_TFLOPS_BF16 = {name: spec.peak_tflops_bf16
                    for name, spec in GENERATIONS.items()}

_GENERATION_PREFIXES = (
    ("v5litepod", "v5e"),
    ("v5p", "v5p"),
    ("v6e", "v6e"),
    ("v4", "v4"),
)


def generation_of(accelerator_type: str) -> str:
    """Accelerator-type name -> generation key of GENERATIONS
    ("v5litepod-16" -> "v5e"). Unknown/empty -> "cpu" (local dev)."""
    name = (accelerator_type or "").lower()
    if name in GENERATIONS:
        return name
    for prefix, gen in _GENERATION_PREFIXES:
        if name.startswith(prefix):
            return gen
    return "cpu"


def spec_of(accelerator_type: str) -> GenerationSpec:
    """Full roofline row for an accelerator type or generation name."""
    return GENERATIONS[generation_of(accelerator_type)]


def peak_tflops_per_chip(accelerator_type: str) -> float:
    """Per-chip bf16 peak for an accelerator type or generation name."""
    return spec_of(accelerator_type).peak_tflops_bf16


def peak_hbm_gbps_per_chip(accelerator_type: str) -> float:
    """Per-chip HBM bandwidth for an accelerator type or generation."""
    return spec_of(accelerator_type).peak_hbm_gbps


def cost_per_chip_hr(accelerator_type: str) -> float:
    """On-demand list $/chip-hr for an accelerator type or generation."""
    return spec_of(accelerator_type).cost_per_chip_hr
