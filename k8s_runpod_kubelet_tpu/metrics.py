"""Prometheus-style metrics registry (counters, gauges, histograms).

The reference has NO metrics (SURVEY.md §5.5: GetStatsSummary/GetMetricsResource
left nil). This build makes the north-star metric first-class: the
schedule->first-step latency is recorded as a histogram per pod, alongside
deploy/reconcile timings, slice-state gauges, and the serving SLO histograms
(TTFT / inter-token latency, sub-second buckets via per-metric ``describe``),
served as Prometheus text on the health server's /metrics.

Exposition follows the Prometheus text format rules scrapers actually
enforce: counters are exposed (HELP/TYPE and samples alike) under the
``<name>_total`` family name, every family carries a ``# TYPE`` line, and
label values escape ``\\``, ``"`` and newlines.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

_DEFAULT_BUCKETS = (0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600, 1800)

# heartbeat metric-snapshot wire shape (Metrics.snapshot /
# MetricsAggregator.ingest); readers warn, not crash, on unknown versions
SNAPSHOT_SCHEMA_VERSION = 1


class _Hist:
    """Fixed-size cumulative buckets + sum/count, plus a bounded tail of raw
    observations for tests/debugging — memory stays O(buckets) for a process
    meant to run for months. Bucket bounds are per-histogram (describe(...,
    buckets=...)): sub-second TTFT/ITL histograms must not be crushed into a
    0.5s first bucket sized for pod-provisioning latencies.

    Exemplars: each bucket (plus +Inf) keeps at most the LATEST
    ``(trace_id, value)`` pair observed into it — O(buckets) storage, enough
    for "p99 bucket -> trace_id -> /debug/traces waterfall" navigation."""

    __slots__ = ("buckets", "bucket_counts", "sum", "count", "recent",
                 "exemplars")

    def __init__(self, buckets: tuple = _DEFAULT_BUCKETS):
        self.buckets = buckets
        self.bucket_counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0
        self.recent: list[float] = []
        # one slot per bucket + one for +Inf; None or (trace_id, value)
        self.exemplars: list = [None] * (len(buckets) + 1)

    def observe(self, value: float, exemplar: Optional[str] = None):
        placed = exemplar is None
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.bucket_counts[i] += 1
                if not placed:
                    # attach to the LOWEST bucket containing the value (the
                    # bucket a non-cumulative view would file it under)
                    self.exemplars[i] = (exemplar, value)
                    placed = True
        if not placed:
            self.exemplars[len(self.buckets)] = (exemplar, value)
        self.sum += value
        self.count += 1
        self.recent.append(value)
        if len(self.recent) > 1000:
            del self.recent[:500]


class Metrics:
    def __init__(self, clock=time.monotonic):
        # duration source for time_block timers; injectable so soak tests
        # driving a FakeClock see deterministic histogram durations
        self._clock = clock
        self.lock = threading.Lock()
        self.counters: dict[tuple[str, tuple], float] = {}
        self.gauges: dict[tuple[str, tuple], float] = {}
        self.histograms: dict[tuple[str, tuple], _Hist] = {}
        self.help: dict[str, str] = {}
        self.bucket_spec: dict[str, tuple] = {}  # name -> histogram bounds

    @staticmethod
    def _key(name: str, labels: Optional[dict]) -> tuple[str, tuple]:
        return name, tuple(sorted((labels or {}).items()))

    def describe(self, name: str, help_text: str,
                 buckets: Optional[tuple] = None):
        """HELP text for a metric; for histograms, optionally its bucket
        bounds (applied to label-sets created AFTER the describe — declare
        before first observe, as every call site in this repo does)."""
        self.help[name] = help_text
        if buckets is not None:
            bounds = tuple(sorted(float(b) for b in buckets))
            if not bounds:
                raise ValueError(f"{name}: buckets must be non-empty")
            self.bucket_spec[name] = bounds

    def incr(self, name: str, value: float = 1.0, labels: Optional[dict] = None):
        k = self._key(name, labels)
        with self.lock:
            self.counters[k] = self.counters.get(k, 0.0) + value

    def set_gauge(self, name: str, value: float, labels: Optional[dict] = None):
        with self.lock:
            self.gauges[self._key(name, labels)] = value

    def remove_gauge(self, name: str, labels: Optional[dict] = None):
        """Drop one labeled gauge series. For per-entity gauges (per-pod,
        per-replica) whose entity was deleted: a phantom series — e.g. a
        stalled=1 for a pod that no longer exists — must not alert forever."""
        with self.lock:
            self.gauges.pop(self._key(name, labels), None)

    def observe(self, name: str, value: float, labels: Optional[dict] = None,
                exemplar: Optional[str] = None):
        """Record one histogram observation. ``exemplar`` is an optional
        trace_id; the containing bucket keeps the latest one so exposition
        can link a tail bucket straight to a replayable trace."""
        with self.lock:
            key = self._key(name, labels)
            h = self.histograms.get(key)
            if h is None:
                h = self.histograms[key] = _Hist(
                    self.bucket_spec.get(name, _DEFAULT_BUCKETS))
            h.observe(value, exemplar=exemplar)

    def time_block(self, name: str, labels: Optional[dict] = None):
        return _Timer(self, name, labels)

    def get_counter(self, name: str, labels: Optional[dict] = None) -> float:
        return self.counters.get(self._key(name, labels), 0.0)

    def get_observations(self, name: str, labels: Optional[dict] = None) -> list[float]:
        """Most recent raw observations (bounded tail; for tests/debugging)."""
        h = self.histograms.get(self._key(name, labels))
        return list(h.recent) if h else []

    # -- exposition ------------------------------------------------------------

    @staticmethod
    def _esc_label(v) -> str:
        """Label-value escaping per the exposition format: backslash first,
        then quote and newline."""
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @staticmethod
    def _esc_help(v: str) -> str:
        return v.replace("\\", "\\\\").replace("\n", "\\n")

    @classmethod
    def _labels_str(cls, labels: tuple) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{cls._esc_label(v)}"' for k, v in labels)
        return "{" + inner + "}"

    def _header(self, out: list[str], family: str, base_name: str, kind: str):
        """HELP (if described) + TYPE under the EXPOSED family name: a
        counter described as ``foo`` but sampled as ``foo_total`` must put
        its metadata on ``foo_total`` too, or scrapers see two different
        metrics (one with metadata and no samples, one untyped)."""
        if base_name in self.help:
            out.append(f"# HELP {family} {self._esc_help(self.help[base_name])}")
        out.append(f"# TYPE {family} {kind}")

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self.lock:
            names = sorted({n for n, _ in (*self.counters, *self.gauges, *self.histograms)})
            for name in names:
                counter_items = sorted((k, v) for k, v in self.counters.items()
                                       if k[0] == name)
                gauge_items = sorted((k, v) for k, v in self.gauges.items()
                                     if k[0] == name)
                hist_items = sorted(((k, h) for k, h in self.histograms.items()
                                     if k[0] == name), key=lambda kv: kv[0])
                if counter_items:
                    self._header(out, f"{name}_total", name, "counter")
                    for (_, lbls), v in counter_items:
                        out.append(f"{name}_total{self._labels_str(lbls)} {v}")
                if gauge_items:
                    self._header(out, name, name, "gauge")
                    for (_, lbls), v in gauge_items:
                        out.append(f"{name}{self._labels_str(lbls)} {v}")
                if hist_items:
                    self._header(out, name, name, "histogram")
                    for (_, lbls), h in hist_items:
                        for i, (b, c) in enumerate(zip(h.buckets,
                                                       h.bucket_counts)):
                            lb = dict(lbls)
                            lb["le"] = str(b)
                            out.append(f"{name}_bucket{self._labels_str(tuple(sorted(lb.items())))} {c}"
                                       f"{self._exemplar_str(h.exemplars[i])}")
                        lb = dict(lbls)
                        lb["le"] = "+Inf"
                        out.append(f"{name}_bucket{self._labels_str(tuple(sorted(lb.items())))} {h.count}"
                                   f"{self._exemplar_str(h.exemplars[len(h.buckets)])}")
                        out.append(f"{name}_sum{self._labels_str(lbls)} {h.sum}")
                        out.append(f"{name}_count{self._labels_str(lbls)} {h.count}")
        return "\n".join(out) + "\n"

    @classmethod
    def _exemplar_str(cls, ex) -> str:
        """OpenMetrics exemplar suffix for a _bucket sample:
        ``... # {trace_id="abc"} 0.07``. Timestamp deliberately omitted so
        fleet-merged exposition stays byte-deterministic."""
        if ex is None:
            return ""
        trace_id, value = ex
        return f' # {{trace_id="{cls._esc_label(trace_id)}"}} {value}'

    # -- heartbeat snapshot / fleet merge -------------------------------------

    def snapshot(self) -> dict:
        """Compact JSON-safe dump of every counter/gauge/histogram with
        metadata (help + bucket bounds). Cumulative, so it can ride every
        fleet heartbeat idempotently; ``MetricsAggregator.ingest`` turns a
        stream of these into fleet-wide totals with restart guards."""
        with self.lock:
            hists = []
            for (n, lbls), h in sorted(self.histograms.items()):
                hists.append([n, [list(p) for p in lbls], {
                    "buckets": list(h.buckets),
                    "bucket_counts": list(h.bucket_counts),
                    "sum": h.sum,
                    "count": h.count,
                    "exemplars": [[i, ex[0], ex[1]]
                                  for i, ex in enumerate(h.exemplars)
                                  if ex is not None],
                }])
            return {
                "schema_version": SNAPSHOT_SCHEMA_VERSION,
                "counters": [[n, [list(p) for p in lbls], v]
                             for (n, lbls), v in sorted(self.counters.items())],
                "gauges": [[n, [list(p) for p in lbls], v]
                           for (n, lbls), v in sorted(self.gauges.items())],
                "hists": hists,
                "help": dict(self.help),
                "bucket_spec": {k: list(v)
                                for k, v in self.bucket_spec.items()},
            }


class _Timer:
    def __init__(self, m: Metrics, name: str, labels: Optional[dict]):
        self.m, self.name, self.labels = m, name, labels

    def __enter__(self):
        self.t0 = self.m._clock()
        return self

    def __exit__(self, *exc):
        self.m.observe(self.name, self.m._clock() - self.t0, self.labels)


class RestartGuard:
    """Non-negative delta extraction from cumulative counters pushed by
    restartable processes — the SLOTracker idiom (fleet/slo.py), extracted
    so every heartbeat-merged counter shares one guard class.

    A replica restart resets its in-process counters to ~0, so a cumulative
    push can go BACKWARDS; naively differencing would subtract the replica's
    whole history from a fleet total. Policy knobs:

    - ``count_first``: on the first sighting of a key, is the full cumulative
      value the delta (fleet totals: yes — traffic before the aggregator
      existed still happened) or zero (SLO windows: no — an old error total
      is not a fresh breach signal)?
    - ``count_restart``: after a detected reset, is the new (small) cumulative
      value the delta (fleet totals: yes — it accrued since restart) or zero
      (SLO windows: conservative skip, re-baseline)?

    Deltas are never negative under either policy."""

    def __init__(self, count_first: bool = True, count_restart: bool = True):
        self._prev: dict = {}
        self._count_first = count_first
        self._count_restart = count_restart

    def delta(self, key, value: float) -> float:
        prev = self._prev.get(key)
        value = float(value)
        self._prev[key] = value
        if prev is None:
            return value if self._count_first else 0.0
        d = value - prev
        if d < 0:
            return value if self._count_restart else 0.0
        return d

    def forget(self, owner):
        """Drop every baseline whose key's first element is ``owner`` (keys
        are ``(replica_id, ...)`` tuples by convention) — a deregistered
        replica that re-registers must be treated as fresh."""
        stale = [k for k in self._prev
                 if (isinstance(k, tuple) and k and k[0] == owner)
                 or k == owner]
        for k in stale:
            del self._prev[k]


class MetricsAggregator:
    """Registry-tier fleet-wide metric merge: replicas push cumulative
    ``Metrics.snapshot()`` payloads on the existing heartbeat; this class
    folds them into one merged registry whose ``render()`` is served as
    ``GET /metrics/fleet``.

    Merge semantics:

    - counters and histogram bucket/sum/count: per-(replica, series)
      RestartGuard deltas accumulated into fleet totals that SURVIVE replica
      exit (a dead replica's traffic still happened — fleet counters never
      dip);
    - gauges: latest per replica, SUMMED across live replicas at render time
      (queue depths, KV pages); dropped on ``forget``;
    - exemplars: incoming per-bucket exemplars overwrite the merged slot
      (best-effort latest — any surviving exemplar must resolve via
      /debug/traces, which push order does not change);
    - help text and bucket bounds ride the snapshot, so the merged
      exposition is line-identical to a single process observing the union
      stream (tests/test_metrics_merge.py pins this property)."""

    def __init__(self):
        self.lock = threading.Lock()
        self._guard = RestartGuard()          # count_first/count_restart True
        self._merged = Metrics()
        self._replica_gauges: dict[str, dict] = {}
        self._hist_prev: dict[tuple, dict] = {}   # (rid, key) -> prev state
        self._last_ingest: dict[str, int] = {}    # rid -> snapshots ingested
        self._schema_warned: set = set()

    @staticmethod
    def _norm_key(name, lbls) -> tuple:
        return name, tuple(sorted((k, v) for k, v in (tuple(p) for p in lbls)))

    def ingest(self, replica_id: str, snap: Optional[dict]):
        """Fold one replica heartbeat snapshot into the fleet merge.
        Malformed payloads are dropped whole (a bad replica must not poison
        the fleet view); unknown schema versions are skipped with one log
        line worth of state (the caller logs)."""
        if not isinstance(snap, dict):
            return
        ver = snap.get("schema_version")
        if ver != SNAPSHOT_SCHEMA_VERSION:
            self._schema_warned.add((replica_id, ver))
            return
        with self.lock:
            m = self._merged
            with m.lock:
                m.help.update({str(k): str(v)
                               for k, v in (snap.get("help") or {}).items()})
                for name, bounds in (snap.get("bucket_spec") or {}).items():
                    m.bucket_spec[str(name)] = tuple(float(b) for b in bounds)
                for name, lbls, value in snap.get("counters") or ():
                    key = self._norm_key(name, lbls)
                    d = self._guard.delta((replica_id, "c", key), value)
                    m.counters[key] = m.counters.get(key, 0.0) + d
                gauges = {}
                for name, lbls, value in snap.get("gauges") or ():
                    gauges[self._norm_key(name, lbls)] = float(value)
                self._replica_gauges[replica_id] = gauges
                for name, lbls, state in snap.get("hists") or ():
                    self._ingest_hist(replica_id, self._norm_key(name, lbls),
                                      state)
            self._last_ingest[replica_id] = \
                self._last_ingest.get(replica_id, 0) + 1

    def _ingest_hist(self, replica_id: str, key: tuple, state: dict):
        """Apply one histogram's cumulative snapshot as deltas. Restart is
        detected on the count going backwards (ints, monotonic per process);
        the whole prev baseline is then discarded so the new cumulative
        state counts once, like the counter guard."""
        # keep bound values EXACTLY as snapshotted (no float coercion): the
        # le="..." label is str(bound), and line-identity with the source
        # process needs int bounds to stay ints
        buckets = tuple(state.get("buckets") or ())
        counts = [int(c) for c in state.get("bucket_counts") or ()]
        if not buckets or len(counts) != len(buckets):
            return
        h = self._merged.histograms.get(key)
        if h is None:
            h = self._merged.histograms[key] = _Hist(buckets)
        elif h.buckets != buckets:
            return  # replicas disagree on bounds: refuse a corrupt merge
        pkey = (replica_id, "h", key)
        prev = self._hist_prev.get(pkey)
        count = int(state.get("count") or 0)
        if prev is not None and count < prev["count"]:
            prev = None  # replica restarted: new baseline, count it whole
        if prev is None:
            prev = {"bucket_counts": [0] * len(buckets),
                    "sum": 0.0, "count": 0}
        for i, c in enumerate(counts):
            h.bucket_counts[i] += max(0, c - prev["bucket_counts"][i])
        h.sum += float(state.get("sum") or 0.0) - prev["sum"]
        h.count += max(0, count - prev["count"])
        for entry in state.get("exemplars") or ():
            try:
                i, trace_id, value = entry
                i = int(i)
            except (TypeError, ValueError):
                continue
            if 0 <= i < len(h.exemplars):
                h.exemplars[i] = (trace_id, float(value))
        self._hist_prev[pkey] = {"bucket_counts": counts,
                                 "sum": float(state.get("sum") or 0.0),
                                 "count": count}

    def forget(self, replica_id: str):
        """Replica left the fleet: drop its gauge contributions and delta
        baselines. Counter and histogram TOTALS stay — fleet history is not
        un-happened by a deregistration."""
        with self.lock:
            self._replica_gauges.pop(replica_id, None)
            self._guard.forget(replica_id)
            for k in [k for k in self._hist_prev if k[0] == replica_id]:
                del self._hist_prev[k]
            self._last_ingest.pop(replica_id, None)

    def render(self) -> str:
        """Merged Prometheus/OpenMetrics exposition for GET /metrics/fleet."""
        with self.lock:
            agg: dict = {}
            for per in self._replica_gauges.values():
                for k, v in per.items():
                    agg[k] = agg.get(k, 0.0) + v
            with self._merged.lock:
                self._merged.gauges = agg
            return self._merged.render()

    def stats(self) -> dict:
        """Aggregation-plane introspection for /debug/costs."""
        with self.lock:
            return {
                "replicas": dict(self._last_ingest),
                "series": {
                    "counters": len(self._merged.counters),
                    "gauges": sum(len(g)
                                  for g in self._replica_gauges.values()),
                    "histograms": len(self._merged.histograms),
                },
                "schema_skews": sorted(
                    [[rid, ver] for rid, ver in self._schema_warned],
                    key=str),
            }
