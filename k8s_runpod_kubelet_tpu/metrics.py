"""Prometheus-style metrics registry (counters, gauges, histograms).

The reference has NO metrics (SURVEY.md §5.5: GetStatsSummary/GetMetricsResource
left nil). This build makes the north-star metric first-class: the
schedule->first-step latency is recorded as a histogram per pod, alongside
deploy/reconcile timings, slice-state gauges, and the serving SLO histograms
(TTFT / inter-token latency, sub-second buckets via per-metric ``describe``),
served as Prometheus text on the health server's /metrics.

Exposition follows the Prometheus text format rules scrapers actually
enforce: counters are exposed (HELP/TYPE and samples alike) under the
``<name>_total`` family name, every family carries a ``# TYPE`` line, and
label values escape ``\\``, ``"`` and newlines.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

_DEFAULT_BUCKETS = (0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600, 1800)


class _Hist:
    """Fixed-size cumulative buckets + sum/count, plus a bounded tail of raw
    observations for tests/debugging — memory stays O(buckets) for a process
    meant to run for months. Bucket bounds are per-histogram (describe(...,
    buckets=...)): sub-second TTFT/ITL histograms must not be crushed into a
    0.5s first bucket sized for pod-provisioning latencies."""

    __slots__ = ("buckets", "bucket_counts", "sum", "count", "recent")

    def __init__(self, buckets: tuple = _DEFAULT_BUCKETS):
        self.buckets = buckets
        self.bucket_counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0
        self.recent: list[float] = []

    def observe(self, value: float):
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.bucket_counts[i] += 1
        self.sum += value
        self.count += 1
        self.recent.append(value)
        if len(self.recent) > 1000:
            del self.recent[:500]


class Metrics:
    def __init__(self, clock=time.monotonic):
        # duration source for time_block timers; injectable so soak tests
        # driving a FakeClock see deterministic histogram durations
        self._clock = clock
        self.lock = threading.Lock()
        self.counters: dict[tuple[str, tuple], float] = {}
        self.gauges: dict[tuple[str, tuple], float] = {}
        self.histograms: dict[tuple[str, tuple], _Hist] = {}
        self.help: dict[str, str] = {}
        self.bucket_spec: dict[str, tuple] = {}  # name -> histogram bounds

    @staticmethod
    def _key(name: str, labels: Optional[dict]) -> tuple[str, tuple]:
        return name, tuple(sorted((labels or {}).items()))

    def describe(self, name: str, help_text: str,
                 buckets: Optional[tuple] = None):
        """HELP text for a metric; for histograms, optionally its bucket
        bounds (applied to label-sets created AFTER the describe — declare
        before first observe, as every call site in this repo does)."""
        self.help[name] = help_text
        if buckets is not None:
            bounds = tuple(sorted(float(b) for b in buckets))
            if not bounds:
                raise ValueError(f"{name}: buckets must be non-empty")
            self.bucket_spec[name] = bounds

    def incr(self, name: str, value: float = 1.0, labels: Optional[dict] = None):
        k = self._key(name, labels)
        with self.lock:
            self.counters[k] = self.counters.get(k, 0.0) + value

    def set_gauge(self, name: str, value: float, labels: Optional[dict] = None):
        with self.lock:
            self.gauges[self._key(name, labels)] = value

    def remove_gauge(self, name: str, labels: Optional[dict] = None):
        """Drop one labeled gauge series. For per-entity gauges (per-pod,
        per-replica) whose entity was deleted: a phantom series — e.g. a
        stalled=1 for a pod that no longer exists — must not alert forever."""
        with self.lock:
            self.gauges.pop(self._key(name, labels), None)

    def observe(self, name: str, value: float, labels: Optional[dict] = None):
        with self.lock:
            key = self._key(name, labels)
            h = self.histograms.get(key)
            if h is None:
                h = self.histograms[key] = _Hist(
                    self.bucket_spec.get(name, _DEFAULT_BUCKETS))
            h.observe(value)

    def time_block(self, name: str, labels: Optional[dict] = None):
        return _Timer(self, name, labels)

    def get_counter(self, name: str, labels: Optional[dict] = None) -> float:
        return self.counters.get(self._key(name, labels), 0.0)

    def get_observations(self, name: str, labels: Optional[dict] = None) -> list[float]:
        """Most recent raw observations (bounded tail; for tests/debugging)."""
        h = self.histograms.get(self._key(name, labels))
        return list(h.recent) if h else []

    # -- exposition ------------------------------------------------------------

    @staticmethod
    def _esc_label(v) -> str:
        """Label-value escaping per the exposition format: backslash first,
        then quote and newline."""
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @staticmethod
    def _esc_help(v: str) -> str:
        return v.replace("\\", "\\\\").replace("\n", "\\n")

    @classmethod
    def _labels_str(cls, labels: tuple) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{cls._esc_label(v)}"' for k, v in labels)
        return "{" + inner + "}"

    def _header(self, out: list[str], family: str, base_name: str, kind: str):
        """HELP (if described) + TYPE under the EXPOSED family name: a
        counter described as ``foo`` but sampled as ``foo_total`` must put
        its metadata on ``foo_total`` too, or scrapers see two different
        metrics (one with metadata and no samples, one untyped)."""
        if base_name in self.help:
            out.append(f"# HELP {family} {self._esc_help(self.help[base_name])}")
        out.append(f"# TYPE {family} {kind}")

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self.lock:
            names = sorted({n for n, _ in (*self.counters, *self.gauges, *self.histograms)})
            for name in names:
                counter_items = sorted((k, v) for k, v in self.counters.items()
                                       if k[0] == name)
                gauge_items = sorted((k, v) for k, v in self.gauges.items()
                                     if k[0] == name)
                hist_items = sorted(((k, h) for k, h in self.histograms.items()
                                     if k[0] == name), key=lambda kv: kv[0])
                if counter_items:
                    self._header(out, f"{name}_total", name, "counter")
                    for (_, lbls), v in counter_items:
                        out.append(f"{name}_total{self._labels_str(lbls)} {v}")
                if gauge_items:
                    self._header(out, name, name, "gauge")
                    for (_, lbls), v in gauge_items:
                        out.append(f"{name}{self._labels_str(lbls)} {v}")
                if hist_items:
                    self._header(out, name, name, "histogram")
                    for (_, lbls), h in hist_items:
                        for b, c in zip(h.buckets, h.bucket_counts):
                            lb = dict(lbls)
                            lb["le"] = str(b)
                            out.append(f"{name}_bucket{self._labels_str(tuple(sorted(lb.items())))} {c}")
                        lb = dict(lbls)
                        lb["le"] = "+Inf"
                        out.append(f"{name}_bucket{self._labels_str(tuple(sorted(lb.items())))} {h.count}")
                        out.append(f"{name}_sum{self._labels_str(lbls)} {h.sum}")
                        out.append(f"{name}_count{self._labels_str(lbls)} {h.count}")
        return "\n".join(out) + "\n"


class _Timer:
    def __init__(self, m: Metrics, name: str, labels: Optional[dict]):
        self.m, self.name, self.labels = m, name, labels

    def __enter__(self):
        self.t0 = self.m._clock()
        return self

    def __exit__(self, *exc):
        self.m.observe(self.name, self.m._clock() - self.t0, self.labels)
