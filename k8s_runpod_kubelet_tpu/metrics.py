"""Prometheus-style metrics registry (counters, gauges, histograms).

The reference has NO metrics (SURVEY.md §5.5: GetStatsSummary/GetMetricsResource
left nil). This build makes the north-star metric first-class: the
schedule->first-step latency is recorded as a histogram per pod, alongside
deploy/reconcile timings and slice-state gauges, served as Prometheus text on
the health server's /metrics.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

_DEFAULT_BUCKETS = (0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600, 1800)


class _Hist:
    """Fixed-size cumulative buckets + sum/count, plus a bounded tail of raw
    observations for tests/debugging — memory stays O(buckets) for a process
    meant to run for months."""

    __slots__ = ("bucket_counts", "sum", "count", "recent")

    def __init__(self):
        self.bucket_counts = [0] * len(_DEFAULT_BUCKETS)
        self.sum = 0.0
        self.count = 0
        self.recent: list[float] = []

    def observe(self, value: float):
        for i, b in enumerate(_DEFAULT_BUCKETS):
            if value <= b:
                self.bucket_counts[i] += 1
        self.sum += value
        self.count += 1
        self.recent.append(value)
        if len(self.recent) > 1000:
            del self.recent[:500]


class Metrics:
    def __init__(self):
        self.lock = threading.Lock()
        self.counters: dict[tuple[str, tuple], float] = {}
        self.gauges: dict[tuple[str, tuple], float] = {}
        self.histograms: dict[tuple[str, tuple], _Hist] = {}
        self.help: dict[str, str] = {}

    @staticmethod
    def _key(name: str, labels: Optional[dict]) -> tuple[str, tuple]:
        return name, tuple(sorted((labels or {}).items()))

    def describe(self, name: str, help_text: str):
        self.help[name] = help_text

    def incr(self, name: str, value: float = 1.0, labels: Optional[dict] = None):
        k = self._key(name, labels)
        with self.lock:
            self.counters[k] = self.counters.get(k, 0.0) + value

    def set_gauge(self, name: str, value: float, labels: Optional[dict] = None):
        with self.lock:
            self.gauges[self._key(name, labels)] = value

    def observe(self, name: str, value: float, labels: Optional[dict] = None):
        with self.lock:
            self.histograms.setdefault(self._key(name, labels), _Hist()).observe(value)

    def time_block(self, name: str, labels: Optional[dict] = None):
        return _Timer(self, name, labels)

    def get_counter(self, name: str, labels: Optional[dict] = None) -> float:
        return self.counters.get(self._key(name, labels), 0.0)

    def get_observations(self, name: str, labels: Optional[dict] = None) -> list[float]:
        """Most recent raw observations (bounded tail; for tests/debugging)."""
        h = self.histograms.get(self._key(name, labels))
        return list(h.recent) if h else []

    # -- exposition ------------------------------------------------------------

    @staticmethod
    def _labels_str(labels: tuple) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in labels)
        return "{" + inner + "}"

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self.lock:
            names = sorted({n for n, _ in (*self.counters, *self.gauges, *self.histograms)})
            for name in names:
                if name in self.help:
                    out.append(f"# HELP {name} {self.help[name]}")
                for (n, lbls), v in sorted(self.counters.items()):
                    if n == name:
                        out.append(f"{name}_total{self._labels_str(lbls)} {v}")
                for (n, lbls), v in sorted(self.gauges.items()):
                    if n == name:
                        out.append(f"{name}{self._labels_str(lbls)} {v}")
                for (n, lbls), h in sorted(self.histograms.items()):
                    if n != name:
                        continue
                    for b, c in zip(_DEFAULT_BUCKETS, h.bucket_counts):
                        lb = dict(lbls)
                        lb["le"] = str(b)
                        out.append(f"{name}_bucket{self._labels_str(tuple(sorted(lb.items())))} {c}")
                    lb = dict(lbls)
                    lb["le"] = "+Inf"
                    out.append(f"{name}_bucket{self._labels_str(tuple(sorted(lb.items())))} {h.count}")
                    out.append(f"{name}_sum{self._labels_str(lbls)} {h.sum}")
                    out.append(f"{name}_count{self._labels_str(lbls)} {h.count}")
        return "\n".join(out) + "\n"


class _Timer:
    def __init__(self, m: Metrics, name: str, labels: Optional[dict]):
        self.m, self.name, self.labels = m, name, labels

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.m.observe(self.name, time.monotonic() - self.t0, self.labels)
