"""Kubelet bootstrap: flags -> config -> clients -> provider -> controllers ->
servers -> recovery -> run loop.

Mirrors the reference's startup call stack (SURVEY.md §3.1, main.go:333-431)
with the config bugs fixed (every flag is wired; SURVEY.md §5.6):

  parse flags / env / file (precedence)      ~ main.go:59-90
  logging (level APPLIED, error sink)        ~ main.go:111-144
  K8s client (in-cluster || kubeconfig)      ~ main.go:464-494
  TPU client + health probe                  ~ kubelet.go:338,365
  Provider + background loops                ~ kubelet.go:334-379
  Node + Pod controllers (in-repo L3')       ~ main.go:167-214
  kubelet API server :10250                  ~ main.go:217-248
  health server :8080 (readyz = Ping)        ~ main.go:395-404
  LoadRunning state recovery                 ~ main.go:425-426
  signal -> graceful shutdown                ~ main.go:344-350

Run: python -m k8s_runpod_kubelet_tpu.cmd.main --node-name=virtual-tpu ...
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from .. import config as config_mod
from ..cloud import HttpTransport, TpuClient
from ..gang import GangExecutor, SshWorkerTransport
from ..health import HealthServer
from ..kube import RealKubeClient
from ..logging_util import setup_logging
from ..metrics import Metrics
from ..node import (KubeletApiServer, NodeController, PodController,
                    RefResourceController)
from ..provider import Provider
from ..tracing import Tracer

log = logging.getLogger("tpu-kubelet")


def parse_flags(argv: list[str]) -> argparse.Namespace:
    p = argparse.ArgumentParser("tpu-virtual-kubelet")
    # flag set mirrors main.go:59-73, GPU-isms retargeted
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("--node-name", dest="node_name", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--internal-ip", dest="internal_ip", default=None)
    p.add_argument("--listen-port", dest="listen_port", type=int, default=None)
    p.add_argument("--health-server-address", dest="health_address", default=None)
    p.add_argument("--reconcile-interval", dest="reconcile_interval_s",
                   type=float, default=None)
    p.add_argument("--max-cost-per-hr", dest="max_cost_per_hr", type=float,
                   default=None, help="cost ceiling, actually enforced")
    p.add_argument("--project", default=None)
    p.add_argument("--zone", default=None)
    p.add_argument("--zones", default=None, help="comma-separated allowed zones")
    p.add_argument("--default-generation", dest="default_generation", default=None)
    p.add_argument("--default-runtime-version", dest="default_runtime_version",
                   default=None,
                   help="TPU software/runtime version requested for created "
                        "slices (empty = the generation's catalog default)")
    p.add_argument("--max-total-chips", dest="max_total_chips", type=int,
                   default=None,
                   help="total google.com/tpu chips advertised as "
                        "allocatable (0 = largest catalog slice / live "
                        "quota when configured)")
    p.add_argument("--breaker-failure-threshold",
                   dest="breaker_failure_threshold", type=int, default=None,
                   help="consecutive cloud-API failures that trip the "
                        "circuit breaker open (and degrade the node)")
    p.add_argument("--breaker-reset-s", dest="breaker_reset_s", type=float,
                   default=None,
                   help="seconds an open breaker waits before its half-open "
                        "probe")
    p.add_argument("--tpu-api-endpoint", dest="tpu_api_endpoint", default=None)
    p.add_argument("--quota-api-endpoint", dest="quota_api_endpoint", default=None)
    p.add_argument("--log-level", dest="log_level", default=None)
    p.add_argument("--provider-config", dest="provider_config", default=None)
    p.add_argument("--os", dest="operating_system", default=None)
    p.add_argument("--preemption-requeue-limit", dest="preemption_requeue_limit",
                   type=int, default=None,
                   help="resubmit a preempted slice this many times before "
                        "failing the pod (elasticity; default 2)")
    p.add_argument("--max-provisioning-s", dest="max_provisioning_s",
                   type=float, default=None,
                   help="fail a pod whose slice queues longer than this "
                        "(0 = queue forever)")
    p.add_argument("--tls-cert-file", dest="tls_cert_file", default=None,
                   help="serve the kubelet API over TLS with this cert")
    p.add_argument("--tls-key-file", dest="tls_key_file", default=None)
    p.add_argument("--workload-path", dest="workload_path", default=None,
                   choices=["ssh", "api"],
                   help="workload launch/status path: 'ssh' drives docker on "
                        "the TPU VMs (real Cloud TPU API); 'api' uses the "
                        ":workload/:detailed aggregator endpoints")
    p.add_argument("--trace-export", dest="trace_export_path", default=None,
                   help="append pod-lifecycle spans (deploy/provisioning/"
                        "gang-launch/ready) to this JSONL file; render with "
                        "tools/trace_summary.py. Empty = in-memory ring "
                        "only, served at the health server's /debug/traces")
    p.add_argument("--telemetry-port", dest="telemetry_port", type=int,
                   default=None,
                   help="training-telemetry port injected into gang workers "
                        "(TPU_TELEMETRY_PORT; worker-0 aggregates step "
                        "heartbeats there; 0 = don't inject)")
    p.add_argument("--straggler-factor", dest="straggler_factor", type=float,
                   default=None,
                   help="workload watchdog: flag a host whose step time "
                        "exceeds this multiple of the across-host median")
    p.add_argument("--stall-timeout-s", dest="stall_timeout_s", type=float,
                   default=None,
                   help="emit TrainingStalled when a Running training pod's "
                        "scraped step counter stops advancing for this long")
    p.add_argument("--elastic-resize", dest="elastic_resize", default=None,
                   choices=["true", "false"],
                   help="honor the tpu.dev/elastic pod annotation: on "
                        "partial host loss, relaunch the gang on the "
                        "surviving workers (resharded from the latest "
                        "checkpoint) instead of requeueing the whole slice")
    p.add_argument("--elastic-grow-grace-s", dest="elastic_grow_grace_s",
                   type=float, default=None,
                   help="grow a shrunk gang back this long after capacity "
                        "returns even when no fresh checkpoint boundary is "
                        "seen in worker logs")
    return p.parse_args(argv)


def build(cfg: config_mod.Config, kube=None, tpu=None, worker_transport=None,
          token_provider=None):
    """Wire the full kubelet; injectable clients for tests.
    ``token_provider``: a pre-resolved credential provider (main() passes
    the one it probed at startup so credentials resolve exactly once)."""
    from ..cloud import SshWorkloadBackend

    metrics = Metrics()
    # one tracer per process: pod-lifecycle spans land in the ring behind
    # the health server's /debug/traces (and the JSONL export when set)
    tracer = Tracer(max_spans=cfg.trace_ring_size,
                    export_path=cfg.trace_export_path)
    kube = kube or RealKubeClient.from_env(cfg.kubeconfig)
    gang = GangExecutor(worker_transport or SshWorkerTransport(
        killable_exec=cfg.exec_killable))
    # "ssh": workload launch/status over the worker transport — works against
    # the PLAIN Cloud TPU v2 surface. "api": the :workload/:detailed extension
    # endpoints (fake server or a worker-agent aggregator deployment).
    backend = SshWorkloadBackend(gang) if cfg.workload_path == "ssh" else None
    # token_provider, not a frozen token string: GCP bearer tokens expire
    # in ~1h, and the provider chain (static -> ADC refresh -> metadata
    # server) keeps the kubelet healthy across expiries with a 401-refresh
    # retry in the transport (VERDICT r2 item 5). Ambient credentials are
    # ONLY attached when the endpoint HOST is *.googleapis.com — a fake
    # server / worker-agent aggregator (or a typo-squatted host) must
    # never receive the operator's real OAuth token
    from ..cloud import default_token_provider, is_google_api_endpoint

    # The static token belongs to whatever host tpu_api_endpoint names. Only
    # seed the Google provider chain with it when that host IS Google —
    # otherwise a fake-server/aggregator credential would ride the quota
    # transport to serviceusage.googleapis.com (foreign-token leak; the
    # ambient ADC/metadata chain is the right credential there).
    google_static_token = (cfg.tpu_api_token
                           if is_google_api_endpoint(cfg.tpu_api_endpoint)
                           else "")

    # chaos hardening (ISSUE 3): ONE circuit breaker, attached to the MAIN
    # TPU transport only (the provider watches it to degrade the node); the
    # quota transport stays breaker-free even when it is configured to the
    # same endpoint — it already fails fast, and a quota-surface outage must
    # not taint the node (or pollute the breaker metrics) while the TPU API
    # itself is healthy. Both transports get retry metrics + trace spans.
    from ..cloud import CircuitBreaker
    tpu_breaker = CircuitBreaker(
        failure_threshold=cfg.breaker_failure_threshold,
        reset_timeout_s=cfg.breaker_reset_s, metrics=metrics)

    def _make_transport(endpoint: str, breaker=None) -> HttpTransport:
        nonlocal token_provider
        kw = dict(breaker=breaker, metrics=metrics, tracer=tracer)
        if is_google_api_endpoint(endpoint):
            # one shared caching provider across transports (same scopes)
            token_provider = (token_provider or
                              default_token_provider(google_static_token))
            return HttpTransport(endpoint, token_provider=token_provider, **kw)
        # the static token is the credential OF cfg.tpu_api_endpoint's host;
        # any other non-Google host (e.g. a custom quota proxy) gets no
        # token rather than someone else's
        tok = cfg.tpu_api_token if endpoint == cfg.tpu_api_endpoint else ""
        return HttpTransport(endpoint, token=tok, **kw)

    transport = _make_transport(cfg.tpu_api_endpoint, breaker=tpu_breaker)
    # Quota is a different HOST in production (serviceusage.googleapis.com,
    # config.quota_api_endpoint); unset = the TPU transport, whose host 404s
    # the quota path against the real API -> capacity falls back to the
    # configured ceiling (get_chip_quota docstring).
    quota_transport = (_make_transport(cfg.quota_api_endpoint)
                       if cfg.quota_api_endpoint else None)
    tpu = tpu or TpuClient(transport, project=cfg.project, zone=cfg.zone,
                           workload_backend=backend,
                           quota_transport=quota_transport)
    provider = Provider(cfg, kube, tpu, gang_executor=gang, metrics=metrics,
                        tracer=tracer)
    node_controller = NodeController(kube, provider,
                                     status_interval_s=cfg.node_status_interval_s)
    pod_controller = PodController(kube, provider, cfg.node_name,
                                   resync_interval_s=cfg.reconcile_interval_s)
    # secret/configmap informer analog (main.go:180-193): object changes
    # turn pending-deploy retries immediate
    ref_controller = RefResourceController(kube, provider)
    api_server = KubeletApiServer(provider, port=cfg.listen_port,
                                  tls_cert=cfg.tls_cert_file,
                                  tls_key=cfg.tls_key_file,
                                  auth_token=cfg.api_auth_token)
    # metrics_enabled=False keeps /metrics dark (dev/airgapped runs);
    # the registry still exists so call sites never branch
    health = HealthServer(cfg.health_address, ready_func=provider.ping,
                          metrics=metrics if cfg.metrics_enabled else None,
                          tracer=tracer,
                          train_status=provider.training_status)
    return (provider, node_controller, pod_controller, ref_controller,
            api_server, health)


def main(argv=None) -> int:
    args = parse_flags(argv if argv is not None else sys.argv[1:])
    overrides = {k: v for k, v in vars(args).items()
                 if v is not None and k != "provider_config"}
    cfg = config_mod.load(file_path=args.provider_config, overrides=overrides)
    setup_logging(cfg.log_level, cfg.sentry_url,
                  os.environ.get("environment", "production"))
    log.info("starting tpu-virtual-kubelet node=%s project=%s zone=%s",
             cfg.node_name, cfg.project, cfg.zone)

    token_provider = None
    from ..cloud import is_google_api_endpoint
    if not cfg.tpu_api_token and is_google_api_endpoint(cfg.tpu_api_endpoint):
        # unlike the reference's hard RUNPOD_API_KEY check (main.go:306-311),
        # auth can also come from ADC or the metadata server — but keep the
        # fail-fast: when resolution lands on the metadata server, PROBE it
        # once (short timeout) so a no-credentials deployment still refuses
        # to start instead of failing slowly on every API call. The probed
        # provider is handed to build() so credentials resolve exactly once
        # (and the probe's token stays warm in its cache).
        from ..cloud import AuthError, MetadataTokenProvider, \
            default_token_provider
        try:
            token_provider = default_token_provider("")
            if isinstance(token_provider, MetadataTokenProvider):
                token_provider.timeout_s = 2.0
                token_provider()          # fail-fast probe; token cached
                token_provider.timeout_s = 10.0
        except AuthError as e:
            log.error("no TPU API credentials: set TPU_API_TOKEN, provide "
                      "ADC, or run with workload identity (%s)", e)
            return 1

    provider, nc, pc, rc, api, health = build(cfg,
                                              token_provider=token_provider)

    stop = threading.Event()

    def on_signal(signum, frame):
        log.info("signal %s — shutting down", signum)
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    health.start()
    nc.start()
    pc.start()
    rc.start()
    api.start()
    provider.start()
    provider.load_running()  # crash recovery (main.go:425-426)
    log.info("kubelet running: kubelet API :%d, health %s",
             cfg.listen_port, cfg.health_address)
    stop.wait()

    # reverse of startup: the ref watcher can kick deploys, so it must die
    # BEFORE the provider — a secret event during shutdown must not create
    # a billable slice on a stopped provider
    rc.stop()
    provider.stop()
    pc.stop()
    nc.stop()
    api.stop()
    health.stop()
    provider.tracer.close()  # flush the JSONL span export (daemon writer)
    log.info("shutdown complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
