"""Process bootstrap (L4')."""
