"""Dependency-free request tracing: spans, bounded ring, JSONL export, W3C
traceparent propagation.

The reference has NO per-request observability (SURVEY.md §5.5) and coarse
counters can't answer "why was THIS request slow?". This module is the
timing-attribution backbone both layers share:

- **serving**: every request gets a span tree (queue-wait -> prefill ->
  decode -> finish) keyed by the trace_id the client sent in its W3C
  ``traceparent`` header (or a fresh one), stamped back into the response.
- **kubelet**: pod lifecycle spans (deploy -> provisioning -> gang-launch ->
  ready) share a trace_id stored in the ``tpu.dev/trace-id`` annotation, so
  a slow request on a slice can be joined back to how that slice was born.

Design constraints, in order:
- stdlib only (the control plane must stay dependency-free);
- O(max_spans) memory for a process that runs for months (bounded deque);
- injected-clock-friendly: ``record()`` takes explicit start/end values in
  the caller's clock domain, so the provider's FakeClock tests and the
  engine's perf_counter bookkeeping both work without monkeypatching;
- export is one JSON object per line (JSONL), the format
  ``tools/trace_summary.py`` renders into waterfalls and TTFT/ITL rollups.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
import time
import uuid
from collections import deque
from typing import Callable, Optional

_TRACEPARENT_VERSION = "00"


@dataclasses.dataclass
class Span:
    """One finished span. ``start``/``end`` are in whatever clock domain the
    recorder used (wall seconds for everything this repo exports)."""

    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float
    end: float
    attrs: dict = dataclasses.field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return self.end - self.start

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "duration_s": self.duration_s,
            "attrs": self.attrs,
        }


def _hex_ok(s: str, n: int) -> bool:
    if len(s) != n or s != s.lower():
        return False
    try:
        return int(s, 16) != 0  # all-zero ids are invalid per the W3C spec
    except ValueError:
        return False


def parse_traceparent(header: Optional[str]) -> Optional[tuple[str, str]]:
    """W3C ``traceparent`` -> (trace_id, parent_span_id), or None if the
    header is absent/malformed. Lenient on the flags byte (we don't sample),
    strict on field shapes so a garbage header can't poison the trace store
    with unjoinable ids."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if version == "ff" or len(version) != 2:
        return None
    if not _hex_ok(trace_id, 32) or not _hex_ok(span_id, 16):
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    """The header value to stamp into a response (flags 01 = sampled: the
    span IS in the ring / export file)."""
    return f"{_TRACEPARENT_VERSION}-{trace_id}-{span_id}-01"


class Tracer:
    """Produces spans into a bounded in-memory ring plus optional JSONL file.

    ``clock`` is the wall clock used by the ``span()`` context manager and
    by callers that want "now" in the tracer's domain; ``monotonic`` times
    context-managed durations. Both are injectable for tests (the provider
    passes its FakeClock-compatible ``clock``). ``record()`` bypasses both
    and trusts the caller's numbers."""

    def __init__(self, max_spans: int = 2048, export_path: str = "",
                 clock: Callable[[], float] = time.time,
                 monotonic: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.monotonic = monotonic
        self.export_path = export_path
        self._ring: deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._local = threading.local()  # per-thread live-span stack
        self.dropped_exports = 0
        # export is ASYNC (ErrorSinkHandler's pattern): record() runs on the
        # serving engine's decode thread, so a slow/stalling disk must cost
        # a bounded-queue put, never a blocking write. One writer thread
        # owns the file; a full queue counts drops instead of blocking.
        self._export_queue: "queue.Queue[Optional[str]]" = \
            queue.Queue(maxsize=1024)
        self._writer: Optional[threading.Thread] = None
        if export_path:
            self._writer = threading.Thread(target=self._drain_exports,
                                            name="trace-export", daemon=True)
            self._writer.start()

    # -- ids -------------------------------------------------------------------

    @staticmethod
    def new_trace_id() -> str:
        return uuid.uuid4().hex  # 32 hex chars

    @staticmethod
    def new_span_id() -> str:
        return uuid.uuid4().hex[:16]

    # -- recording -------------------------------------------------------------

    def record(self, name: str, start: float, end: float,
               trace_id: Optional[str] = None, span_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               attrs: Optional[dict] = None) -> Span:
        """Record a finished span with caller-supplied times (the engine and
        provider know their intervals retroactively — no live span objects
        cross their threads)."""
        span = Span(trace_id=trace_id or self.new_trace_id(),
                    span_id=span_id or self.new_span_id(),
                    parent_id=parent_id or "",
                    name=name, start=float(start), end=float(end),
                    attrs=dict(attrs or {}))
        with self._lock:
            self._ring.append(span)
        self._export(span)
        return span

    def span(self, name: str, trace_id: Optional[str] = None,
             parent_id: Optional[str] = None, attrs: Optional[dict] = None):
        """Context manager for live code paths. Nested ``span()`` calls on
        the same thread auto-parent under the enclosing span and inherit its
        trace_id; the yielded object exposes ``trace_id``/``span_id`` and a
        mutable ``attrs`` dict."""
        return _LiveSpan(self, name, trace_id, parent_id, attrs)

    # -- reads -----------------------------------------------------------------

    def get_trace(self, trace_id: str) -> list[dict]:
        """All ringed spans of one trace, oldest first."""
        with self._lock:
            return [s.to_dict() for s in self._ring if s.trace_id == trace_id]

    def recent(self, n: int = 256) -> list[dict]:
        """The most recent finished spans, oldest first."""
        with self._lock:
            spans = list(self._ring)[-n:]
        return [s.to_dict() for s in spans]

    def query(self, trace_id: str = "") -> dict:
        """The /debug/traces response payload — ONE shape for every debug
        surface (serving front end and kubelet health server serve this
        verbatim): one trace's spans when filtered, else the recent ring."""
        return {"spans": (self.get_trace(trace_id) if trace_id
                          else self.recent()),
                "trace_id": trace_id or None}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- export ----------------------------------------------------------------

    def _export(self, span: Span):
        if self._writer is None:
            return
        try:
            self._export_queue.put_nowait(json.dumps(span.to_dict()) + "\n")
        except queue.Full:  # writer far behind (stalled disk): drop, count
            # under the ring lock: callers race the writer thread's OSError
            # path on this counter, and += on an instance attribute is not
            # atomic — two threads can read the same value and lose a drop
            with self._lock:
                self.dropped_exports += 1

    def _drain_exports(self):
        f = None
        try:
            while True:
                line = self._export_queue.get()
                if line is None:
                    return
                try:
                    if f is None:
                        os.makedirs(os.path.dirname(
                            os.path.abspath(self.export_path)), exist_ok=True)
                        f = open(self.export_path, "a",  # noqa: SIM115
                                 encoding="utf-8")
                    f.write(line)
                    f.flush()
                except OSError:
                    # full/readonly disk must never take down serving;
                    # same lock as _export's queue-full path — the two
                    # threads share this counter
                    with self._lock:
                        self.dropped_exports += 1
        finally:
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass

    def close(self):
        """Flush: FIFO sentinel behind pending lines, bounded join — spans
        recorded before close() reach the file (tests and clean shutdowns
        read it right after)."""
        if self._writer is None:
            return
        try:
            self._export_queue.put(None, timeout=1.0)
        except queue.Full:
            pass  # stalled writer: the bounded join below still applies
        self._writer.join(timeout=5.0)
        self._writer = None

    # -- live-span plumbing ----------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st


class _LiveSpan:
    """The span() context manager: wall start from the tracer's clock, the
    duration from its monotonic clock (wall clocks step; durations must
    not)."""

    def __init__(self, tracer: Tracer, name: str, trace_id, parent_id, attrs):
        self._tracer = tracer
        self.name = name
        self._explicit_trace = trace_id
        self._explicit_parent = parent_id
        self.attrs = dict(attrs or {})
        self.trace_id = ""
        self.span_id = Tracer.new_span_id()
        self.parent_id = ""

    def __enter__(self) -> "_LiveSpan":
        stack = self._tracer._stack()
        enclosing = stack[-1] if stack else None
        self.trace_id = (self._explicit_trace
                         or (enclosing.trace_id if enclosing else None)
                         or Tracer.new_trace_id())
        self.parent_id = (self._explicit_parent
                          or (enclosing.span_id if enclosing else ""))
        self._start_wall = self._tracer.clock()
        self._start_mono = self._tracer.monotonic()
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        duration = self._tracer.monotonic() - self._start_mono
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self._tracer.record(self.name, self._start_wall,
                            self._start_wall + duration,
                            trace_id=self.trace_id, span_id=self.span_id,
                            parent_id=self.parent_id, attrs=self.attrs)
        return False
