"""Minimal RFC 6455 (WebSocket) server-side framing for the kubelet API.

Implements exactly what `kubectl exec/attach` needs when it dials the kubelet
over WebSocket with the Kubernetes channel subprotocol
(`v4.channel.k8s.io`): handshake, masked client frames, binary server
frames, ping/pong, close. First payload byte is the channel id:

  0 stdin   (client -> kubelet)
  1 stdout  (kubelet -> client)
  2 stderr  (kubelet -> client)
  3 error   (kubelet -> client; terminal v1.Status JSON)
  4 resize  (client -> kubelet; {"Width":..,"Height":..})

The reference never had this — its exec/logs endpoints are stubs
(main.go:220-225, kubelet.go:2027-2066). Stdlib-only by design, like the
rest of the kubelet's HTTP surface.
"""

from __future__ import annotations

import base64
import hashlib
import struct
from typing import BinaryIO, Optional

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# opcodes
TEXT = 0x1
BINARY = 0x2
CLOSE = 0x8
PING = 0x9
PONG = 0xA

# k8s channel protocol channels
STDIN = 0
STDOUT = 1
STDERR = 2
ERROR = 3
RESIZE = 4

SUBPROTOCOLS = ("v4.channel.k8s.io", "v3.channel.k8s.io", "channel.k8s.io")


class WsError(Exception):
    pass


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def is_upgrade(headers) -> bool:
    return ("websocket" in (headers.get("Upgrade", "") or "").lower()
            and "upgrade" in (headers.get("Connection", "") or "").lower())


def choose_subprotocol(headers) -> Optional[str]:
    """Pick the first channel protocol we actually implement. RFC 6455 §4.2.2:
    never echo an unknown offer — a client offered only v5.channel.k8s.io
    would otherwise assume v5 semantics (stdin half-close) we don't speak."""
    offered = [p.strip() for p in
               (headers.get("Sec-WebSocket-Protocol", "") or "").split(",")
               if p.strip()]
    for want in SUBPROTOCOLS:
        if want in offered:
            return want
    return None


def handshake_response(headers) -> tuple[str, Optional[str]]:
    """Returns (response_text, subprotocol). Raises WsError on a bad request."""
    key = headers.get("Sec-WebSocket-Key")
    if not key:
        raise WsError("missing Sec-WebSocket-Key")
    sub = choose_subprotocol(headers)
    lines = [
        "HTTP/1.1 101 Switching Protocols",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Accept: {accept_key(key)}",
    ]
    if sub:
        lines.append(f"Sec-WebSocket-Protocol: {sub}")
    return "\r\n".join(lines) + "\r\n\r\n", sub


def _read_exact(rfile: BinaryIO, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise WsError("connection closed mid-frame")
        buf += chunk
    return buf


def read_raw_frame(rfile: BinaryIO) -> tuple[bool, int, bytes]:
    """One wire frame: (fin, opcode, unmasked payload)."""
    b1, b2 = _read_exact(rfile, 2)
    fin = bool(b1 & 0x80)
    op = b1 & 0x0F
    masked = b2 & 0x80
    length = b2 & 0x7F
    if length == 126:
        length = struct.unpack(">H", _read_exact(rfile, 2))[0]
    elif length == 127:
        length = struct.unpack(">Q", _read_exact(rfile, 8))[0]
    if length > 32 * 1024 * 1024:
        raise WsError(f"frame too large: {length}")
    mask = _read_exact(rfile, 4) if masked else b""
    data = _read_exact(rfile, length) if length else b""
    if mask:
        data = bytes(c ^ mask[i % 4] for i, c in enumerate(data))
    return fin, op, data


class MessageReader:
    """Assembles fragmented data messages while letting control frames
    (PING/PONG/CLOSE) interleave between fragments, as RFC 6455 §5.4 allows —
    a control frame returns immediately without disturbing the in-progress
    fragment sequence, which is preserved across calls."""

    def __init__(self, rfile: BinaryIO):
        self._rfile = rfile
        self._op: Optional[int] = None
        self._buf = b""

    def next(self) -> tuple[int, bytes]:
        while True:
            fin, op, data = read_raw_frame(self._rfile)
            if op >= 0x8:  # control frames are never fragmented
                return op, data
            if op != 0:
                self._op, self._buf = op, data
            else:
                if len(self._buf) + len(data) > 32 * 1024 * 1024:
                    # the per-frame cap must also bound the ASSEMBLED message,
                    # or endless fin=0 fragments grow _buf without limit
                    raise WsError("fragmented message too large")
                self._buf += data
            if fin:
                out = (self._op if self._op is not None else 0, self._buf)
                self._op, self._buf = None, b""
                return out


def read_frame(rfile: BinaryIO) -> tuple[int, bytes]:
    """Returns (opcode, payload) of one complete message. For streams where a
    control frame may interleave a fragmented message, hold a MessageReader
    instead (this helper cannot keep fragment state across calls)."""
    return MessageReader(rfile).next()


def write_frame(wfile: BinaryIO, payload: bytes, opcode: int = BINARY) -> None:
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([n])
    elif n < (1 << 16):
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    wfile.write(header + payload)
    wfile.flush()


def send_channel(wfile: BinaryIO, channel: int, data: bytes) -> None:
    write_frame(wfile, bytes([channel]) + data, BINARY)


def send_close(wfile: BinaryIO, code: int = 1000) -> None:
    write_frame(wfile, struct.pack(">H", code), CLOSE)
