"""Pod-watch controller: informer-event dispatch to the provider.

Re-implements node.NewPodController (main.go:180-193): a streaming watch,
field-scoped to ``spec.nodeName=<our node>`` exactly like the reference's scoped
informer (main.go:153), drives provider lifecycle calls:

  ADDED (unknown uid)                       -> provider.create_pod
  MODIFIED, no deletionTimestamp            -> provider.update_pod
  MODIFIED with deletionTimestamp           -> provider.delete_pod (graceful intent)
  DELETED                                   -> provider.delete_pod (object gone)

A periodic full-list resync repairs anything a dropped watch missed (informer
resync analog, main.go:151). Dispatch failures are retried with capped backoff
via an in-memory work queue rather than crashing the watch loop.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Optional

from ..kube.client import KubeApiError, KubeClient
from ..kube import objects as ko

log = logging.getLogger(__name__)

MAX_DISPATCH_RETRIES = 4


class PodController:
    def __init__(self, kube: KubeClient, provider, node_name: str, *,
                 resync_interval_s: float = 30.0):
        self.kube = kube
        self.provider = provider
        self.node_name = node_name
        self.resync_interval_s = resync_interval_s
        self._known: dict[str, str] = {}  # pod uid -> last seen resourceVersion
        self._deleting: set[str] = set()  # uids we already dispatched delete for
        self._stop = threading.Event()
        self._queue: "queue.Queue[tuple[str, dict, int]]" = queue.Queue()
        self._threads: list[threading.Thread] = []
        self.ready = threading.Event()

    # -- event handling (synchronous core, directly testable) ------------------

    def handle_event(self, ev_type: str, pod: dict):
        pod_uid = ko.uid(pod)
        if ev_type == "DELETED":
            self._known.pop(pod_uid, None)
            if pod_uid not in self._deleting:
                self._dispatch("delete", pod)
            self._deleting.discard(pod_uid)
            return
        if ev_type not in ("ADDED", "MODIFIED"):
            return
        if ko.deletion_timestamp(pod):
            if pod_uid not in self._deleting:
                self._deleting.add(pod_uid)
                self._dispatch("delete", pod)
            return
        if pod_uid not in self._known:
            self._known[pod_uid] = ko.meta(pod).get("resourceVersion", "")
            self._dispatch("create", pod)
        else:
            rv = ko.meta(pod).get("resourceVersion", "")
            if rv != self._known[pod_uid]:
                self._known[pod_uid] = rv
                self._dispatch("update", pod)

    def resync(self):
        """List-based repair: dispatch creates for unseen pods, deletes for pods
        the API no longer has but the provider still tracks."""
        self._sync_list(
            self.kube.list_pods(field_selector=f"spec.nodeName={self.node_name}"))

    def _sync_list(self, pods: list[dict]):
        seen = set()
        for pod in pods:
            seen.add(ko.uid(pod))
            if ko.deletion_timestamp(pod):
                self.handle_event("MODIFIED", pod)
            elif ko.uid(pod) not in self._known:
                self.handle_event("ADDED", pod)
        for tracked in self.provider.get_pods():
            if ko.uid(tracked) not in seen and not ko.is_terminal(tracked):
                self.handle_event("DELETED", tracked)

    def _dispatch(self, op: str, pod: dict, attempt: int = 1):
        try:
            if op == "create":
                self.provider.create_pod(pod)
            elif op == "update":
                self.provider.update_pod(pod)
            elif op == "delete":
                self.provider.delete_pod(pod)
        except Exception as e:  # noqa: BLE001 — a bad pod must not kill the loop
            if attempt >= MAX_DISPATCH_RETRIES:
                log.error("dispatch %s %s failed permanently: %s",
                          op, ko.namespaced_name(pod), e)
                return
            log.warning("dispatch %s %s failed (attempt %d): %s — requeueing",
                        op, ko.namespaced_name(pod), attempt, e)
            self._queue.put((op, pod, attempt + 1))

    # -- run loops -------------------------------------------------------------

    def start(self):
        self._threads = [
            threading.Thread(target=self._watch_loop, name="pod-watch", daemon=True),
            threading.Thread(target=self._retry_loop, name="pod-retry", daemon=True),
            threading.Thread(target=self._resync_loop, name="pod-resync", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)

    def _watch_loop(self):
        """List+watch with resourceVersion continuity (client-go Reflector):
        list anchors the RV, the watch resumes from it across reconnects so no
        event between streams is lost, and 410 Gone triggers a fresh list."""
        backoff = 0.2
        rv: Optional[str] = None
        selector = f"spec.nodeName={self.node_name}"
        while not self._stop.is_set():
            try:
                if rv is None:
                    pods, rv = self.kube.list_pods_rv(field_selector=selector)
                    self._sync_list(pods)
                stream = self.kube.watch_pods(field_selector=selector,
                                              stop=self._stop,
                                              resource_version=rv)
                self.ready.set()
                for ev in stream:
                    obj_rv = ko.meta(ev.object).get("resourceVersion", "")
                    if obj_rv:
                        rv = obj_rv  # resume point advances with every event
                    if ev.type == "BOOKMARK":
                        continue
                    self.handle_event(ev.type, ev.object)
                    backoff = 0.2
            except KubeApiError as e:
                if e.status == 410:
                    log.info("pod watch expired (410 Gone) — relisting")
                    rv = None
                    continue
                log.warning("pod watch broken: %s — reconnecting in %.1fs", e, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 10.0)
            except OSError as e:
                log.warning("pod watch broken: %s — reconnecting in %.1fs", e, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 10.0)

    def _retry_loop(self):
        while not self._stop.is_set():
            try:
                op, pod, attempt = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            # backoff on the stop event, not time.sleep: shutdown must not
            # wait out a retry delay, and soaks can release it instantly
            if self._stop.wait(min(0.2 * attempt, 1.0)):
                return
            self._dispatch(op, pod, attempt)

    def _resync_loop(self):
        while not self._stop.wait(self.resync_interval_s):
            try:
                self.resync()
            except (KubeApiError, OSError) as e:
                log.warning("resync failed: %s", e)
