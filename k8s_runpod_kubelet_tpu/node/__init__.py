"""L3': node + pod controllers and the kubelet HTTP API.

The reference imports this whole layer from the external virtual-kubelet library
(go.mod:53; node.NewPodController / node.NewNodeController / api.AttachPodRoutes,
main.go:167-248). That library does not exist for us, so this package
re-implements the reconciliation machinery from scratch (SURVEY.md §1 L3,
§7.4 hard-part #2):

- ``node_controller``: registers the virtual Node, renews its coordination lease,
  pushes node status.
- ``pod_controller``: watches pods field-scoped to our node and dispatches
  lifecycle calls to the provider, with a periodic list-based resync.
- ``api_server``: kubelet API on :10250 — and unlike the reference (which stubs
  exec/logs, main.go:220-225), logs and exec are real, backed by per-worker
  transports (SURVEY.md §5.8).
"""

from .node_controller import NodeController
from .pod_controller import PodController
from .ref_controller import RefResourceController
from .api_server import KubeletApiServer

__all__ = ["NodeController", "PodController", "RefResourceController",
           "KubeletApiServer"]
