"""Node registration, lease heartbeat, and status push.

Re-implements what the reference gets from node.NewNodeController
(main.go:196-211): create-or-adopt the Node object, renew a coordination lease
(kube-node-lease) so the cluster sees the kubelet as alive, and push node status
on an interval and on demand (NotifyNodeStatus analog, kubelet.go:1079-1095).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..kube.client import KubeApiError, KubeClient
from ..kube import objects as ko

log = logging.getLogger(__name__)

DEFAULT_LEASE_DURATION_S = 40
DEFAULT_STATUS_INTERVAL_S = 30.0


class NodeController:
    """Owns the virtual Node object's lifecycle.

    ``node_provider`` must expose:
      get_node() -> dict            full v1.Node (spec+status)
      ping() -> bool                cloud reachability (kubelet.go:1070-1076)
      set_status_listener(cb)       async "push node status now" callback
    """

    def __init__(self, kube: KubeClient, node_provider, *,
                 status_interval_s: float = DEFAULT_STATUS_INTERVAL_S,
                 lease_duration_s: int = DEFAULT_LEASE_DURATION_S,
                 clock: Callable[[], float] = time.time):
        self.kube = kube
        self.node_provider = node_provider
        self.clock = clock  # wall clock for lease renewTime (injectable)
        self.status_interval_s = status_interval_s
        self.lease_duration_s = lease_duration_s
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.ready = threading.Event()
        # owned-taint set as of the last successful sync: lets the heartbeat
        # skip the per-push get_node read when nothing changed (None = never
        # synced / last update failed -> do the full read-compare-update)
        self._synced_taint_keys: frozenset | None = None

    @property
    def node_name(self) -> str:
        return ko.name(self.node_provider.get_node())

    # -- one-shot operations (also used directly by tests) ---------------------

    def register_node(self) -> dict:
        """Create the Node, or adopt+update it if it already exists."""
        node = self.node_provider.get_node()
        try:
            created = self.kube.create_node(node)
            log.info("registered virtual node %s", ko.name(node))
            return created
        except KubeApiError as e:
            if not e.is_conflict:
                raise
            existing = self.kube.get_node(ko.name(node))
            node["metadata"]["resourceVersion"] = existing["metadata"].get("resourceVersion")
            updated = self.kube.update_node(node)
            log.info("adopted existing virtual node %s", ko.name(node))
            return updated

    def push_status(self):
        # Probe BEFORE building the snapshot: ping() refreshes cloud health
        # and the live chip quota, and get_node() reads both — built the
        # other way round, this patch would overwrite the quota-change push
        # from the probe's notify callback with stale capacity.
        self.node_provider.ping()
        node = self.node_provider.get_node()
        self.kube.patch_node_status(ko.name(node), {"status": node.get("status", {})})
        self._sync_taints(node)

    def _sync_taints(self, desired_node: dict):
        """Degraded-node signaling (ISSUE 3): taints live in node.spec, which
        the status patch can't touch — when the desired taint set changes
        (tpu.dev/api-unreachable appearing on breaker-open, vanishing on
        heal), update the Node spec so the scheduler stops/starts binding.

        Only taints whose keys THIS kubelet owns (the provider taint and the
        degraded taint) are added/removed; taints set by operators or other
        controllers (kubectl taint, node-lifecycle NoExecute...) are
        preserved untouched. When the desired owned set matches what we last
        successfully synced, the whole read-compare-update is skipped — the
        common heartbeat must not cost an extra get_node (tradeoff: an
        out-of-band edit of OUR taint keys is only repaired on the next
        actual state change)."""
        from ..provider.node_spec import DEGRADED_TAINT_KEY, TAINT_KEY
        owned = {TAINT_KEY, DEGRADED_TAINT_KEY}
        desired_owned = [t for t in desired_node.get("spec", {}).get("taints", [])
                         if t.get("key") in owned]
        desired_keys = frozenset(t.get("key") for t in desired_owned)
        if desired_keys == self._synced_taint_keys:
            return
        try:
            live = self.kube.get_node(ko.name(desired_node))
        except KubeApiError as e:
            log.warning("taint sync: get node failed: %s", e)
            return
        live_taints = live.get("spec", {}).get("taints", [])
        live_owned = [t for t in live_taints if t.get("key") in owned]
        if desired_keys == {t.get("key") for t in live_owned}:
            self._synced_taint_keys = desired_keys
            return
        foreign = [t for t in live_taints if t.get("key") not in owned]
        live.setdefault("spec", {})["taints"] = foreign + desired_owned
        try:
            self.kube.update_node(live)
            self._synced_taint_keys = desired_keys
            log.info("node taints updated: %s (foreign preserved: %s)",
                     sorted(t.get("key", "") for t in desired_owned),
                     sorted(t.get("key", "") for t in foreign))
        except KubeApiError as e:
            self._synced_taint_keys = None  # retry the full sync next push
            log.warning("taint sync: update failed (next push retries): %s", e)

    def renew_lease(self):
        """Coordination-lease heartbeat — the liveness signal node controllers in
        the cluster watch. Create on first renew, then bump renewTime."""
        import datetime
        name = self.node_name
        # metav1.MicroTime: fractional seconds BEFORE the zone designator.
        # Rendered from the injected clock so lease-renewal tests replay.
        now_micro = datetime.datetime.fromtimestamp(
            self.clock(), datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%S.%fZ")
        lease_spec = {
            "holderIdentity": name,
            "leaseDurationSeconds": self.lease_duration_s,
            "renewTime": now_micro,
        }
        try:
            lease = self.kube.get_lease(name)
            lease["spec"].update(lease_spec)
            self.kube.update_lease(lease)
        except KubeApiError as e:
            if not e.is_not_found:
                raise
            self.kube.create_lease({
                "metadata": {"name": name, "namespace": "kube-node-lease"},
                "spec": {**lease_spec, "acquireTime": lease_spec["renewTime"]},
            })

    # -- run loops -------------------------------------------------------------

    def start(self):
        self.register_node()
        self.push_status()
        self.renew_lease()
        self.node_provider.set_status_listener(self._on_notify)
        self.ready.set()
        self._threads = [
            threading.Thread(target=self._status_loop, name="node-status", daemon=True),
            threading.Thread(target=self._lease_loop, name="node-lease", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)

    def _on_notify(self, _node: Optional[dict] = None):
        try:
            self.push_status()
        except KubeApiError as e:
            log.warning("async node status push failed: %s", e)

    def _status_loop(self):
        while not self._stop.wait(self.status_interval_s):
            try:
                self.push_status()
            except KubeApiError as e:
                log.warning("node status push failed: %s", e)

    def _lease_loop(self):
        # renew at 1/4 of the lease duration, like the kubelet does
        interval = max(1.0, self.lease_duration_s / 4.0)
        while not self._stop.wait(interval):
            try:
                self.renew_lease()
            except KubeApiError as e:
                log.warning("lease renew failed: %s", e)
