"""Secret/ConfigMap change watcher — the reference controller's
secret/configmap informers (main.go:180-193), rebuilt for what they are
actually FOR here: a pod that failed to deploy because a referenced
Secret/ConfigMap was missing or stale sits Pending on a 30s retry ticker;
a watch event for that object turns the next retry immediate.

Services are deliberately NOT watched: the upstream virtual-kubelet
library consumes service informers to inject ``*_SERVICE_HOST/PORT`` env,
but Cloud TPU VMs are not on the cluster pod network — service ClusterIPs
are unreachable from the slice, so injecting them would hand workloads
dead addresses. The same reasoning already strips auto-injected cluster
env at translate time (translate.is_auto_injected_env).
"""

from __future__ import annotations

import logging
import threading

from ..kube.client import KubeClient
from ..kube import objects as ko

log = logging.getLogger(__name__)

WATCH_KINDS = ("secrets", "configmaps")


class RefResourceController:
    """One watch thread per kind; a change to an object some PENDING pod
    references kicks the provider's pending processor immediately."""

    def __init__(self, kube: KubeClient, provider,
                 kinds: tuple[str, ...] = WATCH_KINDS,
                 backoff_s: float = 1.0, max_backoff_s: float = 60.0):
        self.kube = kube
        self.provider = provider
        self.kinds = kinds
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> "RefResourceController":
        for kind in self.kinds:
            t = threading.Thread(target=self._watch_loop, args=(kind,),
                                 name=f"ref-watch-{kind}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def _watch_loop(self, kind: str):
        # Resume from the last-seen resourceVersion so the server's
        # periodic stream closes (~5min) don't replay ADDED for every
        # existing object — which would spuriously "immediate-retry"
        # pending pods on each reconnect. RV is tracked from EVERY event
        # (incl. bookmarks) and reset on 410 Gone (compacted).
        rv: str | None = None
        backoff = self.backoff_s
        while not self._stop.is_set():
            try:
                for ev in self.kube.watch_objects(kind, stop=self._stop,
                                                  resource_version=rv):
                    backoff = self.backoff_s  # stream is healthy
                    new_rv = (ev.object.get("metadata", {})
                              .get("resourceVersion"))
                    if new_rv:
                        rv = new_rv
                    if ev.type not in ("ADDED", "MODIFIED"):
                        continue
                    self._on_change(kind, ev.object)
                # generator exhausted = the server's NORMAL periodic close
                # (~5min, possibly with zero events on a quiet cluster):
                # that is a healthy stream, so the escalated backoff from
                # an earlier transient failure must not persist (r3
                # advisor) — reconnect promptly
                backoff = self.backoff_s
            except Exception as e:  # noqa: BLE001 — watch streams break; resume
                status = getattr(e, "status", None)
                if status == 410:
                    rv = None  # compacted: next connect replays, gate filters
                    log.debug("%s watch RV compacted; restarting fresh", kind)
                else:
                    # a PERSISTENT failure (e.g. RBAC denies cluster-wide
                    # secret watches) must be operator-visible, not a silent
                    # 1/s hot loop: warn with the growing backoff
                    log.warning("%s watch failed (%s) — pending-pod retries "
                                "fall back to the %.0fs ticker; retrying the "
                                "watch in %.0fs", kind, e,
                                self.provider.cfg.pending_retry_interval_s,
                                backoff)
                    backoff = min(backoff * 2, self.max_backoff_s)
            self._stop.wait(backoff)

    def _on_change(self, kind: str, obj: dict):
        ns, name = ko.namespace(obj), ko.name(obj)
        if self.provider.has_pending_reference(kind, ns, name):
            log.info("%s %s/%s changed — retrying pending deploys now "
                     "(instead of the %.0fs ticker)", kind[:-1], ns, name,
                     self.provider.cfg.pending_retry_interval_s)
            self.provider.process_pending_pods()
