"""Kubelet HTTP API (:10250): pods, container logs, command exec.

Analog of api.AttachPodRoutes (main.go:217-248) — but where the reference stubs
logs/exec ("not supported", main.go:220-225, kubelet.go:2027-2066), ours are real:
they fan out to the slice's workers through the provider's gang executor
(SURVEY.md §5.8 "our build should implement real GetContainerLogs/RunInContainer").

Endpoints (kubelet-API shaped):
  GET  /pods                                        -> v1.PodList of tracked pods
  GET  /containerLogs/{ns}/{pod}/{container}        -> text logs (?tailLines=N,
                                                       ?worker=I for one worker)
  POST /run/{ns}/{pod}/{container}                  -> {"cmd": [...]} run on
                                                       worker 0 (?worker=I), returns
                                                       output (old-kubelet /run shape)
  GET  /exec/{ns}/{pod}/{container}?command=...     -> WebSocket upgrade with the
                                                       Kubernetes channel protocol
                                                       (v4.channel.k8s.io): real
                                                       streaming `kubectl exec -it`
                                                       bridged to the worker
                                                       (?worker=I, &tty=true,
                                                       repeated &command= args)
  GET  /healthz                                     -> "ok"

Security: the reference serves :10250 through the virtual-kubelet lib's
cert-based API server (main.go:217-248). Ours matches that exposure model:
pass ``tls_cert``/``tls_key`` to serve HTTPS, and ``auth_token`` to require
``Authorization: Bearer <token>`` on every route except /healthz — our
endpoints can exec on workers, so they must never ship open.
"""

from __future__ import annotations

import hmac
import json
import logging
import re
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import ws

log = logging.getLogger(__name__)

_LOGS_RE = re.compile(r"^/containerLogs/(?P<ns>[^/]+)/(?P<pod>[^/]+)/(?P<container>[^/]+)$")
_RUN_RE = re.compile(r"^/run/(?P<ns>[^/]+)/(?P<pod>[^/]+)/(?P<container>[^/]+)$")

# ssh's own transport-failure complaints (client stderr). Exit 255 alone is
# ambiguous — the remote command may legitimately exit 255 — so the exec
# reaper only fires the remote kill when one of these accompanies it.
# Signatures are anchored to ssh's OWN message forms (client_loop:, kex_/
# ssh_exchange_, "ssh: connect to host", "Connection to X closed by remote
# host", "Timeout, server X not responding"); generic fragments like bare
# "timed out"/"connection reset"/"broken pipe" are deliberately absent —
# the remote command shares the stderr pipe, and e.g. a NESTED ssh failing
# inside the container would otherwise false-positive the reap against a
# possibly-recycled pid. (That nested-ssh case still matches the anchored
# forms — perfect disambiguation is impossible on a shared pipe; the
# anchored set trades a rare leaked remote process, pruned by the next
# exec's pidfile sweep, against TERMing innocent pids on common tool
# output.)
_SSH_TRANSPORT_ERRS = (b"client_loop:",
                       b"ssh_exchange_identification",
                       b"kex_exchange_identification",
                       b"ssh: connect to host",
                       b"closed by remote host",
                       b"connection closed by ",  # ssh's kex/auth-time form
                       b"timeout, server",
                       b"ssh: could not resolve hostname")


def _ssh_transport_failed(stderr_tail: bytes) -> bool:
    low = stderr_tail.lower()
    return any(sig in low for sig in _SSH_TRANSPORT_ERRS)


def _should_reap_remote(rc, stderr_tail: bytes) -> bool:
    """Whether the exec session's REMOTE process needs the remote kill:
    client abort (rc None — local ssh still running), local signal kill
    (rc < 0), or an ssh transport failure (rc 255 + stderr complaint).
    A remote command's own exit 255 (no transport complaint) is a normal
    completion — TERMing its possibly-recycled pid would be worse than
    leaving the pidfile for the next exec's prune sweep."""
    return rc is None or rc < 0 or (rc == 255
                                    and _ssh_transport_failed(stderr_tail))
_EXEC_RE = re.compile(r"^/exec/(?P<ns>[^/]+)/(?P<pod>[^/]+)/(?P<container>[^/]+)$")


class _Handler(BaseHTTPRequestHandler):
    # kubectl and client-go speak HTTP/1.1 and expect it back; the stdlib
    # default (HTTP/1.0) also disables keep-alive, which breaks clients that
    # pipeline /pods polls over one connection
    protocol_version = "HTTP/1.1"
    provider = None    # bound by server factory
    auth_token = None  # bound by server factory; None = no auth required
    # per-connection socket timeout: bounds how long a stalled peer (or a
    # deliberately idle TLS handshake) can pin its handler thread
    timeout = 30

    def log_message(self, *a):
        pass

    def _send(self, status: int, body: bytes, ctype: str = "text/plain"):
        if status >= 400:
            # error paths can return before reading a POST body; under
            # HTTP/1.1 keep-alive the unread bytes would be parsed as the
            # next request line — close instead of desyncing the connection
            self.close_connection = True
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if status >= 400:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _authorized(self) -> bool:
        """Bearer-token gate on every route but /healthz."""
        if self.auth_token is None:
            return True
        got = self.headers.get("Authorization", "")
        return hmac.compare_digest(got, f"Bearer {self.auth_token}")

    def do_GET(self):
        url = urlparse(self.path)
        q = parse_qs(url.query)
        if url.path == "/healthz":
            return self._send(200, b"ok")
        if not self._authorized():
            return self._send(401, b"unauthorized")
        if url.path == "/pods":
            pods = self.provider.get_pods()
            body = json.dumps({"kind": "PodList", "apiVersion": "v1",
                               "items": pods}).encode()
            return self._send(200, body, "application/json")
        m = _EXEC_RE.match(url.path)
        if m:
            return self._do_exec_ws(m, q)
        m = _LOGS_RE.match(url.path)
        if m:
            try:
                tail = int(q.get("tailLines", ["0"])[0]) or None
                worker = q.get("worker", [None])[0]
                worker = int(worker) if worker is not None else None
            except ValueError as e:
                return self._send(400, f"bad query parameter: {e}".encode())
            try:
                logs = self.provider.get_container_logs(
                    m["ns"], m["pod"], m["container"], tail_lines=tail,
                    worker=worker)
            except KeyError:
                return self._send(404, b"pod not found")
            except Exception as e:  # noqa: BLE001
                return self._send(500, f"logs failed: {e}".encode())
            return self._send(200, logs.encode())
        self._send(404, f"no route {url.path}".encode())

    # -- streaming exec (kubectl exec -it) -------------------------------------

    def _do_exec_ws(self, m, q):
        """Bridge a worker-side interactive exec over the WebSocket channel
        protocol. The whole session runs on this connection's handler thread
        plus one stdout pump thread."""
        if not ws.is_upgrade(self.headers):
            return self._send(400, b"exec requires a WebSocket upgrade "
                                   b"(kubectl exec dials ws)")
        cmd = q.get("command", [])
        if not cmd:
            return self._send(400, b"missing ?command=")
        try:
            worker = int(q.get("worker", ["0"])[0])
        except ValueError as e:
            return self._send(400, f"bad query parameter: {e}".encode())
        tty = q.get("tty", ["false"])[0].lower() in ("1", "true")
        # validate the whole handshake BEFORE spawning: the exec command has
        # side effects on the worker, so a client whose session will never
        # establish (bad key, or only unsupported subprotocols offered) must
        # be rejected without anything having run
        offered = (self.headers.get("Sec-WebSocket-Protocol", "") or "").strip()
        try:
            resp, sub = ws.handshake_response(self.headers)
        except ws.WsError as e:
            return self._send(400, str(e).encode())
        if offered and sub is None:
            return self._send(400, b"no supported subprotocol offered "
                                   b"(server speaks " +
                              ", ".join(ws.SUBPROTOCOLS).encode() + b")")
        try:
            proc = self.provider.stream_in_container(
                m["ns"], m["pod"], m["container"], cmd, worker=worker, tty=tty)
        except KeyError:
            return self._send(404, b"pod not found")
        except NotImplementedError as e:
            return self._send(501, str(e).encode())
        except Exception as e:  # noqa: BLE001
            return self._send(500, f"exec failed: {e}".encode())
        self.connection.sendall(resp.encode())
        self.close_connection = True
        self.connection.settimeout(None)  # interactive sessions idle freely
        wlock = threading.Lock()

        def send(channel: int, data: bytes):
            with wlock:
                ws.send_channel(self.wfile, channel, data)

        # last bytes of the transport's stderr: ssh exits 255 both for its
        # OWN transport failures and for a remote command that exits 255 —
        # only the former should trigger the remote reap, and ssh writes a
        # recognizable complaint to stderr when it is the transport dying
        err_tail = bytearray()

        def pump_stream(stream, channel: int):
            import os as _os
            fd = stream.fileno()
            client_gone = False
            try:
                while True:
                    data = _os.read(fd, 65536)
                    if not data:
                        break
                    if channel == ws.STDERR:
                        err_tail.extend(data)
                        del err_tail[:-512]
                    if not client_gone:
                        try:
                            send(channel, data)
                        except (OSError, ValueError):
                            # client is gone; KEEP draining so ssh's final
                            # stderr complaint still lands in err_tail (the
                            # reap decision needs it) and the remote side
                            # never blocks on a full pipe
                            client_gone = True
            except (OSError, ValueError):
                pass

        # stdout and (when the transport keeps it separate) stderr each get
        # their own pump onto their own k8s channel; the finisher waits for
        # both before reporting exit status and closing
        pumps = [threading.Thread(target=pump_stream,
                                  args=(proc.stdout, ws.STDOUT), daemon=True)]
        if getattr(proc, "stderr", None) is not None:
            pumps.append(threading.Thread(target=pump_stream,
                                          args=(proc.stderr, ws.STDERR),
                                          daemon=True))

        def finisher():
            for t in pumps:
                t.join()
            rc = proc.wait()
            status = ({"metadata": {}, "status": "Success"} if rc == 0 else
                      {"metadata": {}, "status": "Failure",
                       "reason": "NonZeroExitCode",
                       "message": f"command terminated with exit code {rc}",
                       "details": {"causes": [{"reason": "ExitCode",
                                               "message": str(rc)}]}})
            try:
                send(ws.ERROR, json.dumps(status).encode())
                with wlock:
                    ws.send_close(self.wfile)
            except OSError:
                pass  # client already gone

        for t in pumps:
            t.start()
        pump = threading.Thread(target=finisher, daemon=True)
        pump.start()
        reader = ws.MessageReader(self.rfile)
        try:
            while True:
                opcode, payload = reader.next()
                if opcode == ws.CLOSE:
                    break
                if opcode == ws.PING:
                    with wlock:
                        ws.write_frame(self.wfile, payload, ws.PONG)
                    continue
                if opcode not in (ws.BINARY, ws.TEXT) or not payload:
                    continue
                channel, data = payload[0], payload[1:]
                if channel == ws.STDIN and data:
                    try:
                        proc.stdin.write(data)
                        proc.stdin.flush()
                    except (OSError, ValueError):
                        break  # process ended; close frame follows from pump
                # RESIZE ignored: worker-side docker exec owns the pty size
        except (ws.WsError, OSError):
            pass  # client disconnected
        finally:
            try:
                proc.stdin.close()
            except (OSError, ValueError):
                pass
            # Reap the REMOTE process unless it completed normally:
            # - poll() is None: client-driven abort (we kill local ssh next)
            # - returncode == 255 AND ssh's stderr shows a transport
            #   complaint: network blip / sshd died — the remote process
            #   may have survived its client. A remote command that itself
            #   exits 255 is indistinguishable by code alone (r3 advisor),
            #   so without the stderr signature we treat 255 as a normal
            #   completion rather than TERM a possibly-recycled pid.
            # - returncode < 0: the local ssh was signal-killed
            # A normal remote completion (0..254) skips the reap: its pid
            # may already be recycled (TERM would hit an innocent process)
            # and the extra ssh round trip would tax every quick exec;
            # stale pidfiles are pruned by the next exec's launch wrapper.
            rc = proc.poll()
            if rc is not None:
                # ssh exited: its pipes are at/near EOF — give the pumps a
                # bounded moment to drain the LAST stderr chunk into
                # err_tail before the reap decision reads it
                for t in pumps:
                    t.join(timeout=2)
            if _should_reap_remote(rc, bytes(err_tail)):
                rk = getattr(proc, "remote_kill", None)
                if rk is not None:
                    rk()
            if proc.poll() is None:
                proc.kill()
            pump.join(timeout=5)

    def do_POST(self):
        if not self._authorized():
            return self._send(401, b"unauthorized")
        url = urlparse(self.path)
        q = parse_qs(url.query)
        m = _RUN_RE.match(url.path)
        if not m:
            return self._send(404, f"no route {url.path}".encode())
        length = int(self.headers.get("Content-Length") or 0)
        try:
            body = json.loads(self.rfile.read(length)) if length else {}
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (json.JSONDecodeError, ValueError) as e:
            return self._send(400, f"bad request body: {e}".encode())
        cmd = body.get("cmd") or q.get("cmd", [])
        if isinstance(cmd, str):
            cmd = cmd.split()
        try:
            worker = int(q.get("worker", ["0"])[0])
        except ValueError as e:
            return self._send(400, f"bad query parameter: {e}".encode())
        try:
            out = self.provider.run_in_container(m["ns"], m["pod"], m["container"],
                                                 cmd, worker=worker)
        except KeyError:
            return self._send(404, b"pod not found")
        except NotImplementedError as e:
            return self._send(501, str(e).encode())
        except Exception as e:  # noqa: BLE001 — exec failure must not kill the handler
            return self._send(500, f"exec failed: {e}".encode())
        self._send(200, out.encode() if isinstance(out, str) else out)


class KubeletApiServer:
    def __init__(self, provider, address: str = "0.0.0.0", port: int = 10250,
                 tls_cert: str = "", tls_key: str = "",
                 auth_token: str = ""):
        handler = type("BoundHandler", (_Handler,),
                       {"provider": provider,
                        "auth_token": auth_token or None})
        self._httpd = ThreadingHTTPServer((address, port), handler)
        self.tls = bool(tls_cert)
        if tls_cert:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key or None)
            # do_handshake_on_connect=False: accept() must not block the
            # single accept loop on a peer's handshake — the handshake runs
            # lazily on first I/O in the per-connection handler thread, and
            # the handler's socket timeout bounds a stalled peer
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True,
                                                 do_handshake_on_connect=False)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="kubelet-api", daemon=True)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "KubeletApiServer":
        self._thread.start()
        log.info("kubelet API listening on :%d", self.port)
        return self

    def stop(self):
        if self._thread.is_alive():  # shutdown() deadlocks on a never-started server
            self._httpd.shutdown()
        self._httpd.server_close()
