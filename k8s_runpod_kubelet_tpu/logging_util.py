"""Multi-sink structured logging.

Parity with the reference's fan-out slog handler (loghandler.go:7-55: every
record goes to stdout AND Sentry) — with two fixes the reference needed
(SURVEY.md §5.5): the configured level is actually applied (the reference
parses --log-level and ignores it, main.go:111-144), and the error sink is a
dependency-free HTTP poster (SENTRY_URL-shaped) with a bounded in-memory ring
of recent errors for the kubelet API/debug endpoints.
"""

from __future__ import annotations

import collections
import json
import logging
import queue
import threading
import traceback
import urllib.request
from typing import Optional

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR}


class ErrorSinkHandler(logging.Handler):
    """Posts WARNING+ records as JSON events to an HTTP sink (Sentry-shaped),
    never blocking the caller: one long-lived worker drains a bounded queue;
    when the queue is full (error storm) events are counted as dropped rather
    than spawning threads or blocking the logging call site."""

    def __init__(self, url: str, environment: str = "production",
                 timeout_s: float = 3.0, queue_size: int = 256):
        super().__init__(level=logging.WARNING)
        self.url = url
        self.environment = environment
        self.timeout_s = timeout_s
        self.dropped = 0
        self.recent: collections.deque = collections.deque(maxlen=100)
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue(maxsize=queue_size)
        self._worker = threading.Thread(target=self._drain, name="error-sink",
                                        daemon=True)
        self._worker.start()

    def emit(self, record: logging.LogRecord):
        event = {
            "message": record.getMessage(),
            "level": record.levelname.lower(),
            "logger": record.name,
            "environment": self.environment,
            "timestamp": record.created,
        }
        if record.exc_info:
            # log.exception() callers post the traceback, not a bare message
            # — a sink event without the stack is useless for the crash it
            # exists to report. exc_text caches the formatting across
            # multi-handler setups (the stdlib Formatter convention).
            if not record.exc_text:
                record.exc_text = "".join(
                    traceback.format_exception(*record.exc_info)).rstrip()
            event["exception"] = record.exc_text
        self.recent.append(event)
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            self.dropped += 1

    def close(self):
        """Flush: queue the sentinel BEHIND any pending events (FIFO) and
        join the worker, so the last error before a shutdown/crash actually
        reaches the sink instead of racing a daemon-thread exit. Bounded:
        a wedged sink can delay close by ~the post timeout, never hang it."""
        try:
            self._queue.put(None, timeout=1.0)
        except queue.Full:
            pass  # worker is far behind; the bounded join below still applies
        self._worker.join(timeout=self.timeout_s + 2.0)
        super().close()

    def _drain(self):
        while True:
            event = self._queue.get()
            if event is None:
                return
            try:
                req = urllib.request.Request(
                    self.url, data=json.dumps(event).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=self.timeout_s).read()
            except Exception:  # noqa: BLE001 — the error sink must never raise
                self.dropped += 1


def setup_logging(level: str = "info", sentry_url: str = "",
                  environment: str = "production") -> list[logging.Handler]:
    """Configure root logging: stdout always; HTTP error sink when configured.
    Returns the installed handlers."""
    root = logging.getLogger()
    root.setLevel(_LEVELS.get(level.lower(), logging.INFO))  # level APPLIED
    for h in list(root.handlers):
        root.removeHandler(h)
    stdout = logging.StreamHandler()
    stdout.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    root.addHandler(stdout)
    handlers: list[logging.Handler] = [stdout]
    if sentry_url:
        sink = ErrorSinkHandler(sentry_url, environment)
        root.addHandler(sink)
        handlers.append(sink)
    return handlers
