"""Single typed config: flags > env > YAML file, validated at startup.

Fixes the reference's config wiring bugs by construction (SURVEY.md §5.6:
--max-gpu-price parsed but never used, --log-level never applied,
PendingJobThreshold/MaxPendingTime defined but dead): every field here is read
somewhere, and load() applies a strict precedence.

Timing defaults keep parity with the reference's control loop (BASELINE.md):
30s reconcile, 30s pending retry, 15min pending give-up, 5min cleanup, and the
5/10/15-minute stuck-terminating ladder — plus TPU-specific knobs the reference
couldn't need (provisioning-queue tolerance, preemption requeue).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass
class Config:
    # identity
    node_name: str = "virtual-tpu"
    namespace: str = "default"
    internal_ip: str = "127.0.0.1"
    operating_system: str = "Linux"

    # cloud
    project: str = "tpu-project"
    zone: str = "us-central2-b"
    zones: list[str] = dataclasses.field(default_factory=list)  # allowed zones filter
    tpu_api_endpoint: str = "https://tpu.googleapis.com"
    # Where to read chip quota (Service Usage consumerQuotaMetrics). Empty =
    # same endpoint/transport as the TPU API — right for fake-server setups
    # whose one listener serves both surfaces; real deployments set
    # https://serviceusage.googleapis.com (the TPU API host itself 404s the
    # quota path, which degrades to the configured capacity ceiling).
    quota_api_endpoint: str = ""
    tpu_api_token: str = ""
    default_generation: str = "v5e"
    default_runtime_version: str = ""
    # how workloads launch + report per-worker status:
    #   "ssh" (default) — drive docker on the TPU VMs over SSH; needs only the
    #          real Cloud TPU v2 CRUD surface (cloud/workload_backend.py)
    #   "api" — POST :workload / GET :detailed extension endpoints (the fake
    #          server, or a worker-agent aggregator service)
    workload_path: str = "ssh"
    max_cost_per_hr: float = 0.0  # 0 = unlimited; actually enforced, unlike the
                                  # reference's --max-gpu-price (SURVEY.md §5.6)
    # total google.com/tpu chips this node advertises as allocatable — the
    # operator's cloud-quota ceiling. The K8s scheduler subtracts bound
    # pods' requests from allocatable itself, so this single number is what
    # bounds concurrently-bound chips (pods past it stay Unschedulable
    # instead of queueing invisibly in the cloud). 0 = the largest catalog
    # slice (parity-equivalent of the reference's static nvidia.com/gpu:4,
    # kubelet.go:1129, but configurable and quota-honest).
    max_total_chips: int = 0
    # non-tty kubectl-exec processes are wrapped so client disconnect can
    # TERM them remotely; requires /bin/sh in the workload image — set
    # False for distroless/scratch images (plain direct exec, no
    # disconnect-kill: kubectl-without-pty parity)
    exec_killable: bool = True

    # control loop timing (reference parity, kubelet.go)
    reconcile_interval_s: float = 30.0       # status poll        (kubelet.go:293)
    notify_interval_s: float = 10.0          # NotifyPods ticker  (kubelet.go:719)
    pending_retry_interval_s: float = 30.0   # pending deployer   (kubelet.go:735)
    max_pending_s: float = 15 * 60           # deploy give-up     (kubelet.go:788)
    cleanup_interval_s: float = 5 * 60       # GC sweep           (kubelet.go:307)
    node_status_interval_s: float = 30.0     # node push          (kubelet.go:1081)
    # stuck-terminating escalation ladder (kubelet.go:1333/:1285/:1350)
    stuck_reterminate_s: float = 5 * 60
    stuck_unreachable_force_s: float = 10 * 60
    stuck_force_delete_s: float = 15 * 60
    # TPU-specific: how long a queued resource may sit ACCEPTED/WAITING before we
    # fail the pod. 0 = forever (QueuedResources legitimately queue for hours;
    # SURVEY.md §7.4 hard-part #3 says don't trip the 15-min ladder on queueing).
    max_provisioning_s: float = 0.0
    # preemption: resubmit the slice instead of failing the pod, this many
    # times. Default 2: preemption is the COMMON case on spot/maintenance TPU
    # capacity (SURVEY.md §5.3), so the headline elasticity feature must be on
    # out of the box. 0 = fail the pod immediately (its Job restarts it).
    preemption_requeue_limit: int = 2

    # chaos hardening (ISSUE 3): the cloud-API circuit breaker trips OPEN
    # after this many consecutive transport failures and probes again
    # (half-open) after breaker_reset_s. The same threshold bounds the
    # reconcile loop's own API-error streak before the node goes degraded
    # (TpuApiReachable=False condition + tpu.dev/api-unreachable:NoSchedule
    # taint) even without a breaker wired.
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 30.0

    # fleet tier (ISSUE 4): the replica-aware router + SLO autoscaler.
    # Same env-var conventions as the breaker knobs (TPU_FLEET_* in
    # _ENV_MAP); flags live on fleet/router_main.py and serve_main.py.
    fleet_router_port: int = 8090
    fleet_heartbeat_interval_s: float = 2.0     # replica -> router cadence
    fleet_heartbeat_timeout_s: float = 10.0     # staler = suspect -> probe
    fleet_ttft_slo_s: float = 2.0               # scale-up SLO burn signal
    fleet_target_queue_per_replica: float = 4.0  # scale-up queue signal
    fleet_min_replicas: int = 1
    fleet_max_replicas: int = 4
    fleet_scale_up_cooldown_s: float = 30.0
    fleet_scale_down_cooldown_s: float = 120.0

    # disaggregated prefill/decode serving (ISSUE 9). serving_role is what
    # a serve_main replica registers to the fleet as: "unified" (default —
    # prefills and decodes, the single-pool mode and the fallback target),
    # "prefill" (computes KV, hands pages off) or "decode" (adopts pages,
    # streams tokens). Configuring BOTH pool ceilings > 0 switches
    # router_main's autoscaler to per-pool loops: the prefill pool scales
    # on TTFT burn + queue depth, the decode pool on ITL p95
    # (fleet_itl_slo_s) + pool-wide free KV pages
    # (fleet_min_free_kv_page_frac). fleet_handoff_timeout_s budgets the
    # prefill hop (compute + page push); past it the router falls back to
    # a single-hop route.
    serving_role: str = "unified"
    fleet_prefill_min_replicas: int = 0
    fleet_prefill_max_replicas: int = 0
    fleet_decode_min_replicas: int = 0
    fleet_decode_max_replicas: int = 0
    fleet_itl_slo_s: float = 0.25
    fleet_min_free_kv_page_frac: float = 0.1
    fleet_handoff_timeout_s: float = 30.0
    # device-native KV transfer (ISSUE 11): replicas advertising EQUAL
    # non-empty placement domains hand KV pages arena-to-arena (zero host
    # copies) on two-hop routes; every device-path failure downgrades to
    # the wire codec, then the unified fallback. fleet_placement_domain
    # overrides the auto-detected domain (proc:<host>:<pid> — the
    # co-location the in-process bus can prove); operators with a real
    # same-slice ICI transport set it per pool.
    fleet_device_transfer_enabled: bool = True
    fleet_placement_domain: str = ""
    # KV fabric (ISSUE 16): the fleet-wide prefix directory + pull hop.
    # fleet_placement_domain_mode governs auto-detection when no explicit
    # domain is set: "auto" prefers the gang scheduler's slice identity
    # (TPU_SLICE_NAME, host-qualified for the shm rung) and falls back to
    # proc:<host>:<pid>; "slice" warns when the slice identity is missing;
    # "proc" pins the ISSUE 11 one-process-per-domain behavior.
    # fleet_prefix_broadcast restores the pre-directory /prefix fan-out
    # (register on EVERY ready replica up front) for operators who prefer
    # eager replication over lazy pulls.
    fleet_prefix_directory_enabled: bool = True
    fleet_pull_timeout_s: float = 10.0      # one pull hop, export->adopt
    fleet_placement_domain_mode: str = "auto"
    fleet_prefix_broadcast: bool = False
    # global prefix-directory size (ISSUE 19 satellite): entries the
    # router-side LRU holds before evicting the least-recently-touched
    # prefix claim. The 4096 default matches the old hardcoded cap.
    fleet_directory_capacity: int = 4096
    # heterogeneous node pools (ISSUE 19): "[name=]generation:chips"
    # comma-list, e.g. "v5e:32,v5p:64". Non-empty switches router_main to
    # scheduler-routed capacity: autoscalers place through the
    # goodput-per-dollar FleetScheduler instead of creating pods
    # directly. "" = the legacy homogeneous fleet (no scheduler).
    fleet_pools: str = ""

    # training telemetry (ISSUE 5). telemetry_port is a gang COORDINATION
    # var: injected into every worker's env (TPU_TELEMETRY_PORT +
    # TPU_TELEMETRY_ADDRESS = worker-0) at gang launch so peers can post
    # step heartbeats to worker-0's aggregator; 0 disables injection.
    # stall_timeout_s doubles as the kubelet-side deadline: a Running
    # training pod whose scraped step counter stops advancing for this long
    # gets a TrainingStalled event + pod.training_stalled span.
    # straggler_factor is the workload watchdog's k×median step-time flag.
    telemetry_port: int = 8478
    straggler_factor: float = 3.0
    stall_timeout_s: float = 300.0

    # serving: paged KV prefix cache (ISSUE 8). kv_page_tokens is the
    # pool's allocation/trie-match granule (tokens per KV page);
    # kv_pool_pages sizes the preallocated HBM arena (0 = auto: one
    # decode-cache's worth); prefix_cache_enabled gates the cross-request
    # radix trie (register_prefix keeps working either way). Flags live on
    # workloads/serve_main.py; the helm chart wires the TPU_KV_* env onto
    # the router, whose autoscaler passes them through to the serving pods
    # it creates.
    kv_page_tokens: int = 16
    kv_pool_pages: int = 0
    prefix_cache_enabled: bool = True
    # paged decode loop (ISSUE 9): decode on per-slot page tables over the
    # shared arena — prefix hits and handed-off KV referenced zero-copy.
    # True = auto (on whenever the model/layout allows it); False pins the
    # contiguous slot-cache loop.
    kv_paged_decode: bool = True
    # paged-native prefill (ISSUE 14): scatter prefill chunks straight
    # into arena pages — no dense scratch cache or page-copy on the hot
    # path. True = auto (on whenever the paged loop runs); False pins the
    # dense-scratch prefill + adoption-copy route.
    kv_paged_prefill: bool = True
    # TP paged serving (ISSUE 12): how the paged arena places over a
    # tensor-parallel serving mesh. "auto" shards each section's kv-heads
    # axis over ``tensor`` like the contiguous cache (MLA latents
    # replicate — headless), degrading to a replicated arena when the
    # mesh doesn't divide the kv-head count; "replicate" pins the
    # replicated layout (pays HBM, keeps paged decode — an
    # odd-geometry/debugging escape hatch).
    kv_arena_sharding: str = "auto"
    # chunked prefill + streamed handoff (ISSUE 10). serving_chunk_tokens:
    # process prompts in chunks of this many tokens, yielding a decode
    # step to the engine between chunks (bounds co-resident streams' ITL
    # under long prefills) and — on disaggregated prefill replicas —
    # streaming each completed chunk's KV pages to the decode replica
    # while the next chunk computes (two-hop TTFT -> max(compute,
    # transfer)). 0 = monolithic. handoff_stream_window bounds the chunk
    # frames queued between prefill compute and the push (the overlap
    # window; compute blocks when transfer falls that far behind).
    serving_chunk_tokens: int = 0
    handoff_stream_window: int = 8
    # serving observability (ISSUE 17). serving_flight_recorder gates the
    # engine's per-decode-step flight recorder (bounded ring at GET
    # /debug/steps, phase split folded into serving.request spans);
    # serving_profiler_port starts the on-demand jax.profiler server
    # (train_main parity; 0 = off); serving_profile_capture enables the
    # GET /debug/profile?seconds= trace endpoint — off by default because
    # a capture stalls the device and writes replica-local files.
    serving_flight_recorder: bool = True
    serving_profiler_port: int = 0
    serving_profile_capture: bool = False
    # cost attribution plane (ISSUE 20): per-request chip-second/dollar
    # metering on the engine (costmeter.py — phase walls priced through
    # the generations.py table, per-tenant ledger at GET /debug/costs,
    # cumulative snapshots riding the fleet heartbeat into the router's
    # fleet-wide /metrics/fleet + /debug/costs).
    serving_cost_meter: bool = True
    # fleet SLO burn rates (ISSUE 17): multi-window breach fractions over
    # the TTFT/ITL/error-rate objectives, computed from registry
    # heartbeats on the injected clock. A signal "burns" when BOTH the
    # short and the long window consume error budget faster than
    # fleet_slo_burn_threshold x the sustainable rate; the autoscaler
    # uses that crossing (not a latched p95 sample) as its latency
    # corroboration. fleet_slo_budget_frac is the error budget (fraction
    # of time the SLO may be breached); fleet_slo_error_rate is the
    # request-error-ratio objective.
    fleet_slo_short_window_s: float = 300.0
    fleet_slo_long_window_s: float = 3600.0
    fleet_slo_burn_threshold: float = 2.0
    fleet_slo_budget_frac: float = 0.05
    fleet_slo_error_rate: float = 0.01

    # elastic gang training (ISSUE 6). elastic_resize is the global gate for
    # the tpu.dev/elastic pod annotation: on partial host loss an elastic
    # gang is relaunched on the SURVIVING workers (mesh rebuilt at the
    # surviving DP width, state resharded from the latest checkpoint)
    # instead of requeueing the whole slice, and grown back when capacity
    # returns — preferring a checkpoint boundary, with elastic_grow_grace_s
    # as the fallback deadline for workloads that never checkpoint.
    elastic_resize: bool = True
    elastic_grow_grace_s: float = 120.0

    # servers
    listen_port: int = 10250
    health_address: str = ":8080"
    metrics_enabled: bool = True
    # kubelet API security (exposure-model parity: the reference serves :10250
    # through the virtual-kubelet lib's cert-based server, main.go:217-248).
    # Our /run endpoint can exec on workers, so production deploys must set
    # these; empty = plaintext/unauthenticated (dev only).
    tls_cert_file: str = ""
    tls_key_file: str = ""
    api_auth_token: str = ""

    # logging
    log_level: str = "info"
    sentry_url: str = ""

    # tracing (pod-lifecycle spans; serving has its own --trace-export)
    trace_export_path: str = ""   # JSONL span export; "" = in-memory ring only
    trace_ring_size: int = 2048   # bounded span ring behind /debug/traces

    # paths
    kubeconfig: str = ""

    def validate(self) -> "Config":
        errs = []
        if not self.node_name:
            errs.append("node_name must be set")
        if self.reconcile_interval_s <= 0:
            errs.append("reconcile_interval_s must be > 0")
        for interval in ("notify_interval_s", "pending_retry_interval_s",
                         "cleanup_interval_s", "node_status_interval_s"):
            if getattr(self, interval) <= 0:
                errs.append(f"{interval} must be > 0 (a non-positive "
                            f"interval spins the loop hot)")
        if self.max_pending_s <= 0:
            errs.append("max_pending_s must be > 0")
        # the stuck-terminating ladder must escalate in order, or a pod
        # would be force-deleted before it was ever re-terminated
        if not 0 < self.stuck_reterminate_s <= self.stuck_unreachable_force_s \
                <= self.stuck_force_delete_s:
            errs.append("stuck_* ladder must satisfy 0 < reterminate <= "
                        "unreachable_force <= force_delete")
        if self.max_provisioning_s < 0:
            errs.append("max_provisioning_s must be >= 0 (0 = queue forever)")
        if self.preemption_requeue_limit < 0:
            errs.append("preemption_requeue_limit must be >= 0 (0 = fail "
                        "the pod immediately)")
        if self.max_cost_per_hr < 0:
            errs.append("max_cost_per_hr must be >= 0 (0 = unlimited)")
        if self.max_total_chips < 0:
            errs.append("max_total_chips must be >= 0 (0 = largest catalog "
                        "slice)")
        if not 0 < self.listen_port <= 65535:
            errs.append("listen_port must be in [1, 65535]")
        if self.fleet_ttft_slo_s <= 0:
            errs.append("fleet_ttft_slo_s must be > 0")
        if self.log_level.lower() not in ("debug", "info", "warning", "error"):
            errs.append(f"unknown log_level {self.log_level!r}")
        if self.workload_path not in ("ssh", "api"):
            errs.append(f"workload_path must be 'ssh' or 'api', "
                        f"got {self.workload_path!r}")
        if self.zones and self.zone not in self.zones:
            errs.append(f"zone {self.zone!r} not in allowed zones {self.zones}")
        if self.trace_ring_size <= 0:
            errs.append("trace_ring_size must be > 0")
        if self.breaker_failure_threshold <= 0:
            errs.append("breaker_failure_threshold must be > 0")
        if self.breaker_reset_s <= 0:
            errs.append("breaker_reset_s must be > 0")
        if self.fleet_router_port <= 0:
            errs.append("fleet_router_port must be > 0")
        if self.fleet_heartbeat_interval_s <= 0:
            errs.append("fleet_heartbeat_interval_s must be > 0")
        if self.fleet_heartbeat_timeout_s < self.fleet_heartbeat_interval_s:
            errs.append("fleet_heartbeat_timeout_s must be >= "
                        "fleet_heartbeat_interval_s (a replica must get at "
                        "least one beat per timeout window)")
        if self.fleet_min_replicas < 0:
            errs.append("fleet_min_replicas must be >= 0")
        if self.fleet_max_replicas < max(1, self.fleet_min_replicas):
            errs.append("fleet_max_replicas must be >= max(1, "
                        "fleet_min_replicas)")
        if self.fleet_target_queue_per_replica <= 0:
            errs.append("fleet_target_queue_per_replica must be > 0")
        if self.fleet_scale_up_cooldown_s < 0 \
                or self.fleet_scale_down_cooldown_s < 0:
            errs.append("fleet cooldowns must be >= 0")
        if self.serving_role not in ("unified", "prefill", "decode"):
            errs.append(f"serving_role must be unified/prefill/decode, "
                        f"got {self.serving_role!r}")
        for pool_field in ("fleet_prefill_min_replicas",
                           "fleet_prefill_max_replicas",
                           "fleet_decode_min_replicas",
                           "fleet_decode_max_replicas"):
            if getattr(self, pool_field) < 0:
                errs.append(f"{pool_field} must be >= 0 (0 = pool disabled)")
        if 0 < self.fleet_prefill_max_replicas \
                < self.fleet_prefill_min_replicas:
            errs.append("fleet_prefill_max_replicas must be >= "
                        "fleet_prefill_min_replicas when the pool is on")
        if 0 < self.fleet_decode_max_replicas \
                < self.fleet_decode_min_replicas:
            errs.append("fleet_decode_max_replicas must be >= "
                        "fleet_decode_min_replicas when the pool is on")
        if (self.fleet_prefill_max_replicas > 0) \
                != (self.fleet_decode_max_replicas > 0):
            # half a disaggregated fleet is not a mode: build() would
            # silently run the legacy whole-fleet loop and the operator
            # would believe the configured pool is managed
            errs.append(
                "disaggregated pools are configured together: set BOTH "
                "fleet_prefill_max_replicas and fleet_decode_max_replicas "
                "> 0 (or neither for the single-pool fleet); got "
                f"prefill_max={self.fleet_prefill_max_replicas}, "
                f"decode_max={self.fleet_decode_max_replicas}")
        if self.fleet_itl_slo_s < 0:
            errs.append("fleet_itl_slo_s must be >= 0 (0 = signal off)")
        if not 0 <= self.fleet_min_free_kv_page_frac < 1:
            errs.append("fleet_min_free_kv_page_frac must be in [0, 1) "
                        "(0 = signal off)")
        if self.fleet_handoff_timeout_s <= 0:
            errs.append("fleet_handoff_timeout_s must be > 0")
        if self.fleet_pull_timeout_s <= 0:
            errs.append("fleet_pull_timeout_s must be > 0")
        if self.fleet_directory_capacity <= 0:
            errs.append("fleet_directory_capacity must be > 0 (the "
                        "directory needs room for at least one prefix)")
        if self.fleet_pools:
            # parse errors surface at startup, not at first scale-up
            from .fleet.scheduler import PoolSpecError, parse_pools
            try:
                parse_pools(self.fleet_pools)
            except PoolSpecError as e:
                errs.append(f"fleet_pools: {e}")
        if self.fleet_placement_domain_mode not in ("auto", "proc", "slice"):
            errs.append(f"fleet_placement_domain_mode must be "
                        f"auto/proc/slice, got "
                        f"{self.fleet_placement_domain_mode!r}")
        if not 0 <= self.telemetry_port <= 65535:
            errs.append("telemetry_port must be in [0, 65535] (0 = off)")
        if self.straggler_factor <= 1.0:
            errs.append("straggler_factor must be > 1 (1x median would flag "
                        "half the fleet)")
        if self.stall_timeout_s <= 0:
            errs.append("stall_timeout_s must be > 0")
        if self.elastic_grow_grace_s < 0:
            errs.append("elastic_grow_grace_s must be >= 0")
        if self.kv_page_tokens < 1:
            errs.append("kv_page_tokens must be >= 1 (tokens per KV page)")
        if self.kv_pool_pages < 0:
            errs.append("kv_pool_pages must be >= 0 (0 = auto-size)")
        if self.kv_arena_sharding not in ("auto", "replicate"):
            errs.append("kv_arena_sharding must be 'auto' or 'replicate'")
        if self.serving_chunk_tokens < 0:
            errs.append("serving_chunk_tokens must be >= 0 (0 = "
                        "monolithic prefill)")
        if self.handoff_stream_window < 1:
            errs.append("handoff_stream_window must be >= 1 (at least one "
                        "frame in flight, or the stream cannot move)")
        if not 0 <= self.serving_profiler_port <= 65535:
            errs.append("serving_profiler_port must be in [0, 65535] "
                        "(0 = off)")
        if self.fleet_slo_short_window_s <= 0:
            errs.append("fleet_slo_short_window_s must be > 0")
        if self.fleet_slo_long_window_s < self.fleet_slo_short_window_s:
            errs.append("fleet_slo_long_window_s must be >= "
                        "fleet_slo_short_window_s (the long window "
                        "confirms the short one)")
        if self.fleet_slo_burn_threshold <= 0:
            errs.append("fleet_slo_burn_threshold must be > 0")
        if not 0 < self.fleet_slo_budget_frac < 1:
            errs.append("fleet_slo_budget_frac must be in (0, 1) — it is "
                        "the fraction of time the SLO may be breached")
        if not 0 < self.fleet_slo_error_rate < 1:
            errs.append("fleet_slo_error_rate must be in (0, 1)")
        if errs:
            raise ValueError("invalid config: " + "; ".join(errs))
        return self


_ENV_MAP = {
    "KUBELET_API_TOKEN": "api_auth_token",
    "TPU_API_TOKEN": "tpu_api_token",
    "TPU_API_ENDPOINT": "tpu_api_endpoint",
    "TPU_QUOTA_API_ENDPOINT": "quota_api_endpoint",
    "TPU_PROJECT": "project",
    "TPU_ZONE": "zone",
    "TPU_ZONES": "zones",
    "NODE_NAME": "node_name",
    "NAMESPACE": "namespace",
    "SENTRY_URL": "sentry_url",
    "LOG_LEVEL": "log_level",
    "TPU_DEFAULT_GENERATION": "default_generation",
    "TPU_DEFAULT_RUNTIME_VERSION": "default_runtime_version",
    "TPU_WORKLOAD_PATH": "workload_path",
    "TPU_MAX_COST_PER_HR": "max_cost_per_hr",
    "TPU_MAX_TOTAL_CHIPS": "max_total_chips",
    "TPU_LISTEN_PORT": "listen_port",
    "TPU_HEALTH_ADDRESS": "health_address",
    "TPU_RECONCILE_INTERVAL_S": "reconcile_interval_s",
    "TPU_MAX_PROVISIONING_S": "max_provisioning_s",
    "TPU_PREEMPTION_REQUEUE_LIMIT": "preemption_requeue_limit",
    "TPU_BREAKER_FAILURE_THRESHOLD": "breaker_failure_threshold",
    "TPU_BREAKER_RESET_S": "breaker_reset_s",
    "TPU_TRACE_EXPORT_PATH": "trace_export_path",
    "TPU_FLEET_ROUTER_PORT": "fleet_router_port",
    "TPU_FLEET_HEARTBEAT_INTERVAL_S": "fleet_heartbeat_interval_s",
    "TPU_FLEET_HEARTBEAT_TIMEOUT_S": "fleet_heartbeat_timeout_s",
    "TPU_FLEET_TTFT_SLO_S": "fleet_ttft_slo_s",
    "TPU_FLEET_TARGET_QUEUE_PER_REPLICA": "fleet_target_queue_per_replica",
    "TPU_FLEET_MIN_REPLICAS": "fleet_min_replicas",
    "TPU_FLEET_MAX_REPLICAS": "fleet_max_replicas",
    "TPU_FLEET_SCALE_UP_COOLDOWN_S": "fleet_scale_up_cooldown_s",
    "TPU_FLEET_SCALE_DOWN_COOLDOWN_S": "fleet_scale_down_cooldown_s",
    "TPU_KV_PAGE_TOKENS": "kv_page_tokens",
    "TPU_KV_POOL_PAGES": "kv_pool_pages",
    "TPU_PREFIX_CACHE_ENABLED": "prefix_cache_enabled",
    "TPU_KV_PAGED_DECODE": "kv_paged_decode",
    "TPU_KV_PAGED_PREFILL": "kv_paged_prefill",
    "TPU_KV_ARENA_SHARDING": "kv_arena_sharding",
    "TPU_SERVING_CHUNK_TOKENS": "serving_chunk_tokens",
    "TPU_HANDOFF_STREAM_WINDOW": "handoff_stream_window",
    "TPU_SERVING_FLIGHT_RECORDER": "serving_flight_recorder",
    "TPU_SERVING_PROFILER_PORT": "serving_profiler_port",
    "TPU_SERVING_PROFILE_CAPTURE": "serving_profile_capture",
    "TPU_SERVING_COST_METER": "serving_cost_meter",
    "TPU_FLEET_SLO_SHORT_WINDOW_S": "fleet_slo_short_window_s",
    "TPU_FLEET_SLO_LONG_WINDOW_S": "fleet_slo_long_window_s",
    "TPU_FLEET_SLO_BURN_THRESHOLD": "fleet_slo_burn_threshold",
    "TPU_FLEET_SLO_BUDGET_FRAC": "fleet_slo_budget_frac",
    "TPU_FLEET_SLO_ERROR_RATE": "fleet_slo_error_rate",
    "TPU_SERVING_ROLE": "serving_role",
    "TPU_FLEET_PREFILL_MIN_REPLICAS": "fleet_prefill_min_replicas",
    "TPU_FLEET_PREFILL_MAX_REPLICAS": "fleet_prefill_max_replicas",
    "TPU_FLEET_DECODE_MIN_REPLICAS": "fleet_decode_min_replicas",
    "TPU_FLEET_DECODE_MAX_REPLICAS": "fleet_decode_max_replicas",
    "TPU_FLEET_ITL_SLO_S": "fleet_itl_slo_s",
    "TPU_FLEET_MIN_FREE_KV_PAGE_FRAC": "fleet_min_free_kv_page_frac",
    "TPU_FLEET_HANDOFF_TIMEOUT_S": "fleet_handoff_timeout_s",
    "TPU_FLEET_DEVICE_TRANSFER_ENABLED": "fleet_device_transfer_enabled",
    "TPU_FLEET_PLACEMENT_DOMAIN": "fleet_placement_domain",
    "TPU_FLEET_PREFIX_DIRECTORY_ENABLED": "fleet_prefix_directory_enabled",
    "TPU_FLEET_PULL_TIMEOUT_S": "fleet_pull_timeout_s",
    "TPU_FLEET_PLACEMENT_DOMAIN_MODE": "fleet_placement_domain_mode",
    "TPU_FLEET_PREFIX_BROADCAST": "fleet_prefix_broadcast",
    "TPU_FLEET_DIRECTORY_CAPACITY": "fleet_directory_capacity",
    "TPU_FLEET_POOLS": "fleet_pools",
    "TPU_TELEMETRY_PORT": "telemetry_port",
    "TPU_STRAGGLER_FACTOR": "straggler_factor",
    "TPU_STALL_TIMEOUT_S": "stall_timeout_s",
    "TPU_ELASTIC_RESIZE_ENABLED": "elastic_resize",
    "TPU_ELASTIC_GROW_GRACE_S": "elastic_grow_grace_s",
}


def load(file_path: Optional[str] = None, env: Optional[dict] = None,
         overrides: Optional[dict] = None) -> Config:
    """Build config with precedence: overrides (flags) > env > file > defaults."""
    values: dict = {}
    if file_path:
        import yaml
        with open(file_path) as f:
            loaded = yaml.safe_load(f) or {}
        known = {f.name for f in dataclasses.fields(Config)}
        unknown = set(loaded) - known
        if unknown:
            raise ValueError(f"unknown config keys in {file_path}: {sorted(unknown)}")
        values.update(loaded)
    env = os.environ if env is None else env
    for env_key, field in _ENV_MAP.items():
        if env.get(env_key):
            values[field] = env[env_key]
    if overrides:
        values.update({k: v for k, v in overrides.items() if v is not None})
    # coerce numerics/lists that may arrive as strings from env/flags
    cfg = Config()
    for f in dataclasses.fields(Config):
        if f.name not in values:
            continue
        v = values[f.name]
        cur = getattr(cfg, f.name)
        if isinstance(cur, bool) and isinstance(v, str):
            v = v.lower() in ("1", "true", "yes")
        elif isinstance(cur, float) and not isinstance(v, float):
            v = float(v)
        elif isinstance(cur, int) and not isinstance(v, (int, bool)):
            v = int(v)
        elif isinstance(cur, list) and isinstance(v, str):
            v = [s.strip() for s in v.split(",") if s.strip()]
        setattr(cfg, f.name, v)
    return cfg.validate()
