"""Workload launch/status backends: how `:workload` and `:detailed` happen.

The real Cloud TPU v2 API only has queued-resource create/get/list/delete —
it knows nothing about launching containers or per-worker health (the
reference's cloud did both: deploy ran the image, runpod_client.go:522-634,
and GetDetailedPodStatus returned runtime info, :773-818). The kubelet
therefore needs a strategy for the workload half:

- ApiWorkloadBackend: POST `:workload` / GET `:detailed` extension endpoints.
  Used against the in-repo fake server and any deployment that runs a
  worker-agent aggregator service speaking the same shape.
- SshWorkloadBackend: the REAL-CLOUD path (VERDICT r1 item 2). Launches the
  workload container on every TPU VM over the per-worker exec transport
  (gang/exec.py) with all-or-nothing semantics, and aggregates per-worker
  docker state into the same DetailedStatus the reconcile loop consumes.
  Needs only the plain v2 surface plus SSH to the VMs.

Both produce identical DetailedStatus shapes, so provider/reconcile.py is
backend-agnostic; tests/test_ssh_workload.py runs the full pod lifecycle with
the fake server's extension endpoints DISABLED to prove it.
"""

from __future__ import annotations

import logging
import shlex
import threading
from typing import Any, Optional

from ..gang.exec import GangExecutor, WorkerExecError
from .types import DetailedStatus, QueuedResource, QueuedResourceState, WorkerRuntimeInfo

log = logging.getLogger(__name__)


class WorkloadBackendError(Exception):
    """Launch/status failure; the reconcile loop retries next pass."""


class WorkloadBackend:
    """Protocol. ``client`` is the owning TpuClient (for resource reads).
    ``worker_ids`` (elastic resize, ISSUE 6) restricts a launch to a
    surviving subset of the slice's workers; None = the whole gang."""

    def start(self, client, name: str, spec, worker_env, zone,
              worker_ids=None) -> None:
        raise NotImplementedError

    def detailed_status(self, client, name: str, zone) -> DetailedStatus:
        raise NotImplementedError


class ApiWorkloadBackend(WorkloadBackend):
    """Extension endpoints over the cloud transport (fake server / aggregator)."""

    def start(self, client, name, spec, worker_env, zone, worker_ids=None):
        from .transport import TransportError
        body: dict[str, Any] = {"workload": spec.to_json()}
        if worker_env is not None:
            body["workerEnv"] = worker_env
        if worker_ids is not None:
            body["workerIds"] = sorted(worker_ids)
        try:
            client.transport.request(
                "POST", f"{client._base(zone)}/queuedResources/{name}:workload",
                body=body, expect_status=(200, 204))
        except TransportError as e:
            raise client._wrap(e, f"start workload on {name}") from e

    def detailed_status(self, client, name, zone):
        from .transport import TransportError
        from .tpu_client import _resource_from_json
        try:
            d = client.transport.request(
                "GET", f"{client._base(zone)}/queuedResources/{name}:detailed")
        except TransportError as e:
            if e.status == 404:
                return _not_found(name)
            raise client._wrap(e, f"detailed status {name}") from e
        runtime = [WorkerRuntimeInfo(**w) for w in d.get("runtime", [])]
        ports = {int(k): int(v) for k, v in d.get("ports", {}).items()}
        return DetailedStatus(resource=_resource_from_json(d["resource"]),
                              runtime=runtime, ports=ports)


def _not_found(name: str) -> DetailedStatus:
    return DetailedStatus(resource=QueuedResource(
        name=name, accelerator_type="", runtime_version="",
        state=QueuedResourceState.NOT_FOUND,
        state_message="queued resource not found"))


class SshWorkloadBackend(WorkloadBackend):
    """Real-cloud path: docker over the worker exec transport.

    Launch = `docker run -d --net=host --privileged` on every worker (gang:
    a partial launch is torn down and reported failed); status = `docker
    inspect` fanned out and folded into WorkerRuntimeInfo. The workload
    container is named ``container_name`` so logs/exec (gang/exec.py) and
    this backend agree on the target.
    """

    def __init__(self, executor: GangExecutor, container_name: str = "workload"):
        self.executor = executor
        self.container_name = container_name
        self._lock = threading.Lock()
        # qr name -> container ports (host networking: container == host port);
        # best-effort cache for readiness — empty after a kubelet restart
        # until docker inspect refreshes it below
        self._ports: dict[str, dict[int, int]] = {}

    # -- launch ----------------------------------------------------------------

    def _run_script(self, spec, env: dict[str, str]) -> list[str]:
        """The per-worker launch command. Host networking (TPU pods address
        workers by VM hostname:port), privileged for /dev/accel*, stale
        container removed first so relaunch-after-crash is idempotent. The
        workload's port list rides a docker label so a restarted kubelet can
        recover it from `docker inspect` (readiness needs it)."""
        parts = ["docker rm -f", shlex.quote(self.container_name),
                 ">/dev/null 2>&1 || true; ", "docker run -d --name",
                 shlex.quote(self.container_name),
                 "--net=host --privileged --restart=no",
                 "-l", shlex.quote("tpu-ports=" + (",".join(spec.ports) or "-"))]
        merged = dict(spec.env)
        merged.update(env)
        for k, v in sorted(merged.items()):
            parts.append(f"-e {shlex.quote(f'{k}={v}')}")
        parts.append(shlex.quote(spec.image))
        for c in list(spec.command) + list(spec.args):
            parts.append(shlex.quote(c))
        return ["sh", "-c", " ".join(parts)]

    def start(self, client, name, spec, worker_env, zone, worker_ids=None):
        qr = client.get_queued_resource(name, zone=zone)
        if not qr.workers:
            raise WorkloadBackendError(f"slice {name} reports no workers")
        workers = qr.workers
        if worker_ids is not None:
            wanted = set(worker_ids)
            workers = [w for w in qr.workers if w.worker_id in wanted]
            if len(workers) != len(wanted):
                have = {w.worker_id for w in qr.workers}
                raise WorkloadBackendError(
                    f"slice {name} has no workers {sorted(wanted - have)}")
        n = len(workers)
        envs = worker_env if worker_env is not None else [{} for _ in range(n)]
        if len(envs) != n:
            raise WorkloadBackendError(
                f"worker_env has {len(envs)} entries for {n} workers")
        cmds = {w.worker_id: self._run_script(spec, envs[i])
                for i, w in enumerate(workers)}
        try:
            self.executor.run_per_worker(qr, cmds, timeout_s=120.0, host=True)
        except WorkerExecError as e:
            # all-or-nothing: tear down any worker that did start, so the
            # retry next reconcile pass begins from a clean slate
            self._teardown(qr, worker_ids=worker_ids)
            raise WorkloadBackendError(f"gang launch on {name} failed: {e}") from e
        with self._lock:
            self._ports[name] = {int(p.split("/")[0]): int(p.split("/")[0])
                                 for p in spec.ports}
        log.info("ssh backend: launched %s on %d/%d workers of %s",
                 spec.image, n, len(qr.workers), name)

    def _teardown(self, qr: QueuedResource, worker_ids=None):
        workers = (qr.workers if worker_ids is None
                   else [w for w in qr.workers if w.worker_id in set(worker_ids)])
        for w in workers:
            try:
                self.executor.run_on_worker(
                    qr, w.worker_id,
                    ["sh", "-c", f"docker rm -f {shlex.quote(self.container_name)} "
                                 ">/dev/null 2>&1 || true"],
                    timeout_s=30.0, host=True)
            except WorkerExecError:
                pass  # unreachable worker: nothing to tear down

    # -- status ----------------------------------------------------------------

    _INSPECT_FMT = ('{{.State.Status}} {{.State.ExitCode}} {{.State.StartedAt}}'
                    ' {{index .Config.Labels "tpu-ports"}}')

    def _inspect_one(self, qr: QueuedResource, w) -> WorkerRuntimeInfo:
        info = WorkerRuntimeInfo(worker_id=w.worker_id, hostname=w.hostname,
                                 internal_ip=w.internal_ip)
        try:
            out = self.executor.run_on_worker(
                qr, w.worker_id,
                ["docker", "inspect", "--format", self._INSPECT_FMT,
                 self.container_name], timeout_s=30.0, host=True).strip()
        except WorkerExecError as e:
            if e.exit_code == 255:  # ssh itself failed: VM unreachable
                info.healthy = False
                info.exit_message = f"worker unreachable: {e}"
                return info
            # reachable VM, container missing (not launched yet / removed)
            info.healthy = True
            info.workload_running = False
            return info
        fields = out.split()
        state = fields[0] if fields else ""
        info.workload_running = state == "running"
        if state == "exited" and len(fields) > 1:
            try:
                info.exit_code = int(fields[1])
            except ValueError:
                info.exit_code = 1
        elif state in ("dead", "oomkilled"):
            info.exit_code = 137
            info.exit_message = f"container {state}"
        if len(fields) > 3 and fields[3] != "-":
            # recover the port list from the container label (survives a
            # kubelet restart, when the in-memory cache starts empty)
            with self._lock:
                self._ports.setdefault(qr.name, {
                    int(p.split("/")[0]): int(p.split("/")[0])
                    for p in fields[3].split(",") if p})
        return info

    def detailed_status(self, client, name, zone):
        from .tpu_client import NotFoundError
        try:
            qr = client.get_queued_resource(name, zone=zone)
        except NotFoundError:
            return _not_found(name)
        if qr.state is not QueuedResourceState.ACTIVE or not qr.workers:
            return DetailedStatus(resource=qr)
        runtime: list[WorkerRuntimeInfo] = []
        errors: list[Exception] = []
        results: dict[int, WorkerRuntimeInfo] = {}

        def one(w):
            try:
                results[w.worker_id] = self._inspect_one(qr, w)
            except Exception as e:  # noqa: BLE001 — one worker must not kill the sweep
                errors.append(e)
                results[w.worker_id] = WorkerRuntimeInfo(
                    worker_id=w.worker_id, hostname=w.hostname,
                    healthy=False, exit_message=str(e))

        threads = [threading.Thread(target=one, args=(w,), daemon=True)
                   for w in qr.workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=40.0)
        runtime = [results[w.worker_id] for w in qr.workers
                   if w.worker_id in results]
        with self._lock:
            ports = dict(self._ports.get(name, {}))
        # pre-launch: EVERY worker is reachable and none has a container.
        # Report no runtime so the reconcile loop's launch-adoption check
        # stays false and the gang launch proceeds. An unreachable worker is
        # NOT pre-launch evidence — if all VMs vanish post-launch the gang is
        # broken, and masking that would leave the pod non-terminal forever.
        launched = any(r.workload_running or r.exit_code is not None
                       or not r.healthy for r in runtime)
        if not launched:
            return DetailedStatus(resource=qr)
        return DetailedStatus(resource=qr, runtime=runtime, ports=ports)
