"""GCP bearer-token providers for the cloud transport.

The reference's static API key lives forever (runpod_client.go:144 sets one
Authorization header at client construction). GCP OAuth2 access tokens expire
in ~1h, so a static TPU_API_TOKEN kubelet goes permanently unhealthy after the
first expiry (VERDICT r2 item 5). The transport instead takes a *provider*
callable: it returns a currently-valid token, caches it until shortly before
expiry, and can be invalidated when the API answers 401 (token revoked early,
clock skew) so the transport's single auth-retry fetches a fresh one.

stdlib-only, like the rest of the control plane. Three providers:

- ``StaticTokenProvider`` — wraps a fixed token (tests, api-key-style gateways,
  and the fake server).
- ``MetadataTokenProvider`` — the GCE/GKE metadata server
  (``computeMetadata/v1/.../token``); the standard in-cluster path, no
  credentials on disk.
- ``AdcUserTokenProvider`` — an ``authorized_user`` Application Default
  Credentials file (``gcloud auth application-default login``): exchanges the
  refresh token at oauth2.googleapis.com. Service-account *key files* need
  RS256 JWT signing, which stdlib cannot do — those deployments should use
  workload identity / the metadata server instead (clear error, not a silent
  wrong path).

``default_token_provider(cfg_token)`` picks, in order: explicit static token →
ADC file (GOOGLE_APPLICATION_CREDENTIALS or the gcloud well-known path) →
metadata server.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

log = logging.getLogger(__name__)

# refresh this long before expiry so an in-flight request never sends a
# token that dies mid-request
EXPIRY_SLACK_S = 300.0

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")
_OAUTH_TOKEN_URL = "https://oauth2.googleapis.com/token"
_ADC_WELL_KNOWN = os.path.join("~", ".config", "gcloud",
                               "application_default_credentials.json")


class AuthError(Exception):
    """Could not obtain a bearer token."""


class StaticTokenProvider:
    """A fixed token: the reference's API-key behavior, provider-shaped.
    Deliberately has NO ``invalidate()`` — the transport's 401-refresh
    gate checks for that attribute, so a deterministic 401 with a fixed
    token fails fast instead of re-issuing the identical request."""

    def __init__(self, token: str):
        self._token = token

    def __call__(self) -> str:
        return self._token


class _CachingProvider:
    """Shared cache + expiry logic; subclasses implement _fetch() ->
    (token, lifetime_s)."""

    def __init__(self, now=time.time):
        self._now = now
        self._lock = threading.Lock()
        self._token: Optional[str] = None
        self._expires_at = 0.0

    def __call__(self) -> str:
        with self._lock:
            if self._token is None or \
                    self._now() >= self._expires_at - EXPIRY_SLACK_S:
                token, lifetime = self._fetch()
                self._token = token
                self._expires_at = self._now() + lifetime
            return self._token

    def invalidate(self) -> None:
        """Drop the cached token (the API said 401) so the next call
        fetches a fresh one."""
        with self._lock:
            self._token = None

    def _fetch(self) -> tuple[str, float]:  # pragma: no cover — abstract
        raise NotImplementedError


# public name: kube/client.py's exec-credential plugin builds on the same
# cache/skew/invalidate contract (one token-cache implementation project-wide)
CachingTokenProvider = _CachingProvider


class MetadataTokenProvider(_CachingProvider):
    """GCE/GKE metadata-server tokens (workload identity / attached SA)."""

    def __init__(self, url: str = _METADATA_TOKEN_URL, timeout_s: float = 10.0,
                 now=time.time):
        super().__init__(now)
        self.url = url
        self.timeout_s = timeout_s

    def _fetch(self) -> tuple[str, float]:
        req = urllib.request.Request(self.url,
                                     headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                payload = json.loads(resp.read())
        except (urllib.error.URLError, TimeoutError, OSError,
                json.JSONDecodeError) as e:
            raise AuthError(f"metadata server token fetch failed: {e}") from e
        try:
            return payload["access_token"], float(payload.get("expires_in", 0))
        except (KeyError, TypeError) as e:
            raise AuthError(f"metadata server returned no access_token: "
                            f"{payload!r}") from e


class AdcUserTokenProvider(_CachingProvider):
    """authorized_user ADC: refresh-token exchange at the OAuth2 endpoint."""

    def __init__(self, adc: dict, token_url: str = _OAUTH_TOKEN_URL,
                 timeout_s: float = 10.0, now=time.time):
        super().__init__(now)
        missing = {"client_id", "client_secret", "refresh_token"} - set(adc)
        if missing:
            raise AuthError(f"ADC file missing fields: {sorted(missing)}")
        self._adc = adc
        self.token_url = token_url
        self.timeout_s = timeout_s

    def _fetch(self) -> tuple[str, float]:
        form = urllib.parse.urlencode({
            "grant_type": "refresh_token",
            "client_id": self._adc["client_id"],
            "client_secret": self._adc["client_secret"],
            "refresh_token": self._adc["refresh_token"],
        }).encode()
        req = urllib.request.Request(
            self.token_url, data=form, method="POST",
            headers={"Content-Type": "application/x-www-form-urlencoded"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise AuthError(f"OAuth2 refresh failed: HTTP {e.code} "
                            f"{e.read().decode(errors='replace')[:200]}") from e
        except (urllib.error.URLError, TimeoutError, OSError,
                json.JSONDecodeError) as e:
            raise AuthError(f"OAuth2 refresh failed: {e}") from e
        try:
            return payload["access_token"], float(payload.get("expires_in", 0))
        except (KeyError, TypeError) as e:
            raise AuthError(f"OAuth2 endpoint returned no access_token: "
                            f"{list(payload)}") from e


def is_google_api_endpoint(url: str) -> bool:
    """True iff the URL's HOST is googleapis.com (or a subdomain) — the gate
    for attaching ambient GCP credentials. A substring check would match
    attacker-controlled hosts like evilgoogleapis.com or path segments."""
    host = urllib.parse.urlsplit(url).hostname or ""
    return host == "googleapis.com" or host.endswith(".googleapis.com")


def _adc_path() -> Optional[str]:
    explicit = os.environ.get("GOOGLE_APPLICATION_CREDENTIALS")
    if explicit:
        return explicit
    well_known = os.path.expanduser(_ADC_WELL_KNOWN)
    return well_known if os.path.exists(well_known) else None


def default_token_provider(static_token: str = ""):
    """Provider resolution: explicit token → ADC file → metadata server.

    Mirrors google-auth's ADC order without the dependency. A service-account
    key file is rejected with guidance (stdlib can't RS256-sign); the
    metadata-server fallback is returned UNPROBED — first use fails loudly if
    the kubelet isn't on GCP, which beats hanging a constructor on a probe."""
    if static_token:
        return StaticTokenProvider(static_token)
    path = _adc_path()
    if path:
        try:
            with open(path) as f:
                adc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise AuthError(f"cannot read ADC file {path}: {e}") from e
        kind = adc.get("type", "")
        if kind == "authorized_user":
            log.info("auth: ADC authorized_user from %s", path)
            return AdcUserTokenProvider(adc)
        if kind == "service_account":
            raise AuthError(
                "service-account key files need RS256 JWT signing (not in "
                "the stdlib); run the kubelet with workload identity / an "
                "attached service account (metadata server) or set "
                "TPU_API_TOKEN from an external token source")
        raise AuthError(f"unsupported ADC credential type {kind!r} in {path}")
    log.info("auth: no static token or ADC file — using the metadata server")
    return MetadataTokenProvider()
