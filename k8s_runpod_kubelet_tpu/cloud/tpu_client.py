"""Cloud TPU client: queued-resource CRUD + catalog + workload launch.

Method-for-capability mirror of the reference's RunPod client
(/root/reference/pkg/virtual_kubelet/runpod_client.go):

  create_queued_resource  ~ DeployPodREST        runpod_client.go:522 (POST /pods,
                                                 60s deploy timeout :753-756)
  get_queued_resource     ~ GetPodStatusREST     runpod_client.go:386
  get_detailed_status     ~ GetDetailedPodStatus runpod_client.go:773-818
                                                 (404 -> synthetic NOT_FOUND :788-793)
  delete_queued_resource  ~ TerminatePod         runpod_client.go:712-739
  list_queued_resources   ~ fetchRunPodInstancesByStatus kubelet.go:1637-1675
  list_accelerator_types  ~ GetGPUTypes          runpod_client.go:431-520
  start_workload          — net-new: a slice is bare VMs; the workload (container,
                            per-worker env) is launched onto every worker as a gang.

The wire protocol is a REST shape modeled on the Cloud TPU v2 API
(projects/{p}/locations/{z}/queuedResources). The workload half (launch +
per-worker runtime status) is pluggable via ``workload_backend``
(cloud/workload_backend.py): ApiWorkloadBackend speaks the :detailed and
:workload extension endpoints (fake server / a worker-agent aggregator
service); SshWorkloadBackend needs only the plain v2 CRUD surface and drives
docker on the TPU VMs over SSH — the real-cloud path (VERDICT r1 item 2).
"""

from __future__ import annotations

import dataclasses
import logging
import re
from typing import Optional

from .transport import HttpTransport, TransportError, DEPLOY_TIMEOUT_S
from .types import (
    AcceleratorType,
    DetailedStatus,
    QueuedResource,
    QueuedResourceState,
    TpuWorker,
)

log = logging.getLogger(__name__)

# Queued-resource ids must be RFC-1035-ish, like GCE resource names.
_NAME_RE = re.compile(r"^[a-z]([-a-z0-9]{0,61}[a-z0-9])?$")


class TpuApiError(Exception):
    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class NotFoundError(TpuApiError):
    pass


class QuotaError(TpuApiError):
    """Out of capacity / quota — deploy should requeue, not fail the pod."""


@dataclasses.dataclass
class WorkloadSpec:
    """What runs on every worker of the slice (gang semantics: same program, all hosts).

    The analog of the reference's deployment params dict (runpod_client.go:1334-1372:
    imageName/env/ports/containerDiskInGb...), minus GPU-isms, plus the per-worker
    env template the TPU runtime needs (TPU_WORKER_ID etc. are appended per worker
    by the server/agent, see gang/env.py).
    """

    image: str
    command: list[str] = dataclasses.field(default_factory=list)
    args: list[str] = dataclasses.field(default_factory=list)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    ports: list[str] = dataclasses.field(default_factory=list)  # "port/proto"
    boot_disk_gb: int = 100
    registry_auth_id: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "WorkloadSpec":
        return cls(**{k: d[k] for k in d if k in {f.name for f in dataclasses.fields(cls)}})


@dataclasses.dataclass
class TpuParameters:
    """Full deploy request: slice shape + workload. Built by provider/translate.py."""

    name: str
    accelerator_type: str
    runtime_version: str
    zone: str
    workload: WorkloadSpec
    spot: bool = False
    reservation: str = ""
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    valid_after_s: float = 0.0

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["workload"] = self.workload.to_json()
        return d


def _resource_from_json(d: dict) -> QueuedResource:
    workers = [TpuWorker(**w) for w in d.get("workers", [])]
    return QueuedResource(
        name=d["name"],
        accelerator_type=d["acceleratorType"],
        runtime_version=d.get("runtimeVersion", ""),
        state=QueuedResourceState(d["state"]),
        zone=d.get("zone", ""),
        state_message=d.get("stateMessage", ""),
        spot=d.get("spot", False),
        reservation=d.get("reservation", ""),
        workers=workers,
        labels=d.get("labels", {}),
        create_time=d.get("createTime", 0.0),
    )


class TpuClient:
    """Typed client over the queued-resources REST surface."""

    def __init__(self, transport: HttpTransport, project: str = "tpu-project",
                 zone: str = "us-central2-b", workload_backend=None,
                 quota_transport: Optional[HttpTransport] = None):
        from .workload_backend import ApiWorkloadBackend
        self.transport = transport
        # Quota lives on a DIFFERENT host than the TPU API in production
        # (serviceusage.googleapis.com); default to the main transport only
        # for single-listener setups (the hermetic fake serves both paths).
        self.quota_transport = quota_transport or transport
        self.project = project
        self.zone = zone
        self.workload_backend = workload_backend or ApiWorkloadBackend()

    def _base(self, zone: Optional[str] = None) -> str:
        return f"/v2/projects/{self.project}/locations/{zone or self.zone}"

    @property
    def breaker(self):
        """The main transport's circuit breaker (None when not configured).
        The provider watches its state to flip the node's TpuApiReachable
        condition/taint; the quota transport deliberately has no breaker
        (it already fails fast, and a serviceusage outage must not taint
        the node while the TPU API itself is healthy)."""
        return getattr(self.transport, "breaker", None)

    @staticmethod
    def _wrap(e: TransportError, what: str) -> TpuApiError:
        if e.status == 404:
            return NotFoundError(f"{what}: not found", status=404)
        if e.status in (403, 429) and ("quota" in e.body.lower() or "capacity" in e.body.lower()
                                       or e.status == 429):
            return QuotaError(f"{what}: {e.body or e}", status=e.status)
        return TpuApiError(f"{what}: {e}", status=e.status)

    # -- CRUD ------------------------------------------------------------------

    def create_queued_resource(self, params: TpuParameters) -> QueuedResource:
        if not _NAME_RE.match(params.name):
            raise TpuApiError(f"invalid queued-resource name {params.name!r}")
        try:
            d = self.transport.request(
                "POST", f"{self._base(params.zone)}/queuedResources"
                        f"?queued_resource_id={params.name}",
                body=params.to_json(), timeout_s=DEPLOY_TIMEOUT_S)
        except TransportError as e:
            raise self._wrap(e, f"create {params.name}") from e
        return _resource_from_json(d)

    def get_queued_resource(self, name: str, zone: Optional[str] = None) -> QueuedResource:
        try:
            d = self.transport.request("GET", f"{self._base(zone)}/queuedResources/{name}")
        except TransportError as e:
            raise self._wrap(e, f"get {name}") from e
        return _resource_from_json(d)

    def get_detailed_status(self, name: str, zone: Optional[str] = None) -> DetailedStatus:
        """Slice state + per-worker runtime info via the workload backend;
        404 becomes a synthetic NOT_FOUND status rather than an exception
        (parity: runpod_client.go:788-793), so the reconcile loop can treat
        disappearance as a state, not an error."""
        return self.workload_backend.detailed_status(self, name, zone)

    def delete_queued_resource(self, name: str, zone: Optional[str] = None,
                               force: bool = True) -> None:
        """Idempotent delete; 404 is success (parity: TerminatePod treats the
        instance as gone, runpod_client.go:712-739 + kubelet 404 handling)."""
        try:
            self.transport.request(
                "DELETE", f"{self._base(zone)}/queuedResources/{name}?force={str(force).lower()}",
                expect_status=(200, 204))
        except TransportError as e:
            if e.status == 404:
                return
            raise self._wrap(e, f"delete {name}") from e

    def list_queued_resources(self, states: Optional[list[QueuedResourceState]] = None,
                              zone: Optional[str] = None) -> list[QueuedResource]:
        q = ""
        if states:
            q = "?states=" + ",".join(s.value for s in states)
        try:
            d = self.transport.request("GET", f"{self._base(zone)}/queuedResources{q}")
        except TransportError as e:
            raise self._wrap(e, "list queued resources") from e
        return [_resource_from_json(r) for r in d.get("queuedResources", [])]

    # -- catalog / health ------------------------------------------------------

    def list_accelerator_types(self, zone: Optional[str] = None) -> list[AcceleratorType]:
        try:
            d = self.transport.request("GET", f"{self._base(zone)}/acceleratorTypes")
        except TransportError as e:
            raise self._wrap(e, "list accelerator types") from e
        return [AcceleratorType(**a) for a in d.get("acceleratorTypes", [])]

    def health_check(self) -> bool:
        """Cloud availability probe (parity: checkRunPodAPIHealth does GET gpuTypes,
        kubelet.go:320-331)."""
        try:
            self.list_accelerator_types()
            return True
        except TpuApiError:
            return False

    def get_chip_quota(self, generation: str = "") -> Optional[int]:
        """The project's effective TPU chip quota, or None when the quota
        surface is unavailable.

        ``generation`` (e.g. "v5e") selects that generation's ``*_chips``
        metric — the honest capacity for a node that binds slices of ONE
        generation (ADVICE r4: summing v4+v5e grants into one
        ``google.com/tpu`` number can bind v5e pods beyond the v5e grant;
        they then fail at provision time instead of going Unschedulable).
        When the named metric is absent — or no generation is given — the
        per-generation metrics are SUMMED, accepting that tradeoff for
        projects whose metric names differ from <gen>_chips.

        The Cloud TPU v2 API itself exposes no quota read; real deployments
        read Service Usage ``consumerQuotaMetrics`` for tpu.googleapis.com and
        sum the per-generation ``*_chips`` limits. Per metric, a bucket whose
        ``region`` dimension matches ours beats the dimensionless default
        bucket; other regions' buckets and ``-1`` (unlimited) buckets are
        ignored. "Quota surface unavailable" degrades to None so the caller
        keeps its configured ceiling: 404 (endpoint absent) and 403 (what the
        real API returns for SERVICE_DISABLED / a service account without
        serviceusage.quotas.get). This is the fix for the reference's
        hard-coded node capacity (kubelet.go:1129) AND for our own r3
        operator-set-constant version (VERDICT r3 weak-6).

        The read rides the readiness probe's ping path, so it fails FAST
        (one attempt, short timeout) rather than inheriting the transport's
        full retry budget — a serviceusage outage must not flap readyz while
        the TPU API itself is healthy."""
        region = self.zone.rsplit("-", 1)[0]
        path = (f"/v1/projects/{self.project}/services/tpu.googleapis.com"
                f"/consumerQuotaMetrics")
        # the listing is paginated; chip metrics can land past page 1 (bounded
        # pages so a misbehaving server can't spin the readiness path)
        metrics, page_token = [], ""
        for _ in range(8):
            q = f"?pageToken={page_token}" if page_token else ""
            try:
                d = self.quota_transport.request("GET", path + q,
                                                 timeout_s=5.0, max_retries=1)
            except TransportError as e:
                if e.status in (403, 404):
                    return None
                raise self._wrap(e, "get chip quota") from e
            metrics.extend(d.get("metrics", []))
            page_token = d.get("nextPageToken", "")
            if not page_token:
                break
        chip_metrics = [m for m in metrics
                        if m.get("metric", "").endswith("_chips")]
        if generation:
            # the service listing also carries API request-rate quotas; a
            # generation-named chip metric is the node's own capacity
            named = [m for m in chip_metrics
                     if m.get("metric", "").endswith(f"/{generation}_chips")]
            if named:
                chip_metrics = named
        total, found = 0, False
        for metric in chip_metrics:
            # Each consumerQuotaLimits entry is an independently applicable
            # limit: the effective cap is the MIN across limits. Specificity
            # (region bucket beats the dimensionless default) applies only
            # WITHIN one limit's buckets.
            per_limit: list[int] = []
            for lim in metric.get("consumerQuotaLimits", []):
                best: Optional[tuple[int, int]] = None  # (specificity, limit)
                for bucket in lim.get("quotaBuckets", []):
                    try:
                        eff = int(bucket.get("effectiveLimit", -1))
                    except (TypeError, ValueError):
                        continue
                    if eff < 0:  # -1 = unlimited; never bounds capacity
                        continue
                    dims = bucket.get("dimensions") or {}
                    if not dims:
                        score = 0
                    elif dims.get("region") == region:
                        score = 1
                    else:
                        continue  # some other region's bucket
                    if (best is None or score > best[0]
                            or (score == best[0] and eff < best[1])):
                        best = (score, eff)
                if best is not None:
                    per_limit.append(best[1])
            if per_limit:
                total += min(per_limit)
                found = True
        return total if found else None

    # -- workload --------------------------------------------------------------

    def start_workload(self, name: str, spec: WorkloadSpec,
                       worker_env: Optional[list[dict[str, str]]] = None,
                       zone: Optional[str] = None,
                       worker_ids: Optional[list[int]] = None) -> None:
        """Launch the workload on every worker of an ACTIVE slice (gang launch)
        via the workload backend. ``worker_env`` is the per-worker env overlay
        (TPU_WORKER_ID, coordinator...) computed by gang/env.py.
        ``worker_ids`` restricts the launch to a surviving subset (elastic
        resize, ISSUE 6); None = the whole gang."""
        from .workload_backend import WorkloadBackendError
        try:
            self.workload_backend.start(self, name, spec, worker_env, zone,
                                        worker_ids=worker_ids)
        except WorkloadBackendError as e:
            raise TpuApiError(str(e)) from e
