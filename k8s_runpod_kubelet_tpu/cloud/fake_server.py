"""In-process fake Cloud TPU API server for hermetic tests.

The reference has NO API fake (SURVEY.md §4: "no mock/fake RunPod API server (no
httptest anywhere)") and its integration tests hit the live paid cloud. This module
inverts that: an httptest-style threading HTTP server that implements the exact
REST surface TpuClient speaks, with

- a lazy-clock state machine (ACCEPTED -> PROVISIONING -> ACTIVE on read, after
  configurable delays, or instantly via advance()/set_state()),
- workload simulation (gang launch marks every worker running; finish_workload()
  or auto_finish_s drives per-worker exits), and
- fault injection (SURVEY.md §5.3 gap): quota exhaustion, API blackout, worker
  preemption, slice vanish (NOT_FOUND paths) — plus a pluggable seeded
  ``FaultPlan`` (cloud/faults.py) that composes error bursts, latency spikes,
  blackouts and preemption storms deterministically for chaos soaks.

The service clock is injectable (``clock=``): chaos tests share one FakeClock
across provider, transport and this server, so the whole state machine runs
on simulated time with zero real sleeps.

Tests drive failure paths the reference never covered.
"""

from __future__ import annotations

import json
import threading
import time
import re
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .types import ACCELERATOR_CATALOG, QueuedResourceState, lookup_accelerator

_QR_PATH = re.compile(
    r"^/v2/projects/(?P<project>[^/]+)/locations/(?P<zone>[^/]+)/queuedResources"
    r"(?:/(?P<name>[^/:]+))?(?::(?P<verb>detailed|workload))?$")
_CATALOG_PATH = re.compile(
    r"^/v2/projects/(?P<project>[^/]+)/locations/(?P<zone>[^/]+)/acceleratorTypes$")
# Service-Usage-shaped quota listing (the real Cloud TPU v2 surface has no
# quota read; deployments enable serviceusage.googleapis.com and read
# consumerQuotaMetrics). Served here so the provider's quota-honest node
# capacity (VERDICT r3 weak-6) is testable hermetically.
_QUOTA_PATH = re.compile(
    r"^/v1/projects/(?P<project>[^/]+)/services/tpu\.googleapis\.com"
    r"/consumerQuotaMetrics$")


class _FakeResource:
    """Server-side record: slice lifecycle + per-worker workload simulation."""

    def __init__(self, name: str, body: dict, now: float, provision_delay_s: float):
        self.name = name
        self.accelerator_type = body["accelerator_type"]
        self.runtime_version = body.get("runtime_version", "")
        self.zone = body.get("zone", "")
        self.spot = body.get("spot", False)
        self.reservation = body.get("reservation", "")
        self.labels = body.get("labels", {})
        self.workload = body.get("workload", {})
        self.create_time = now
        self.state = QueuedResourceState.ACCEPTED
        self.state_message = "queued"
        self.state_since = now
        self.provision_delay_s = provision_delay_s
        self.deleting_since: Optional[float] = None
        self.workers: list[dict] = []
        self.runtime: list[dict] = []
        self.ports: dict[int, int] = {}
        self.workload_started_at: Optional[float] = None
        self.auto_finish_s: Optional[float] = None
        self.worker_env: list[dict] = []

    def _make_workers(self):
        acc = lookup_accelerator(self.accelerator_type)
        hosts = acc.hosts if acc else 1
        self.workers = [
            {"worker_id": i,
             "hostname": f"{self.name}-w{i}",
             "internal_ip": f"10.0.{hash(self.name) % 200}.{i + 2}",
             "external_ip": "",
             "state": "READY"}
            for i in range(hosts)
        ]

    def advance(self, now: float):
        """Lazy clock: move the state machine forward based on elapsed time."""
        if self.state is QueuedResourceState.ACCEPTED:
            if now - self.state_since >= self.provision_delay_s * 0.3:
                self._set(QueuedResourceState.PROVISIONING, "creating TPU VMs", now)
        if self.state is QueuedResourceState.PROVISIONING:
            if now - self.state_since >= self.provision_delay_s * 0.7:
                self._make_workers()
                self._set(QueuedResourceState.ACTIVE, "slice ready", now)
        if (self.state is QueuedResourceState.ACTIVE and self.workload_started_at
                and self.auto_finish_s is not None
                and now - self.workload_started_at >= self.auto_finish_s):
            self.finish_workload(now=now)

    def _set(self, state: QueuedResourceState, msg: str, now: float):
        self.state = state
        self.state_message = msg
        self.state_since = now

    def start_workload(self, spec: dict, worker_env: list[dict], now: float,
                       auto_finish_s: Optional[float],
                       worker_ids: Optional[list[int]] = None):
        """``worker_ids`` restricts the (re)launch to a subset — the elastic
        resize path. Subset launches REPLACE those workers' runtime entries
        and keep the others' (a dead worker's unhealthy record must survive
        the surviving gang's relaunch, exactly as real per-VM state would)."""
        self.workload = spec or self.workload
        self.worker_env = worker_env
        self.workload_started_at = now
        self.auto_finish_s = auto_finish_s

        def entry(w):
            return {"worker_id": w["worker_id"], "hostname": w["hostname"],
                    "internal_ip": w["internal_ip"],
                    "healthy": w.get("state") != "PREEMPTED",
                    "workload_running": w.get("state") != "PREEMPTED",
                    "exit_code": None, "exit_message": "",
                    "started_at": now, "finished_at": None}

        if worker_ids is None:
            self.runtime = [entry(w) for w in self.workers]
        else:
            wanted = set(worker_ids)
            prior = {r["worker_id"]: r for r in self.runtime}
            self.runtime = [entry(w) if w["worker_id"] in wanted
                            else prior.get(w["worker_id"], {
                                "worker_id": w["worker_id"],
                                "hostname": w["hostname"],
                                "internal_ip": w["internal_ip"],
                                "healthy": w.get("state") != "PREEMPTED",
                                "workload_running": False, "exit_code": None,
                                "exit_message": "", "started_at": None,
                                "finished_at": None})
                            for w in self.workers]
        for p in self.workload.get("ports", []):
            port = int(str(p).split("/")[0])
            self.ports[port] = 30000 + port % 2000

    def finish_workload(self, exit_codes: Optional[list[int]] = None,
                        message: str = "", now: Optional[float] = None):
        now = time.time() if now is None else now
        for i, r in enumerate(self.runtime):
            code = exit_codes[i] if exit_codes and i < len(exit_codes) else 0
            r["workload_running"] = False
            r["exit_code"] = code
            r["finished_at"] = now
            r["exit_message"] = message or ("completed successfully" if code == 0
                                            else f"exited with code {code}")

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "acceleratorType": self.accelerator_type,
            "runtimeVersion": self.runtime_version,
            "state": self.state.value,
            "zone": self.zone,
            "stateMessage": self.state_message,
            "spot": self.spot,
            "reservation": self.reservation,
            "workers": self.workers,
            "labels": self.labels,
            "createTime": self.create_time,
        }


class FakeTpuService:
    """Shared mutable state + fault-injection switches (thread-safe)."""

    def __init__(self, provision_delay_s: float = 0.0,
                 workload_auto_finish_s: Optional[float] = None,
                 clock=time.time):
        self.lock = threading.RLock()
        self.clock = clock
        self.resources: dict[str, _FakeResource] = {}
        self.provision_delay_s = provision_delay_s
        self.workload_auto_finish_s = workload_auto_finish_s
        # extensions_enabled=False emulates the PLAIN Cloud TPU v2 surface
        # (create/get/list/delete only): :detailed and :workload 404, as they
        # would against the real googleapis endpoint — the SSH workload
        # backend must carry the whole workload half (tests/test_ssh_workload)
        self.extensions_enabled = True
        # Chip quota served via the Service-Usage-shaped endpoint. None (the
        # default) 404s the route — the project hasn't enabled the quota API —
        # so the kubelet falls back to its configured ceiling. Tests set an
        # int for the simple shape, or chip_quota_metrics for a full
        # consumerQuotaMetrics payload (regional buckets, -1 unlimited...).
        self.chip_quota: Optional[int] = None
        self.chip_quota_metrics: Optional[list[dict]] = None
        self.quota_error: Optional[int] = None  # force this HTTP status
        # fault injection
        self.api_down = False            # every request -> 503
        self.fail_next_create: Optional[tuple[int, str]] = None  # (status, message)
        # seeded composite chaos: when set, every request consults the plan
        # (latency spikes advance the injected clock, storms preempt ACTIVE
        # slices, host_loss kills ONE worker of a multi-host slice and
        # restores it when the window closes, blackouts/bursts reject) —
        # see cloud/faults.py
        self.fault_plan = None
        # elastic soaks over the SSH path bridge the fake cloud's worker
        # state to the docker-lite FakeWorkerHost: called as
        # hook(slice_name, worker_id, lost) after the server applies a
        # host_loss transition to its own records
        self.host_loss_hook = None
        self.create_count = 0
        self.delete_count = 0
        self.request_log: list[tuple[str, str]] = []

    # -- test hooks ------------------------------------------------------------

    def get(self, name: str) -> _FakeResource:
        with self.lock:
            return self.resources[name]

    def advance_all(self):
        """Force every resource fully forward (ACCEPTED/PROVISIONING -> ACTIVE)."""
        with self.lock:
            for r in self.resources.values():
                if r.state is QueuedResourceState.ACCEPTED:
                    r._set(QueuedResourceState.PROVISIONING, "creating TPU VMs", self.clock())
                if r.state is QueuedResourceState.PROVISIONING:
                    r._make_workers()
                    r._set(QueuedResourceState.ACTIVE, "slice ready", self.clock())

    def preempt(self, name: str, worker_id: Optional[int] = None):
        """Simulate a maintenance event: whole slice (or one worker) goes away."""
        with self.lock:
            r = self.resources[name]
            if worker_id is None:
                r._set(QueuedResourceState.SUSPENDED, "preempted by maintenance event",
                       self.clock())
                for w in r.workers:
                    w["state"] = "PREEMPTED"
                for rt in r.runtime:
                    rt["healthy"] = False
                    rt["workload_running"] = False
            else:
                r.workers[worker_id]["state"] = "PREEMPTED"
                if worker_id < len(r.runtime):
                    r.runtime[worker_id]["healthy"] = False
                    r.runtime[worker_id]["workload_running"] = False

    def restore_worker(self, name: str, worker_id: int):
        """Capacity returned: the lost worker's replacement VM is READY
        again (its container is NOT running — the kubelet's grow path
        relaunches the gang). The host_loss fault window calls this when
        it closes; tests call it directly."""
        with self.lock:
            r = self.resources.get(name)
            if r is None:
                return
            if worker_id < len(r.workers):
                r.workers[worker_id]["state"] = "READY"
            for rt in r.runtime:
                if rt["worker_id"] == worker_id:
                    rt["healthy"] = True
                    rt["workload_running"] = False

    def vanish(self, name: str):
        """Simulate the slice disappearing entirely (NOT_FOUND path)."""
        with self.lock:
            self.resources.pop(name, None)

    def stuck(self, name: str, state: QueuedResourceState, message: str = "stuck"):
        """Pin a resource to a state (e.g. DELETING forever) for escalation tests."""
        with self.lock:
            r = self.resources[name]
            r._set(state, message, self.clock())
            r.provision_delay_s = float("inf")

    # -- request handling (called from the HTTP handler) -----------------------

    def handle(self, method: str, path: str, query: dict, body: Optional[dict]):
        """Returns (status, json_body_or_None) or (status, body, headers)."""
        with self.lock:
            self.request_log.append((method, path))
            if self.api_down:
                return 503, {"error": "service unavailable"}
            if self.fault_plan is not None:
                # latency first (simulated time passes BEFORE the request is
                # served), then storms/host-losses mutate state, then reject
                # decisions
                self.fault_plan.apply_latency()
                for victim in self.fault_plan.preempt_victims(
                        [r.name for r in self.resources.values()
                         if r.state is QueuedResourceState.ACTIVE]):
                    self.preempt(victim)
                for name, wid, lost in self.fault_plan.host_loss_transitions(
                        [(r.name, len(r.workers))
                         for r in self.resources.values()
                         if r.state is QueuedResourceState.ACTIVE]):
                    if name in self.resources:
                        if lost:
                            self.preempt(name, worker_id=wid)
                        else:
                            self.restore_worker(name, wid)
                        if self.host_loss_hook is not None:
                            self.host_loss_hook(name, wid, lost)
                fault = self.fault_plan.request_fault()
                if fault is not None:
                    return fault
            now = self.clock()
            for r in self.resources.values():
                r.advance(now)

            m = _CATALOG_PATH.match(path)
            if m and method == "GET":
                cat = [
                    {"name": a.name, "generation": a.generation, "chips": a.chips,
                     "hosts": a.hosts, "chips_per_host": a.chips_per_host,
                     "topology": a.topology, "hbm_gib_per_chip": a.hbm_gib_per_chip,
                     "default_runtime": a.default_runtime,
                     "cost_per_chip_hr": a.cost_per_chip_hr}
                    for a in ACCELERATOR_CATALOG.values()
                ]
                return 200, {"acceleratorTypes": cat}

            if _QUOTA_PATH.match(path) and method == "GET":
                if self.quota_error is not None:
                    return self.quota_error, {"error": "quota backend failing"}
                metrics = self.chip_quota_metrics
                if metrics is None and self.chip_quota is not None:
                    metrics = [{
                        "metric": "tpu.googleapis.com/v5e_chips",
                        "consumerQuotaLimits": [{"quotaBuckets": [
                            {"effectiveLimit": str(self.chip_quota),
                             "dimensions": {}}]}],
                    }]
                if metrics is None:
                    return 404, {"error": "quota API not enabled"}
                return 200, {"metrics": metrics}

            m = _QR_PATH.match(path)
            if not m:
                return 404, {"error": f"no route {path}"}
            name, verb = m.group("name"), m.group("verb")

            if method == "POST" and name is None and verb is None:
                return self._create(query, body, now)
            if name is None and method == "GET":
                return self._list(query)
            if name not in self.resources:
                return 404, {"error": f"queued resource {name} not found"}
            r = self.resources[name]
            if verb in ("detailed", "workload") and not self.extensions_enabled:
                return 404, {"error": f"no route {path} (plain v2 surface)"}
            if method == "GET" and verb == "detailed":
                return 200, {"resource": r.to_json(), "runtime": r.runtime,
                             "ports": {str(k): v for k, v in r.ports.items()}}
            if method == "GET":
                return 200, r.to_json()
            if method == "POST" and verb == "workload":
                if r.state is not QueuedResourceState.ACTIVE:
                    return 409, {"error": f"slice {name} is {r.state.value}, not ACTIVE"}
                r.start_workload(body.get("workload", {}), body.get("workerEnv", []),
                                 now, self.workload_auto_finish_s,
                                 worker_ids=body.get("workerIds"))
                return 200, {}
            if method == "DELETE":
                self.delete_count += 1
                if r.provision_delay_s == float("inf") and r.state is QueuedResourceState.DELETING:
                    return 200, {}  # stuck deleting: accept but never finish
                del self.resources[name]
                return 200, {}
            return 405, {"error": f"{method} not allowed"}

    def _create(self, query: dict, body: Optional[dict], now: float):
        self.create_count += 1
        if self.fail_next_create is not None:
            status, msg = self.fail_next_create
            self.fail_next_create = None
            return status, {"error": msg}
        name = (query.get("queued_resource_id") or [None])[0] or (body or {}).get("name")
        if not name:
            return 400, {"error": "missing queued_resource_id"}
        if name in self.resources:
            return 409, {"error": f"queued resource {name} already exists"}
        if not lookup_accelerator(body["accelerator_type"]):
            return 400, {"error": f"unknown accelerator type {body['accelerator_type']}"}
        r = _FakeResource(name, body, now, self.provision_delay_s)
        self.resources[name] = r
        r.advance(now)  # delay 0 -> immediately ACTIVE
        return 200, r.to_json()

    def _list(self, query: dict):
        states = None
        if "states" in query:
            states = {QueuedResourceState(s) for s in query["states"][0].split(",")}
        items = [r.to_json() for r in self.resources.values()
                 if states is None or r.state in states]
        return 200, {"queuedResources": items}


class _Handler(BaseHTTPRequestHandler):
    service: FakeTpuService  # set by server factory

    def log_message(self, *a):  # silence
        pass

    def _dispatch(self, method: str):
        parsed = urlparse(self.path)
        body = None
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            try:
                body = json.loads(self.rfile.read(length))
            except json.JSONDecodeError:
                body = None
        headers: dict = {}
        try:
            result = self.service.handle(method, parsed.path,
                                         parse_qs(parsed.query), body)
            if len(result) == 3:
                status, payload, headers = result
            else:
                status, payload = result
        except (KeyError, TypeError, ValueError) as e:
            status, payload = 400, {"error": f"bad request: {e}"}
        data = json.dumps(payload).encode() if payload is not None else b""
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


class FakeTpuServer:
    """Owns the HTTP listener; use as a context manager or start()/stop()."""

    def __init__(self, provision_delay_s: float = 0.0,
                 workload_auto_finish_s: Optional[float] = None,
                 clock=time.time):
        self.service = FakeTpuService(provision_delay_s, workload_auto_finish_s,
                                      clock=clock)
        handler = type("BoundHandler", (_Handler,), {"service": self.service})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    @property
    def base_url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FakeTpuServer":
        self._thread.start()
        return self

    def stop(self):
        if self._thread.is_alive():  # shutdown() deadlocks on a never-started server
            self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "FakeTpuServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
