"""L1': Cloud TPU client layer.

TPU-native analog of the reference's cloud client
(/root/reference/pkg/virtual_kubelet/runpod_client.go). Where the reference speaks
RunPod REST/GraphQL and selects GPUs by price, this layer speaks the Cloud TPU
QueuedResources API shape and selects accelerator generation + slice topology.
"""

from .types import (
    AcceleratorType,
    QueuedResource,
    QueuedResourceState,
    TpuWorker,
    WorkerRuntimeInfo,
    DetailedStatus,
    ACCELERATOR_CATALOG,
    lookup_accelerator,
    select_accelerator,
)
from .tpu_client import TpuClient, TpuApiError, NotFoundError, QuotaError
from .gcp_auth import (AdcUserTokenProvider, AuthError, MetadataTokenProvider,
                       StaticTokenProvider, default_token_provider,
                       is_google_api_endpoint)
from .transport import (CircuitBreaker, CircuitOpenError, HttpTransport,
                        TransportError, parse_retry_after)
from .faults import FaultPlan, FaultWindow
from .workload_backend import (ApiWorkloadBackend, SshWorkloadBackend,
                               WorkloadBackend, WorkloadBackendError)

__all__ = [
    "ApiWorkloadBackend",
    "SshWorkloadBackend",
    "WorkloadBackend",
    "WorkloadBackendError",
    "AcceleratorType",
    "QueuedResource",
    "QueuedResourceState",
    "TpuWorker",
    "WorkerRuntimeInfo",
    "DetailedStatus",
    "ACCELERATOR_CATALOG",
    "lookup_accelerator",
    "select_accelerator",
    "TpuClient",
    "TpuApiError",
    "NotFoundError",
    "QuotaError",
    "HttpTransport",
    "TransportError",
    "CircuitBreaker",
    "CircuitOpenError",
    "parse_retry_after",
    "FaultPlan",
    "FaultWindow",
    "AuthError",
    "StaticTokenProvider",
    "MetadataTokenProvider",
    "AdcUserTokenProvider",
    "default_token_provider",
    "is_google_api_endpoint",
]
